# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""TPUJob dashboard: REST + HTML view AND write path for TPUJobs.

The reference deployed a TFJob dashboard backend + UI behind Ambassador
at ``/tfjobs/ui/`` (``kubeflow/core/tf-job.libsonnet:271-458``, backend
``/opt/tensorflow_k8s/dashboard/backend`` on :8080) that could CREATE
and DELETE jobs, not just list them. This is its TPUJob equivalent:

  GET    /tpujobs/ui/                    HTML job table + create form
  POST   /tpujobs/ui/create              form-encoded create
  GET    /tpujobs/api/tpujob             all TPUJobs (JSON)
  POST   /tpujobs/api/tpujob             create (full TPUJob CR JSON,
                                         validated against the CRD's
                                         openAPIV3 schema)
  GET    /tpujobs/api/tpujob/<ns>/<name> one TPUJob + its gang pods
                                         (per-replica phase/slice/exit
                                         code/drained + conditions)
  GET    /tpujobs/api/tpujob/<ns>/<name>/logs/<pod>?tail=N
                                         recent log tail, proxied
                                         through the apiserver client
  GET    /tpujobs/ui/job/<ns>/<name>     HTML per-pod drill-down
  DELETE /tpujobs/api/tpujob/<ns>/<name> delete the job + its gang
  GET    /tpujobs/api/traces             profiler runs under --trace_root
                                         (XPlane dirs; SURVEY §5's
                                         "surfaced through the
                                         dashboard" target)
  GET    /tpujobs/api/operator          controller workqueue/reconcile
                                         metrics (read from the
                                         ConfigMap the operator
                                         publishes; ?namespace=)
  GET    /tpujobs/api/fleet             serving-fleet membership,
                                         health/saturation and the
                                         last autoscaler decision
                                         (from the ConfigMap the
                                         autoscaler loop publishes;
                                         ?namespace=)
  GET    /tpujobs/api/slo               fleet telemetry: collector
                                         target status, SLO burn
                                         rates, alert states +
                                         transition history (the
                                         in-process collector; falls
                                         back to the kft-alerts
                                         ConfigMap a sidecar
                                         collector publishes)
  GET    /tpujobs/ui/health             HTML "Fleet health" page: SLO
                                         status, burn rates, firing
                                         alerts, exemplar → /tracez
                                         links
  GET    /tpujobs/api/trace             assembled-trace index (the
                                         ids the collector's
                                         SpanStore holds)
  GET    /tpujobs/api/trace/<trace_id>  one request's fleet-wide
                                         spans + latency attribution
  GET    /tpujobs/ui/waterfall          HTML Waterfall page
                                         (?trace_id=): per-trace span
                                         tree + queue/prefill/decode/
                                         relay/gap attribution bar
  GET    /healthz

against either a real apiserver (kubectl shim) or the in-repo fake
(hermetic citest). Deployed by ``manifests/tpujob.py`` as the
``tpujob-dashboard`` Deployment with the Ambassador route rewrite
``/tpujobs/ui/``.
"""

from __future__ import annotations

import argparse
import html
import json
import logging
from typing import Any, Dict

import tornado.ioloop
import tornado.web

from kubeflow_tpu.manifests.tpujob import KIND
from kubeflow_tpu.obs import metrics as obs_metrics
from kubeflow_tpu.obs.exposition import (
    ChromeTraceHandler,
    MetricsHandler,
    TraceContextHandlerMixin,
    access_log_function,
)
from kubeflow_tpu.operator.reconciler import (
    DEADLINE_CONDITION,
    JOB_LABEL,
    PREEMPTED_CONDITION,
    PREEMPTOR_CONDITION,
    RESIZED_CONDITION,
    RESIZING_CONDITION,
    SHRUNK_CONDITION,
    STALLED_CONDITION,
)

logger = logging.getLogger(__name__)

_D_REQUESTS = obs_metrics.Counter(
    "kft_dashboard_requests_total",
    "Dashboard HTTP requests by handler and status class",
    ("handler", "code"))


#: Non-phase conditions the operator raises for jobs needing operator
#: (human) attention: quarantined poison jobs, gangs that blew their
#: scheduling deadline, and gangs evicted by a higher-priority job.
#: Surfaced as warnings in the job views. The names are the
#: reconciler's own constants — the banner must track what the
#: operator actually writes.
_WARNING_CONDITIONS = (STALLED_CONDITION, DEADLINE_CONDITION,
                       PREEMPTED_CONDITION, SHRUNK_CONDITION)
#: Informational (non-warning) conditions: the preemptor's record of
#: having evicted a victim — the other half of the preemption story —
#: and an elastic resize roll in flight.
_INFO_CONDITIONS = (PREEMPTOR_CONDITION, RESIZING_CONDITION)
#: Record conditions stay True as history (the last completed resize)
#: — no banner, and they must not steal the per-job transition anchor
#: from the phase conditions.
_RECORD_CONDITIONS = (RESIZED_CONDITION,)


def job_warnings(job: Dict[str, Any]) -> list:
    """Active warning conditions, as [{type, reason, since}]."""
    out = []
    for cond in job.get("status", {}).get("conditions", []):
        if (cond.get("type") in _WARNING_CONDITIONS
                and cond.get("status") == "True"):
            out.append({
                "type": cond.get("type"),
                "reason": cond.get("reason") or "",
                "since": cond.get("lastTransitionTime") or "",
            })
    return out


def job_notices(job: Dict[str, Any]) -> list:
    """Active informational conditions (PreemptedVictim), same shape
    as :func:`job_warnings` — rendered as a note, not an alert."""
    out = []
    for cond in job.get("status", {}).get("conditions", []):
        if (cond.get("type") in _INFO_CONDITIONS
                and cond.get("status") == "True"):
            out.append({
                "type": cond.get("type"),
                "reason": cond.get("reason") or "",
                "since": cond.get("lastTransitionTime") or "",
            })
    return out


def job_summary(job: Dict[str, Any]) -> Dict[str, Any]:
    meta = job.get("metadata", {})
    status = job.get("status", {})
    replicas = {
        spec.get("replicaType", "?"): spec.get("replicas", 0)
        for spec in job.get("spec", {}).get("replicaSpecs", [])
    }
    # The active condition's transition is "when did the job last
    # change state" — the reference UI's per-job timeline anchor.
    # Warning/info conditions (also True) must not steal the anchor.
    active = next((c for c in status.get("conditions", [])
                   if c.get("status") == "True"
                   and c.get("type") not in _WARNING_CONDITIONS
                   and c.get("type") not in _INFO_CONDITIONS
                   and c.get("type") not in _RECORD_CONDITIONS), {})
    from kubeflow_tpu.operator.reconciler import (
        elastic_current_replicas,
        job_elastic_bounds,
        job_priority,
    )

    # Elastic view rides the RECONCILER's own coercion helpers:
    # malformed min/max/current degrade to the rigid reading (None),
    # never a 500 — the badge must show what the operator will
    # actually do.
    bounds = job_elastic_bounds(job)
    elastic = None
    if bounds is not None:
        elastic = {
            "current": elastic_current_replicas(job),
            "min": bounds[0],
            "max": bounds[1],
        }
    return {
        "name": meta.get("name", ""),
        "namespace": meta.get("namespace", ""),
        "phase": status.get("phase", "Pending"),
        "restartCount": status.get("restartCount", 0),
        "replicas": replicas,
        "elastic": elastic,
        "numSlices": int(job.get("spec", {}).get("numSlices", 1) or 1),
        # The operator's own coercion — the badge must show what the
        # preemption logic will actually act on.
        "priority": job_priority(job),
        "lastTransitionTime": active.get("lastTransitionTime", ""),
        "reason": status.get("reason", ""),
        "creationTimestamp": meta.get("creationTimestamp", ""),
        "warnings": job_warnings(job),
        "notices": job_notices(job),
    }


def pod_summary(pod: Dict[str, Any]) -> Dict[str, Any]:
    """Per-replica drill-down row (parity: the reference UI backend's
    per-replica views, ``kubeflow/core/tf-job.libsonnet:271-458``)."""
    from kubeflow_tpu.operator.reconciler import (
        REPLICA_INDEX_LABEL,
        REPLICA_TYPE_LABEL,
        SLICE_INDEX_LABEL,
        pod_drained,
    )

    meta = pod.get("metadata", {})
    labels = meta.get("labels", {})
    status = pod.get("status", {})
    exit_code = None
    container_restarts = 0
    for cs in status.get("containerStatuses", []):
        container_restarts += int(cs.get("restartCount", 0))
        term = (cs.get("state") or {}).get("terminated")
        if term and exit_code is None:
            exit_code = term.get("exitCode")
    return {
        "name": meta.get("name", ""),
        "phase": status.get("phase", "Unknown"),
        "replicaType": labels.get(REPLICA_TYPE_LABEL, ""),
        "replicaIndex": labels.get(REPLICA_INDEX_LABEL, ""),
        "slice": labels.get(SLICE_INDEX_LABEL, "0"),
        "exitCode": exit_code,
        "drained": pod_drained(pod),
        "containerRestarts": container_restarts,
    }


class BaseHandler(TraceContextHandlerMixin, tornado.web.RequestHandler):
    # Context adoption + the per-request span come from the shared
    # mixin; health probes opt out (they would evict real handler
    # spans from the ring buffer).
    _obs_span = "dashboard_request"
    _obs_cat = "dashboard"

    @property
    def api(self):
        return self.application.settings["api"]

    def on_finish(self) -> None:
        _D_REQUESTS.labels(type(self).__name__,
                           f"{self.get_status() // 100}xx").inc()
        super().on_finish()

    def write_json(self, payload: Any, status: int = 200) -> None:
        self.set_status(status)
        self.set_header("Content-Type", "application/json")
        self.finish(json.dumps(payload))


class HealthHandler(BaseHandler):
    _obs_span = None  # kubelet probes must not churn the span buffer

    def get(self):
        self.write_json({"status": "ok"})


def _create_error_code(exc: Exception) -> int:
    """409 only for genuine already-exists conflicts; any other
    apiserver failure (outage, RBAC) is a 502 so clients retry
    instead of concluding the job exists."""
    from kubeflow_tpu.operator.fake import Conflict

    if isinstance(exc, Conflict) or "AlreadyExists" in str(exc) \
            or "already exists" in str(exc):
        return 409
    return 502


def validate_tpujob(job: Any) -> list:
    """CRD-schema validation for a submitted CR; returns error list."""
    from kubeflow_tpu.manifests.tpujob import GROUP, VERSION, crd
    from kubeflow_tpu.utils.openapi import crd_openapi_schema, validate

    if not isinstance(job, dict):
        return ["body must be a JSON object (a TPUJob CR)"]
    errors = []
    if job.get("kind") != KIND:
        errors.append(f"kind must be {KIND!r}, got {job.get('kind')!r}")
    want_api = f"{GROUP}/{VERSION}"
    if job.get("apiVersion") != want_api:
        errors.append(f"apiVersion must be {want_api!r}, "
                      f"got {job.get('apiVersion')!r}")
    if not job.get("metadata", {}).get("name"):
        errors.append("metadata.name is required")
    if not job.get("spec", {}).get("replicaSpecs"):
        errors.append("spec.replicaSpecs must be non-empty")
    errors += validate(job, crd_openapi_schema(crd()))
    return errors


class JobListHandler(BaseHandler):
    async def get(self):
        # Apiserver access shells out to kubectl in the real client;
        # run off the IO loop so a slow apiserver can't stall /healthz.
        jobs = await tornado.ioloop.IOLoop.current().run_in_executor(
            None, self.api.list, KIND)
        self.write_json({"items": [job_summary(j) for j in jobs]})

    async def post(self):
        """Create a TPUJob from a full CR (the reference UI's create
        path, tf-job.libsonnet:271-458 — here schema-validated)."""
        try:
            job = json.loads(self.request.body or b"null")
        except json.JSONDecodeError:
            return self.write_json({"error": "body is not valid JSON"}, 400)
        errors = validate_tpujob(job)
        if errors:
            return self.write_json({"error": "invalid TPUJob",
                                    "details": errors}, 400)
        job.setdefault("metadata", {}).setdefault("namespace", "default")
        loop = tornado.ioloop.IOLoop.current()
        try:
            created = await loop.run_in_executor(None, self.api.create, job)
        except Exception as e:  # noqa: BLE001 — apiserver-side failure
            return self.write_json({"error": str(e)}, _create_error_code(e))
        self.write_json({"created": job_summary(created)}, 201)


#: Fallback cap when the apiserver client can't filter server-side: a
#: busy shared namespace holds thousands of Events, and a detail-page
#: click must not shuttle (or sort) them all.
_EVENT_FALLBACK_CAP = 500


def _job_events(api, namespace: str, name: str,
                job: Dict[str, Any]) -> list:
    """The operator's lifecycle Events for THIS job incarnation
    (kubectl-describe semantics: filtered by involvedObject name +
    uid), newest last. The name filter runs SERVER-side via
    fieldSelector (involvedObject.name=<job>) so each detail-page
    click costs one small list, not the namespace's whole event
    history; clients without field_selector support fall back to a
    client-side filter over a capped list. Best-effort — a client
    without event access yields an empty list, never a failed detail
    view."""
    uid = job.get("metadata", {}).get("uid", "")
    try:
        try:
            events = api.list("Event", namespace,
                              field_selector={"involvedObject.name": name})
        except TypeError:
            # Older/duck-typed clients without the field_selector
            # parameter: list and filter here, bounded by the cap
            # (keep the NEWEST slice — kubectl-describe shows the
            # recent lifecycle, not the genesis).
            events = api.list("Event", namespace)
            if len(events) > _EVENT_FALLBACK_CAP:
                # Coalesce across timestamp fields: EventsV1 recorders
                # store eventTime and an explicit null lastTimestamp —
                # sorting on lastTimestamp alone would trim exactly
                # those (possibly newest) events first.
                events = sorted(
                    events,
                    key=lambda e: (e.get("lastTimestamp")
                                   or e.get("eventTime") or ""),
                )[-_EVENT_FALLBACK_CAP:]
    except Exception:  # noqa: BLE001
        return []
    # `or`-coalesce, not get() defaults: other writers (EventsV1
    # recorders, `kubectl create event`) legally store explicit nulls
    # in these fields, and a null must not 500 the detail view.
    mine = [
        {
            "reason": e.get("reason") or "",
            "type": e.get("type") or "Normal",
            "message": e.get("message") or "",
            "count": e.get("count") or 1,
            "lastTimestamp": e.get("lastTimestamp") or "",
        }
        for e in events
        if e.get("involvedObject", {}).get("name") == name
        and (e.get("involvedObject", {}).get("uid") or "") in ("", uid)
    ]
    mine.sort(key=lambda e: e["lastTimestamp"])
    return mine


class JobDetailHandler(BaseHandler):
    async def get(self, namespace: str, name: str):
        from kubeflow_tpu.operator.fake import NotFound

        loop = tornado.ioloop.IOLoop.current()
        try:
            job = await loop.run_in_executor(
                None, self.api.get, KIND, namespace, name)
        except NotFound:
            return self.write_json(
                {"error": f"{KIND} {namespace}/{name} not found"}, 404)
        import asyncio

        # Pods and events are independent apiserver calls (each a
        # kubectl subprocess on the real client): fetch concurrently.
        raw_pods, events = await asyncio.gather(
            loop.run_in_executor(
                None, lambda: self.api.list(
                    "Pod", namespace, label_selector={JOB_LABEL: name})),
            loop.run_in_executor(
                None, _job_events, self.api, namespace, name, job))
        self.write_json({"job": job, "summary": job_summary(job),
                         "conditions": job.get("status", {}).get(
                             "conditions", []),
                         "warnings": job_warnings(job),
                         "notices": job_notices(job),
                         "pods": [pod_summary(p) for p in raw_pods],
                         "events": events})

    async def delete(self, namespace: str, name: str):
        """Delete the job AND its gang pods (the operator only
        reconciles live jobs; a deleted CR's pods must not linger)."""
        from kubeflow_tpu.operator.fake import NotFound

        loop = tornado.ioloop.IOLoop.current()
        try:
            await loop.run_in_executor(
                None, self.api.delete, KIND, namespace, name)
        except NotFound:
            return self.write_json(
                {"error": f"{KIND} {namespace}/{name} not found"}, 404)
        pods = await loop.run_in_executor(
            None, lambda: self.api.list(
                "Pod", namespace, label_selector={JOB_LABEL: name}))
        for pod in pods:
            try:
                await loop.run_in_executor(
                    None, self.api.delete, "Pod", namespace,
                    pod["metadata"]["name"])
            except NotFound:
                pass
        try:
            await loop.run_in_executor(
                None, self.api.delete, "Service", namespace, name)
        except NotFound:
            pass
        self.write_json({"deleted": f"{namespace}/{name}",
                         "pods_deleted": len(pods)})


class PodLogsHandler(BaseHandler):
    """Recent log tail of one gang pod, proxied through the apiserver
    client (kubectl logs / GET pods/<name>/log) — the last piece of
    the reference UI backend's per-replica view."""

    async def get(self, namespace: str, name: str, pod: str):
        from kubeflow_tpu.operator.fake import NotFound

        try:
            tail = int(self.get_query_argument("tail", "100"))
        except ValueError:
            return self.write_json({"error": "tail must be an int"}, 400)
        tail = max(1, min(tail, 10_000))
        loop = tornado.ioloop.IOLoop.current()
        # Only pods of THIS job are served (the dashboard's RBAC is
        # pods/log cluster-wide; the route contract is narrower). One
        # GET, not a gang-sized LIST per click.
        try:
            obj = await loop.run_in_executor(
                None, self.api.get, "Pod", namespace, pod)
        except NotFound:
            obj = None
        if (obj is None or obj.get("metadata", {}).get("labels", {})
                .get(JOB_LABEL) != name):
            return self.write_json(
                {"error": f"pod {pod} is not part of "
                          f"{namespace}/{name}"}, 404)
        try:
            text = await loop.run_in_executor(
                None, lambda: self.api.pod_logs(namespace, pod,
                                                tail=tail))
        except NotFound:
            return self.write_json({"error": f"pod {pod} not found"}, 404)
        except Exception as e:  # noqa: BLE001 — kubelet/apiserver side
            return self.write_json({"error": str(e)}, 502)
        self.set_header("Content-Type", "text/plain; charset=utf-8")
        self.finish(text)


class OperatorMetricsHandler(BaseHandler):
    """The controller's workqueue/reconcile metrics, read from the
    ConfigMap it publishes (operator/controller.py publish_metrics) —
    the dashboard and the load benchmark read the SAME numbers:
    queue depth, per-key retry counts and backoff state, quarantined
    jobs, reconcile totals, watch health, informer-cache counters
    (per-kind objects/events/relists/Gone) and preemption counters
    (eligible/granted/rateLimited/noVictim)."""

    async def get(self):
        from kubeflow_tpu.operator.controller import (
            METRICS_CONFIGMAP,
            METRICS_KEY,
        )
        from kubeflow_tpu.operator.fake import NotFound

        namespace = self.get_query_argument("namespace", "default")
        loop = tornado.ioloop.IOLoop.current()
        try:
            cm = await loop.run_in_executor(
                None, self.api.get, "ConfigMap", namespace,
                METRICS_CONFIGMAP)
        except NotFound:
            return self.write_json(
                {"available": False,
                 "error": f"ConfigMap {namespace}/{METRICS_CONFIGMAP} "
                          f"not found (operator not publishing?)"}, 404)
        except Exception as e:  # noqa: BLE001 — apiserver-side
            return self.write_json({"available": False,
                                    "error": str(e)}, 502)
        try:
            metrics = json.loads(
                cm.get("data", {}).get(METRICS_KEY, "{}"))
        except json.JSONDecodeError:
            return self.write_json(
                {"available": False,
                 "error": "metrics ConfigMap holds invalid JSON"}, 502)
        self.write_json({"available": True, "namespace": namespace,
                         "metrics": metrics})


class FleetHandler(BaseHandler):
    """Serving-fleet state: replica membership, health/saturation and
    the last autoscaler decision, read from the ConfigMap the
    autoscaler loop publishes (scaling/autoscaler.py AutoscalerLoop
    .publish) — the same operator-metrics pattern as
    /tpujobs/api/operator: the dashboard and the fleet bench read the
    SAME numbers the controller acted on."""

    async def get(self):
        from kubeflow_tpu.operator.fake import NotFound
        from kubeflow_tpu.scaling.autoscaler import (
            FLEET_CONFIGMAP,
            FLEET_KEY,
        )

        namespace = self.get_query_argument("namespace", "default")
        loop = tornado.ioloop.IOLoop.current()
        try:
            cm = await loop.run_in_executor(
                None, self.api.get, "ConfigMap", namespace,
                FLEET_CONFIGMAP)
        except NotFound:
            return self.write_json(
                {"available": False,
                 "error": f"ConfigMap {namespace}/{FLEET_CONFIGMAP} "
                          f"not found (autoscaler not publishing?)"},
                404)
        except Exception as e:  # noqa: BLE001 — apiserver-side
            return self.write_json({"available": False,
                                    "error": str(e)}, 502)
        try:
            fleet = json.loads(cm.get("data", {}).get(FLEET_KEY, "{}"))
        except json.JSONDecodeError:
            return self.write_json(
                {"available": False,
                 "error": "fleet ConfigMap holds invalid JSON"}, 502)
        self.write_json({"available": True, "namespace": namespace,
                         "fleet": fleet})


def _fetch_fleet(api, namespace: str = "default"):
    """Best-effort fleet snapshot for the HTML view (None when the
    autoscaler is not publishing)."""
    from kubeflow_tpu.scaling.autoscaler import FLEET_CONFIGMAP, FLEET_KEY

    try:
        cm = api.get("ConfigMap", namespace, FLEET_CONFIGMAP)
        return json.loads(cm.get("data", {}).get(FLEET_KEY, "{}"))
    except Exception:  # noqa: BLE001 — section simply absent
        return None


def _telemetry_payload(settings, api, namespace: str) -> Dict[str, Any]:
    """The /tpujobs/api/slo document: from the IN-PROCESS collector +
    alert manager when the dashboard runs them, else from the
    ``kft-alerts`` ConfigMap a sidecar collector publishes, else
    unavailable (with the wiring hint)."""
    collector = settings.get("collector")
    alerts = settings.get("alerts")
    if collector is not None or alerts is not None:
        payload: Dict[str, Any] = {"available": True,
                                   "source": "in-process"}
        if collector is not None:
            payload["collector"] = collector.state()
            payload["exemplars"] = collector.store.exemplars()[:32]
            payload["tenants"] = tenant_rows_from_store(
                collector.store)
        if alerts is not None:
            payload.update(alerts.state())
        return payload
    from kubeflow_tpu.obs.slo import ALERTS_CONFIGMAP, ALERTS_KEY

    try:
        cm = api.get("ConfigMap", namespace, ALERTS_CONFIGMAP)
        doc = json.loads(cm.get("data", {}).get(ALERTS_KEY, "{}"))
        return {"available": True, "source": "configmap", **doc}
    except Exception:  # noqa: BLE001 — collector simply not running
        return {"available": False,
                "error": "no in-process collector and no "
                         f"{ALERTS_CONFIGMAP} ConfigMap (start the "
                         "dashboard with --collect_endpoints/"
                         "--collect_static, or run the collector "
                         "sidecar)"}


def tenant_rows_from_store(store, now=None,
                           window_s: float = 300.0):
    """Per-tenant rate rows from the collector's store (ISSUE 14):
    offered load, quota/overload sheds, expiries and delivered
    decode tokens, summed across replicas (the ``kft_tenant_*``
    families are cardinality-capped at the serving layer, so this
    is bounded at top-K + 'other' rows per process). Malformed or
    absent data degrades to an empty list — never a 500."""
    import time as _time

    now = _time.monotonic() if now is None else now
    rows: Dict[str, Dict[str, Any]] = {}

    def add(metric: str, field: str, reason=None) -> None:
        for labels_key, rate in store.rate(metric, window_s,
                                           now).items():
            labels = dict(labels_key)
            tenant = labels.get("tenant")
            if tenant is None:
                continue
            if reason is not None and labels.get("reason") != reason:
                continue
            row = rows.setdefault(tenant, {"tenant": tenant})
            row[field] = round(row.get(field, 0.0) + rate, 4)

    try:
        add("kft_tenant_requests_total", "requests_per_s")
        add("kft_tenant_shed_total", "quota_shed_per_s", "quota")
        add("kft_tenant_shed_total", "overload_shed_per_s",
            "overload")
        add("kft_tenant_expired_total", "expired_per_s")
        add("kft_tenant_decode_tokens_total", "decode_tokens_per_s")
    except Exception:  # noqa: BLE001 — a malformed store snapshot
        # degrades to "no rows", same contract as the fleet page.
        logger.warning("tenant rows computation failed",
                       exc_info=True)
        return []
    return sorted(rows.values(),
                  key=lambda r: -r.get("requests_per_s", 0.0))


class TenantsHandler(BaseHandler):
    """Per-tenant serving telemetry (ISSUE 14): shed/quota/usage
    rates from the in-process collector store. Requires the dashboard
    to run its collector (--collect_endpoints/--collect_static);
    without one the endpoint answers 404 with the wiring hint —
    malformed data degrades to empty rows, never a 500."""

    async def get(self):
        collector = self.application.settings.get("collector")
        if collector is None:
            return self.write_json(
                {"available": False,
                 "error": "no in-process collector (start the "
                          "dashboard with --collect_endpoints/"
                          "--collect_static to aggregate the "
                          "kft_tenant_* families)"}, 404)
        rows = await tornado.ioloop.IOLoop.current().run_in_executor(
            None, tenant_rows_from_store, collector.store)
        self.write_json({"available": True, "tenants": rows})


class TraceIndexHandler(BaseHandler):
    """Assembled-trace index (ISSUE 15): the trace ids the in-process
    collector's SpanStore holds, newest first. 404 with the wiring
    hint when the dashboard runs no collector — same contract as
    /tpujobs/api/tenants."""

    def _span_store(self):
        collector = self.application.settings.get("collector")
        return getattr(collector, "span_store", None)

    async def get(self):
        store = self._span_store()
        if store is None:
            return self.write_json(
                {"available": False,
                 "error": "no in-process span collection (start the "
                          "dashboard with --collect_endpoints/"
                          "--collect_static; spans are scraped from "
                          "each target's /tracez)"}, 404)
        self.write_json({"available": True,
                         "traces": store.trace_ids(),
                         "store": store.state()})


class TraceDetailHandler(TraceIndexHandler):
    """One assembled trace: spans + tree + attribution report — the
    JSON the Waterfall page and ``kft-trace`` render."""

    async def get(self, trace_id: str):
        from kubeflow_tpu.obs import trace as obs_trace

        store = self._span_store()
        if store is None:
            return self.write_json(
                {"available": False,
                 "error": "no in-process span collection"}, 404)
        spans = store.trace(trace_id)
        if not spans:
            return self.write_json(
                {"available": False,
                 "error": f"no spans for trace {trace_id!r} (evicted, "
                          f"not yet scraped, or never traced)"}, 404)
        assembled = await tornado.ioloop.IOLoop.current() \
            .run_in_executor(None, obs_trace.assemble, spans)
        self.write_json({
            "available": True,
            "trace_id": trace_id,
            "spans": spans,
            "attribution": obs_trace.attribution(spans),
            "waterfall": obs_trace.waterfall_lines(assembled),
        })


class SloHandler(BaseHandler):
    """Fleet telemetry JSON: collector targets, SLO burn rates, alert
    states and the transition history (docs/observability.md "Fleet
    telemetry & SLOs")."""

    async def get(self):
        namespace = self.get_query_argument("namespace", "default")
        settings = self.application.settings
        payload = await tornado.ioloop.IOLoop.current().run_in_executor(
            None, _telemetry_payload, settings, self.api, namespace)
        self.write_json(payload,
                        200 if payload.get("available") else 404)


class TraceListHandler(BaseHandler):
    """Profiler traces under the shared trace root (written by
    trainer ``--profile_dir`` / ``LoopConfig.profile_dir``; recipe for
    opening them: docs/profiling.md)."""

    async def get(self):
        from kubeflow_tpu.utils.traces import list_traces

        root = self.application.settings["trace_root"]
        traces = await tornado.ioloop.IOLoop.current().run_in_executor(
            None, list_traces, root)
        self.write_json({"trace_root": root, "items": traces})


_PHASE_COLORS = {
    "Running": "#1a7f37", "Succeeded": "#0969da", "Pending": "#9a6700",
    "Restarting": "#bc4c00", "Failed": "#cf222e",
}

_PAGE = """<!doctype html>
<html><head><title>TPUJobs</title>
<style>
 body {{ font-family: system-ui, sans-serif; margin: 2rem; }}
 table {{ border-collapse: collapse; min-width: 48rem; }}
 th, td {{ text-align: left; padding: .4rem .9rem;
          border-bottom: 1px solid #d0d7de; }}
 th {{ background: #f6f8fa; }}
 .phase {{ font-weight: 600; }}
</style></head>
<body>
<h1>TPUJobs</h1>
<table>
<tr><th>Namespace</th><th>Name</th><th>Phase</th><th>Restarts</th>
<th>Replicas</th></tr>
{rows}
</table>
<p>{count} job(s). JSON: <a href="/tpujobs/api/tpujob">/tpujobs/api/tpujob</a></p>
<h2>Profiler traces</h2>
<table>
<tr><th>Job</th><th>Run</th><th>Files</th><th>Trace dir</th></tr>
{trace_rows}
</table>
<p>{trace_count} trace run(s) under {trace_root}.
JSON: <a href="/tpujobs/api/traces">/tpujobs/api/traces</a> &middot;
open with <code>tensorboard --logdir &lt;trace dir&gt;</code>
(docs/profiling.md)</p>
<h2>Serving fleet</h2>
<p><a href="/tpujobs/ui/health">Fleet health</a> — SLO status, burn
rates, firing alerts, exemplar trace links
(<a href="/tpujobs/api/slo">JSON</a>).</p>
{fleet_section}
<h2>Request spans</h2>
<p>Host-side request spans (Chrome trace-event JSON — open in
<a href="https://ui.perfetto.dev">Perfetto</a>):
<a href="/tpujobs/api/spans">/tpujobs/api/spans</a> for this
dashboard's own handlers; serving pods expose theirs at
<code>/tracez</code> (proxy and model server). Prometheus metrics:
<a href="/metrics">/metrics</a> here, plus <code>/metrics</code> on
the proxy, model server, and the operator's metrics port
(docs/observability.md).</p>
<h2>Create TPUJob</h2>
<form method="post" action="/tpujobs/ui/create">
 <label>Name <input name="name" required pattern="[a-z0-9-]+"></label>
 <label>Namespace <input name="namespace" value="default"></label>
 <label>Workers <input name="workers" type="number" value="2" min="1"></label>
 <label>Image <input name="image"
   value="ghcr.io/kubeflow-tpu/trainer:v0.1.0" size="40"></label>
 <label>Accelerator <input name="tpu_accelerator"
   value="tpu-v5-lite-podslice"></label>
 <label>Topology <input name="tpu_topology" value="2x4"></label>
 <label>Command <input name="command" size="40"
   placeholder="python -m kubeflow_tpu.training.launcher"></label>
 <button type="submit">Create</button>
</form>
</body></html>
"""


_DETAIL_PAGE = """<!doctype html>
<html><head><title>TPUJob {name}</title>
<style>
 body {{ font-family: system-ui, sans-serif; margin: 2rem; }}
 table {{ border-collapse: collapse; min-width: 48rem;
          margin-bottom: 1.5rem; }}
 th, td {{ text-align: left; padding: .4rem .9rem;
          border-bottom: 1px solid #d0d7de; }}
 th {{ background: #f6f8fa; }}
 .phase {{ font-weight: 600; }}
</style></head>
<body>
<p><a href="/tpujobs/ui/">&larr; all jobs</a></p>
<h1>{name} <small style="color:{phase_color}">{phase}</small></h1>
<p>{namespace} &middot; restarts {restarts} &middot; slices {slices}
{elastic_line}&middot; last transition {transition} {reason}</p>
{warning_banner}
<h2>Replicas</h2>
<table>
<tr><th>Pod</th><th>Slice</th><th>Type</th><th>Index</th><th>Phase</th>
<th>Exit</th><th>Logs</th></tr>
{pod_rows}
</table>
<h2>Conditions</h2>
<table>
<tr><th>Type</th><th>Status</th><th>Last transition</th><th>Reason</th></tr>
{cond_rows}
</table>
<h2>Events</h2>
<table>
<tr><th>Type</th><th>Reason</th><th>Count</th><th>Last seen</th>
<th>Message</th></tr>
{event_rows}
</table>
<p>JSON: <a href="{api}">{api}</a></p>
</body></html>
"""


class UIJobDetailHandler(BaseHandler):
    """HTML per-pod drill-down (the reference UI's job page)."""

    async def get(self, namespace: str, name: str):
        from kubeflow_tpu.operator.fake import NotFound

        loop = tornado.ioloop.IOLoop.current()
        try:
            job = await loop.run_in_executor(
                None, self.api.get, KIND, namespace, name)
        except NotFound:
            self.set_status(404)
            return self.finish(f"TPUJob {namespace}/{name} not found")
        import asyncio

        summary = job_summary(job)
        # Pods and events concurrently (independent apiserver calls).
        raw_pods, events = await asyncio.gather(
            loop.run_in_executor(
                None, lambda: self.api.list(
                    "Pod", namespace, label_selector={JOB_LABEL: name})),
            loop.run_in_executor(
                None, _job_events, self.api, namespace, name, job))
        pods = [pod_summary(p) for p in raw_pods]
        # Operator-attention banner: quarantined reconcile (the
        # controller is failing to act on this job), a blown
        # scheduling deadline (gang torn down, slices released), or a
        # preemption eviction. PreemptedVictim (this job evicted
        # someone) rides below as an informational note, not an alert.
        warning_rows = [
            f"<p style=\"background:#fff1f0;border:1px solid #cf222e;"
            f"padding:.5rem .9rem\"><strong>"
            f"{html.escape(w['type'])}</strong> since "
            f"{html.escape(w['since'][:19] or '-')}: "
            f"{html.escape(w['reason'])}</p>"
            for w in job_warnings(job)]
        warning_rows += [
            f"<p style=\"background:#ddf4ff;border:1px solid #0969da;"
            f"padding:.5rem .9rem\"><strong>"
            f"{html.escape(n['type'])}</strong> since "
            f"{html.escape(n['since'][:19] or '-')}: "
            f"{html.escape(n['reason'])}</p>"
            for n in job_notices(job)]

        def _num(s: str) -> int:
            return int(s) if s.isdigit() else 0

        pods.sort(key=lambda p: (_num(p["slice"]), p["replicaType"],
                                 _num(p["replicaIndex"])))
        pod_rows = []
        for p in pods:
            color = _PHASE_COLORS.get(p["phase"], "#57606a")
            exit_txt = "-" if p["exitCode"] is None else str(p["exitCode"])
            if p["drained"]:
                exit_txt += " (drained)"
            logs = (f"/tpujobs/api/tpujob/{namespace}/{name}/logs/"
                    f"{p['name']}?tail=100")
            pod_rows.append(
                "<tr>"
                f"<td><code>{html.escape(p['name'])}</code></td>"
                f"<td>{html.escape(p['slice'])}</td>"
                f"<td>{html.escape(p['replicaType'])}</td>"
                f"<td>{html.escape(p['replicaIndex'])}</td>"
                f"<td class=\"phase\" style=\"color:{color}\">"
                f"{html.escape(p['phase'])}</td>"
                f"<td>{html.escape(exit_txt)}</td>"
                f"<td><a href=\"{html.escape(logs)}\">tail</a></td>"
                "</tr>")
        cond_rows = []
        for c in job.get("status", {}).get("conditions", []):
            cond_rows.append(
                "<tr>"
                f"<td>{html.escape(c.get('type', ''))}</td>"
                f"<td>{html.escape(c.get('status', ''))}</td>"
                f"<td>{html.escape(c.get('lastTransitionTime', ''))}</td>"
                f"<td>{html.escape(c.get('reason', ''))}</td>"
                "</tr>")
        event_rows = []
        for e in events:
            color = "#cf222e" if e["type"] == "Warning" else "#57606a"
            event_rows.append(
                "<tr>"
                f"<td style=\"color:{color}\">"
                f"{html.escape(e['type'])}</td>"
                f"<td>{html.escape(e['reason'])}</td>"
                f"<td>{int(e['count'])}</td>"
                f"<td>{html.escape(e['lastTimestamp'][:19])}</td>"
                f"<td>{html.escape(e['message'])}</td>"
                "</tr>")
        # Elastic badge: current/min/max workers; rendered only for
        # elastic jobs (job_summary already degraded any malformed
        # bounds to the rigid reading).
        elastic_line = ""
        if summary.get("elastic"):
            e = summary["elastic"]
            elastic_line = (
                f"&middot; workers "
                f"{html.escape(str(e.get('current')))}"
                f" (min {html.escape(str(e.get('min')))}"
                f" / max {html.escape(str(e.get('max')))}) ")
        self.set_header("Content-Type", "text/html; charset=utf-8")
        self.finish(_DETAIL_PAGE.format(
            name=html.escape(name),
            namespace=html.escape(namespace),
            phase=html.escape(summary["phase"]),
            phase_color=_PHASE_COLORS.get(summary["phase"], "#57606a"),
            restarts=int(summary["restartCount"]),
            slices=int(summary["numSlices"]),
            elastic_line=elastic_line,
            transition=html.escape(summary["lastTransitionTime"] or "-"),
            reason=html.escape(
                f"({summary['reason']})" if summary["reason"] else ""),
            warning_banner="\n".join(warning_rows),
            pod_rows="\n".join(pod_rows) or
            "<tr><td colspan=7>no pods</td></tr>",
            cond_rows="\n".join(cond_rows) or
            "<tr><td colspan=4>none</td></tr>",
            event_rows="\n".join(event_rows) or
            "<tr><td colspan=5>none</td></tr>",
            api=html.escape(f"/tpujobs/api/tpujob/{namespace}/{name}"),
        ))


_HEALTH_COLORS = {"healthy": "#1a7f37", "unknown": "#9a6700",
                  "unhealthy": "#cf222e", "draining": "#bc4c00"}


def _fleet_section_html(fleet) -> str:
    """The "Serving fleet" block: replica membership/health/
    saturation rows + the last autoscaler decision, or a pointer at
    the publishing contract when the autoscaler isn't running. A
    malformed ConfigMap (version skew, a hand edit — humans CAN
    patch it) degrades to a note, never a 500 for the whole page."""
    try:
        return _fleet_section_html_unsafe(fleet)
    except Exception:  # noqa: BLE001 — render is best-effort
        logger.warning("fleet ConfigMap malformed; omitting section",
                       exc_info=True)
        return ("<p>Fleet ConfigMap unreadable (malformed "
                "<code>serving-fleet-metrics</code>?). JSON: "
                "<a href=\"/tpujobs/api/fleet\">/tpujobs/api/fleet"
                "</a></p>")


def _fleet_section_html_unsafe(fleet) -> str:
    if not fleet or not fleet.get("replicas"):
        return ("<p>No fleet published (the serving autoscaler "
                "writes the <code>serving-fleet-metrics</code> "
                "ConfigMap). JSON: "
                "<a href=\"/tpujobs/api/fleet\">/tpujobs/api/fleet"
                "</a></p>")
    rows = []
    for r in fleet.get("replicas", []):
        reachable = r.get("reachable")
        health = "healthy" if reachable else "unhealthy"
        color = _HEALTH_COLORS.get(health, "#57606a")
        models = ", ".join(r.get("resident_models", [])) or "-"
        wait = (f"{r.get('queue_wait_ms', 0.0):.0f} ms"
                if reachable else "-")
        shed = (f"{r.get('shed_rate', 0.0):.2f}/s"
                if reachable else "-")
        # Role + shard topology (ISSUE 10): values come from healthz
        # payloads and the endpoints file — malformed ones degrade to
        # the role-less/single-shard rendering, never a 500. The role
        # vocabulary (and its degrade rule) is single-sourced from
        # the endpoint registry so the dashboard can never disagree
        # with the router about which roles exist.
        from kubeflow_tpu.scaling.endpoints import normalize_role

        role = normalize_role(r.get("role"))
        try:
            shards = max(1, int(r.get("shards", 1)))
        except (TypeError, ValueError):
            shards = 1
        occupancy = r.get("slot_occupancy")
        try:
            role_cell = (f"{role} ({float(occupancy) * 100:.0f}% "
                         f"slots)" if occupancy is not None
                         and role == "decode" else role)
        except (TypeError, ValueError):
            role_cell = role
        # Page pressure + prefix-cache hit rate (ISSUE 11): the KV
        # page pool can be the binding constraint while slots look
        # free. Absent/malformed values degrade to "-", never 500.
        pages_cell = "-"
        try:
            page_occ = r.get("page_occupancy")
            if page_occ is not None:
                pages_cell = f"{float(page_occ) * 100:.0f}%"
        except (TypeError, ValueError):
            pages_cell = "-"
        try:
            hit_rate = r.get("prefix_hit_rate")
            if pages_cell != "-" and hit_rate is not None:
                # Per-value degrade: a malformed hit rate drops only
                # its own suffix, never the valid occupancy number.
                pages_cell += (f" ({float(hit_rate) * 100:.0f}% "
                               f"prefix hits)")
        except (TypeError, ValueError):
            pass
        # Tiered KV memory (ISSUE 20): the per-tier breakdown rides
        # the same cell — HBM page occupancy above, host-tier pool
        # fill and fleet-fetch hits here. Same per-value degrade
        # rule: each malformed value drops only its own fragment.
        try:
            host_occ = r.get("host_kv_occupancy")
            if host_occ is not None:
                frag = f"host {float(host_occ) * 100:.0f}%"
                pages_cell = (frag if pages_cell == "-"
                              else f"{pages_cell}, {frag}")
        except (TypeError, ValueError):
            pass
        try:
            fetches = r.get("kv_fetch_hits")
            if fetches is not None and float(fetches) > 0:
                frag = f"{float(fetches):.0f} fleet fetches"
                pages_cell = (frag if pages_cell == "-"
                              else f"{pages_cell}, {frag}")
        except (TypeError, ValueError):
            pass
        rows.append(
            "<tr>"
            f"<td><code>{html.escape(str(r.get('address', '')))}"
            f"</code></td>"
            f"<td class=\"phase\" style=\"color:{color}\">"
            f"{'reachable' if reachable else 'unreachable'}</td>"
            f"<td>{html.escape(role_cell)}</td>"
            f"<td>{shards if shards > 1 else '-'}</td>"
            f"<td>{wait}</td><td>{shed}</td>"
            f"<td>{html.escape(pages_cell)}</td>"
            f"<td>{html.escape(models)}</td>"
            "</tr>")

    def render_decision(d, label=""):
        prefix = (f"Last autoscaler decision ({html.escape(label)})"
                  if label else "Last autoscaler decision")
        signal = str(d.get("signal", "queue_wait"))
        # The decision's published inputs (docs/capacity.md): what the
        # forecaster believed and which clamp bit, so a surprising
        # scale event is explainable from this page alone.
        inputs = d.get("inputs") or {}
        extra = ""
        forecast = inputs.get("forecast")
        if isinstance(forecast, dict):
            extra += (
                f" Forecast: "
                f"{float(forecast.get('rate_rps', 0.0)):.1f} rps "
                f"at +{float(forecast.get('horizon_s', 0.0)):.0f}s "
                f"→ {int(forecast.get('replicas', 0))} replicas.")
        if inputs.get("clamp"):
            extra += f" Clamp: {html.escape(str(inputs['clamp']))}."
        return (
            f"<p>{prefix}: <strong>"
            f"{html.escape(str(d.get('action', '-')))}</strong> "
            f"({html.escape(str(d.get('reason', '')))}) — "
            f"{int(d.get('current', 0))} → {int(d.get('desired', 0))} "
            f"replicas, signal {html.escape(signal)}, mean queue wait "
            f"{float(d.get('mean_queue_wait_ms', 0.0)):.0f} ms vs "
            f"target "
            f"{float(d.get('target_queue_wait_ms', 0.0)):.0f} ms, "
            f"{float(d.get('age_s', 0.0)):.0f}s ago.{extra}</p>")

    decisions = fleet.get("decisions")
    if isinstance(decisions, dict) and decisions:
        # Role-split fleets: one decision per pool.
        decision = "".join(
            render_decision(d, label=role)
            for role, d in sorted(decisions.items()))
    else:
        decision = render_decision(fleet.get("decision", {}) or {})
    return (
        "<table>\n<tr><th>Replica</th><th>Health</th><th>Role</th>"
        "<th>Shards</th>"
        "<th>Queue wait</th><th>Shed</th><th>Pages</th>"
        "<th>Models</th></tr>\n"
        + "\n".join(rows) + "\n</table>\n" + decision
        + "<p>JSON: <a href=\"/tpujobs/api/fleet\">"
          "/tpujobs/api/fleet</a></p>")


_ALERT_COLORS = {"firing": "#cf222e", "pending": "#9a6700",
                 "inactive": "#1a7f37", "resolved": "#1a7f37"}

_HEALTH_PAGE = """<!doctype html>
<html><head><title>Fleet health</title>
<style>
 body {{ font-family: system-ui, sans-serif; margin: 2rem; }}
 table {{ border-collapse: collapse; min-width: 48rem;
          margin-bottom: 1.5rem; }}
 th, td {{ text-align: left; padding: .4rem .9rem;
          border-bottom: 1px solid #d0d7de; }}
 th {{ background: #f6f8fa; }}
 .state {{ font-weight: 600; }}
</style></head>
<body>
<p><a href="/tpujobs/ui/">&larr; all jobs</a></p>
<h1>Fleet health</h1>
{alert_banner}
<h2>SLOs</h2>
<table>
<tr><th>SLO</th><th>Objective</th><th>State</th>
<th>Window</th><th>Burn (long / short)</th><th>Threshold</th>
<th>Fired</th></tr>
{slo_rows}
</table>
<p>Burn rate = error rate &divide; error budget; an alert needs BOTH
windows over the threshold (Google-SRE multi-window multi-burn-rate;
docs/observability.md).</p>
<h2>Collector targets</h2>
<table>
<tr><th>Target</th><th>Job</th><th>Status</th><th>Last scrape</th>
<th>Duration</th><th>Samples</th></tr>
{target_rows}
</table>
<p>{store_line}</p>
<h2>Tenants</h2>
<table>
<tr><th>Tenant</th><th>Requests/s</th><th>Quota shed/s</th>
<th>Overload shed/s</th><th>Expired/s</th><th>Tokens/s</th></tr>
{tenant_rows}
</table>
<p>Per-tenant rates over the last 5 minutes (cardinality-capped at
the serving layer: top-K tenants + an <code>other</code> overflow
bucket — docs/tenancy.md). JSON:
<a href="/tpujobs/api/tenants">/tpujobs/api/tenants</a></p>
<h2>Exemplars</h2>
<table>
<tr><th>Histogram</th><th>le</th><th>Instance</th><th>Value</th>
<th>Trace</th><th>Waterfall</th></tr>
{exemplar_rows}
</table>
<p>Exemplar workflow: a latency bucket grew &rarr; its exemplar
carries the trace id of one request that landed there &rarr;
<code>/tracez?trace_id=&lt;id&gt;</code> on the instance returns that
process's retained (tail-sampled) spans, and the
<a href="/tpujobs/ui/waterfall">Waterfall</a> page shows the
FLEET-assembled tree + latency attribution (queue / prefill / decode
/ relay / gap). JSON:
<a href="/tpujobs/api/slo">/tpujobs/api/slo</a> &middot;
<a href="/tpujobs/api/trace">/tpujobs/api/trace</a></p>
</body></html>
"""


def _health_page_html(payload: Dict[str, Any]) -> str:
    """Render the Fleet health page from the /tpujobs/api/slo payload
    (best-effort: a malformed payload degrades per section, never a
    500 for the page)."""
    firing = [w for s in payload.get("slos", ())
              for w in s.get("windows", ())
              if w.get("state") == "firing"]
    if firing:
        items = "; ".join(
            f"{html.escape(str(s.get('slo', '?')))}"
            for s in payload.get("slos", ())
            if any(w.get("state") == "firing"
                   for w in s.get("windows", ())))
        alert_banner = (
            f"<p style=\"background:#fff1f0;border:1px solid #cf222e;"
            f"padding:.5rem .9rem\"><strong>{len(firing)} alert(s) "
            f"FIRING</strong>: {items}</p>")
    else:
        alert_banner = ("<p style=\"background:#dafbe1;border:1px "
                        "solid #1a7f37;padding:.5rem .9rem\">"
                        "No firing alerts.</p>")
    slo_rows = []
    for s in payload.get("slos", ()):
        windows = s.get("windows", ()) or [{}]
        for i, w in enumerate(windows):
            state = str(w.get("state", "inactive"))
            color = _ALERT_COLORS.get(state, "#57606a")
            burn = (f"{w.get('long_burn', '-')} / "
                    f"{w.get('short_burn', '-')}")
            first = (f"<td rowspan={len(windows)}>"
                     f"{html.escape(str(s.get('slo', '')))}</td>"
                     f"<td rowspan={len(windows)}>"
                     f"{float(s.get('objective', 0)):.2%}</td>"
                     f"<td rowspan={len(windows)} class=\"state\" "
                     f"style=\"color:"
                     f"{_ALERT_COLORS.get(str(s.get('state', '')), '#57606a')}\">"
                     f"{html.escape(str(s.get('state', '')))}</td>"
                     if i == 0 else "")
            slo_rows.append(
                "<tr>" + first +
                f"<td>{html.escape(str(w.get('window', '')))} "
                f"({html.escape(str(w.get('severity', '')))})</td>"
                f"<td class=\"state\" style=\"color:{color}\">{burn}"
                f"</td>"
                f"<td>&gt;{w.get('factor', '-')}&times;</td>"
                f"<td>{int(w.get('fire_count', 0) or 0)}</td></tr>")
    target_rows = []
    collector = payload.get("collector") or {}
    for address, st in (collector.get("targets") or {}).items():
        ok = st.get("ok")
        color = "#1a7f37" if ok else "#cf222e"
        target_rows.append(
            "<tr>"
            f"<td><code>{html.escape(str(address))}</code></td>"
            f"<td>{html.escape(str(st.get('job', '')))}</td>"
            f"<td class=\"state\" style=\"color:{color}\">"
            f"{'ok' if ok else html.escape(str(st.get('error', 'down'))[:60])}"
            f"</td>"
            f"<td>{float(st.get('age_s', 0)):.0f}s ago</td>"
            f"<td>{float(st.get('duration_ms', 0)):.1f} ms</td>"
            f"<td>{int(st.get('samples', 0))}</td></tr>")
    store = collector.get("store") or {}
    store_line = (
        f"Store: {int(store.get('series', 0))} series "
        f"(cap {int(store.get('max_series', 0))}, "
        f"{int(store.get('dropped_series', 0))} dropped), "
        f"{int(store.get('exemplars', 0))} exemplars."
        if store else "No in-process collector "
                      "(showing ConfigMap-published alerts).")
    exemplar_rows = []
    for e in (payload.get("exemplars") or ())[:16]:
        labels = e.get("labels", {})
        instance = str(labels.get("instance", ""))
        trace_id = str(e.get("trace_id", ""))
        tracez = (f"http://{instance}/tracez?trace_id={trace_id}"
                  if instance else f"/tracez?trace_id={trace_id}")
        metric = str(e.get("metric", "")).replace("_bucket", "")
        waterfall = f"/tpujobs/ui/waterfall?trace_id={trace_id}"
        exemplar_rows.append(
            "<tr>"
            f"<td>{html.escape(metric)}</td>"
            f"<td>{html.escape(str(labels.get('le', '')))}</td>"
            f"<td><code>{html.escape(instance)}</code></td>"
            f"<td>{float(e.get('value', 0)):.4f}</td>"
            f"<td><a href=\"{html.escape(tracez)}\"><code>"
            f"{html.escape(trace_id[:16])}</code></a></td>"
            f"<td><a href=\"{html.escape(waterfall)}\">waterfall"
            f"</a></td></tr>")
    tenant_rows = []
    for row in payload.get("tenants", ()):
        tenant_rows.append(
            "<tr>"
            f"<td><code>{html.escape(str(row.get('tenant', '?')))}"
            f"</code></td>"
            f"<td>{float(row.get('requests_per_s', 0) or 0):.2f}</td>"
            f"<td>{float(row.get('quota_shed_per_s', 0) or 0):.2f}</td>"
            f"<td>{float(row.get('overload_shed_per_s', 0) or 0):.2f}"
            f"</td>"
            f"<td>{float(row.get('expired_per_s', 0) or 0):.2f}</td>"
            f"<td>{float(row.get('decode_tokens_per_s', 0) or 0):.1f}"
            f"</td></tr>")
    return _HEALTH_PAGE.format(
        alert_banner=alert_banner,
        slo_rows="\n".join(slo_rows)
        or "<tr><td colspan=7>no SLOs configured</td></tr>",
        target_rows="\n".join(target_rows)
        or "<tr><td colspan=6>none</td></tr>",
        store_line=store_line,
        tenant_rows="\n".join(tenant_rows)
        or "<tr><td colspan=6>no tenant traffic observed</td></tr>",
        exemplar_rows="\n".join(exemplar_rows)
        or "<tr><td colspan=6>none yet</td></tr>")


_WATERFALL_PAGE = """<!doctype html>
<html><head><title>Waterfall {trace_id}</title>
<style>
 body {{ font-family: system-ui, sans-serif; margin: 2rem; }}
 table {{ border-collapse: collapse; min-width: 56rem;
          margin-bottom: 1.5rem; }}
 th, td {{ text-align: left; padding: .3rem .7rem;
          border-bottom: 1px solid #d0d7de; }}
 th {{ background: #f6f8fa; }}
 .bar {{ height: .8rem; display: inline-block; }}
 .attr {{ display: flex; height: 1.4rem; min-width: 48rem;
          border: 1px solid #d0d7de; }}
 .attr div {{ overflow: hidden; font-size: .7rem; color: #fff;
          padding-left: .2rem; white-space: nowrap; }}
</style></head>
<body>
<p><a href="/tpujobs/ui/health">&larr; fleet health</a></p>
<h1>Waterfall <code>{trace_id}</code></h1>
<h2>Latency attribution</h2>
<div class="attr">{attr_bar}</div>
<p>{attr_line}</p>
<h2>Spans ({span_count})</h2>
<table>
<tr><th>Span</th><th>Leg</th><th>Instance</th><th>Detail</th>
<th>Duration</th><th></th></tr>
{span_rows}
</table>
<p>Durations are per-process wall time; cross-process nesting comes
from the span parent links (docs/observability.md, "Distributed
tracing &amp; latency attribution"). JSON:
<a href="{api}">{api}</a> &middot; CLI:
<code>kft-trace {trace_id}</code></p>
</body></html>
"""

_ATTR_COLORS = {"queue_ms": "#9a6700", "prefill_ms": "#0969da",
                "decode_ms": "#1a7f37", "relay_ms": "#8250df",
                "gap_ms": "#57606a"}

_SPAN_BAR_COLORS = {"router": "#8250df", "serving": "#0969da",
                    "engine": "#1a7f37"}

_WATERFALL_INDEX = """<!doctype html>
<html><head><title>Waterfalls</title>
<style>
 body {{ font-family: system-ui, sans-serif; margin: 2rem; }}
 table {{ border-collapse: collapse; min-width: 40rem; }}
 th, td {{ text-align: left; padding: .3rem .7rem;
          border-bottom: 1px solid #d0d7de; }}
 th {{ background: #f6f8fa; }}
</style></head>
<body>
<p><a href="/tpujobs/ui/health">&larr; fleet health</a></p>
<h1>Assembled traces</h1>
<table>
<tr><th>Trace</th><th>Request id</th><th>Spans</th></tr>
{rows}
</table>
<p>{store_line}</p>
</body></html>
"""


def _waterfall_html(trace_id: str, spans, assembled,
                    report) -> str:
    """Render one assembled trace: attribution bar + indented span
    tree with duration bars (width ∝ share of the e2e wall)."""
    total = max(report["total_ms"], 1e-9)
    attr_parts = []
    for key, ms in report["buckets"].items():
        width = max(0.0, min(100.0, ms / total * 100.0))
        if width <= 0.0:
            continue
        attr_parts.append(
            f"<div style=\"width:{width:.1f}%;background:"
            f"{_ATTR_COLORS.get(key, '#57606a')}\" title=\""
            f"{html.escape(key)}: {ms:.2f} ms\">"
            f"{html.escape(key.removesuffix('_ms'))}</div>")
    attr_line = (f"e2e {report['total_ms']:.2f} ms — coverage "
                 f"{report['coverage'] * 100:.1f}%" + "".join(
                     f" &middot; {html.escape(k.removesuffix('_ms'))} "
                     f"{ms:.2f} ms"
                     for k, ms in report["buckets"].items()))
    if report.get("missing"):
        attr_line += (" &middot; missing: "
                      + html.escape(", ".join(report["missing"])))
    rows = []

    def walk(node, depth):
        span = node["span"]
        args = span.get("args") or {}
        dur_ms = float(span.get("dur", 0.0)) / 1e3
        width = max(0.4, min(100.0, dur_ms / total * 100.0))
        color = _SPAN_BAR_COLORS.get(span.get("cat", ""), "#57606a")
        detail = " ".join(
            f"{k}={args[k]}"
            for k in ("model", "tenant", "outcome", "slot", "reason",
                      "tokens", "program", "shapes", "rows")
            if k in args)
        indent = "&nbsp;" * (depth * 4)
        rows.append(
            "<tr>"
            f"<td>{indent}<code>{html.escape(str(span.get('name', '?')))}"
            f"</code></td>"
            f"<td>{html.escape(str(args.get('leg', '')))}</td>"
            f"<td><code>{html.escape(str(args.get('instance', '')))}"
            f"</code></td>"
            f"<td>{html.escape(detail)}</td>"
            f"<td>{dur_ms:.2f} ms</td>"
            f"<td><span class=\"bar\" style=\"width:{width:.1f}%;"
            f"background:{color}\"></span></td>"
            "</tr>")
        for child in node["children"]:
            walk(child, depth + 1)

    for root in assembled["roots"]:
        walk(root, 0)
    return _WATERFALL_PAGE.format(
        trace_id=html.escape(trace_id),
        attr_bar="".join(attr_parts) or "<div>no data</div>",
        attr_line=attr_line,
        span_count=len(spans),
        span_rows="\n".join(rows)
        or "<tr><td colspan=6>no spans</td></tr>",
        api=html.escape(f"/tpujobs/api/trace/{trace_id}"))


class WaterfallUIHandler(BaseHandler):
    """HTML Waterfall page (ISSUE 15): one request's assembled
    fleet-wide trace as an indented span tree plus the latency
    attribution bar; without ?trace_id=, an index of the traces the
    collector holds. Linked from the Fleet health exemplar table —
    the histogram-bucket → exemplar → waterfall workflow."""

    async def get(self):
        from kubeflow_tpu.obs import trace as obs_trace

        collector = self.application.settings.get("collector")
        store = getattr(collector, "span_store", None)
        self.set_header("Content-Type", "text/html; charset=utf-8")
        if store is None:
            return self.finish(
                "<p>No in-process span collection (start the "
                "dashboard with <code>--collect_endpoints</code>/"
                "<code>--collect_static</code>).</p>")
        trace_id = self.get_query_argument("trace_id", "")
        if not trace_id:
            rows = "\n".join(
                "<tr>"
                f"<td><a href=\"/tpujobs/ui/waterfall?trace_id="
                f"{html.escape(t['trace_id'])}\"><code>"
                f"{html.escape(t['trace_id'][:24])}</code></a></td>"
                f"<td><code>{html.escape(t['request_id'])}</code></td>"
                f"<td>{int(t['spans'])}</td></tr>"
                for t in store.trace_ids())
            state = store.state()
            return self.finish(_WATERFALL_INDEX.format(
                rows=rows or "<tr><td colspan=3>none yet</td></tr>",
                store_line=f"{state['traces']} trace(s), "
                           f"{state['spans']} span(s) held "
                           f"(caps {state['max_traces']} × "
                           f"{state['max_spans_per_trace']}; "
                           f"{state['dropped_spans']} dropped)."))
        spans = store.trace(trace_id)
        if not spans:
            self.set_status(404)
            return self.finish(
                f"<p>No spans for trace "
                f"<code>{html.escape(trace_id)}</code> (evicted, not "
                f"yet scraped, or never traced).</p>")
        loop = tornado.ioloop.IOLoop.current()
        assembled = await loop.run_in_executor(
            None, obs_trace.assemble, spans)
        try:
            body = _waterfall_html(trace_id, spans, assembled,
                                   obs_trace.attribution(spans))
        except Exception:  # noqa: BLE001 — render is best-effort
            logger.warning("waterfall render failed", exc_info=True)
            body = (f"<p>Waterfall render failed. JSON: <a href="
                    f"\"/tpujobs/api/trace/{html.escape(trace_id)}\">"
                    f"/tpujobs/api/trace/{html.escape(trace_id)}</a>"
                    f"</p>")
        self.finish(body)


class FleetHealthUIHandler(BaseHandler):
    """HTML "Fleet health" page: the operator's one-look view — SLO
    states and burn rates, firing alerts, collector target health,
    and exemplar links into /tracez."""

    async def get(self):
        namespace = self.get_query_argument("namespace", "default")
        settings = self.application.settings
        payload = await tornado.ioloop.IOLoop.current().run_in_executor(
            None, _telemetry_payload, settings, self.api, namespace)
        self.set_header("Content-Type", "text/html; charset=utf-8")
        try:
            body = _health_page_html(payload)
        except Exception:  # noqa: BLE001 — render is best-effort
            logger.warning("fleet health render failed", exc_info=True)
            body = ("<p>Fleet health payload unreadable. JSON: "
                    "<a href=\"/tpujobs/api/slo\">/tpujobs/api/slo"
                    "</a></p>")
        self.finish(body)


class UIHandler(BaseHandler):
    async def get(self):
        import asyncio

        from kubeflow_tpu.utils.traces import list_traces

        loop = tornado.ioloop.IOLoop.current()
        raw = await loop.run_in_executor(None, self.api.list, KIND)
        jobs = [job_summary(j) for j in raw]
        rows = []
        for j in jobs:
            color = _PHASE_COLORS.get(j["phase"], "#57606a")
            replicas = ", ".join(
                f"{html.escape(str(t))}×{int(n)}"
                for t, n in sorted(j["replicas"].items()))
            detail = (f"/tpujobs/ui/job/{j['namespace']}/{j['name']}")
            rows.append(
                "<tr>"
                f"<td>{html.escape(j['namespace'])}</td>"
                f"<td><a href=\"{html.escape(detail)}\">"
                f"{html.escape(j['name'])}</a></td>"
                f"<td class=\"phase\" style=\"color:{color}\">"
                f"{html.escape(j['phase'])}</td>"
                f"<td>{int(j['restartCount'])}</td>"
                f"<td>{replicas}</td>"
                "</tr>")
        trace_root = self.application.settings["trace_root"]
        traces, fleet = await asyncio.gather(
            loop.run_in_executor(None, list_traces, trace_root),
            loop.run_in_executor(None, _fetch_fleet, self.api))
        trace_rows = []
        for t in traces:
            files = ", ".join(f["name"] for f in t["files"])
            trace_rows.append(
                "<tr>"
                f"<td>{html.escape(t['job'] or '-')}</td>"
                f"<td>{html.escape(t['run'])}</td>"
                f"<td>{html.escape(files)}</td>"
                f"<td><code>{html.escape(t['dir'])}</code></td>"
                "</tr>")
        self.set_header("Content-Type", "text/html; charset=utf-8")
        self.finish(_PAGE.format(
            rows="\n".join(rows), count=len(jobs),
            trace_rows="\n".join(trace_rows), trace_count=len(traces),
            trace_root=html.escape(trace_root),
            fleet_section=_fleet_section_html(fleet)))


class UICreateHandler(BaseHandler):
    """Form-encoded create: builds the CR through the same manifest
    builders the CLI prototypes use, then the validated create path."""

    async def post(self):
        from kubeflow_tpu.manifests.tpujob import replica_spec, tpu_job

        name = self.get_body_argument("name", "")
        namespace = self.get_body_argument("namespace", "default")
        try:
            workers = int(self.get_body_argument("workers", "2"))
        except ValueError:
            return self.write_json({"error": "workers must be an int"}, 400)
        command = self.get_body_argument("command", "").split() or None
        job = tpu_job(
            name, namespace,
            [replica_spec(
                "TPU_WORKER", workers,
                image=self.get_body_argument(
                    "image", "ghcr.io/kubeflow-tpu/trainer:v0.1.0"),
                command=command,
                tpu_accelerator=self.get_body_argument(
                    "tpu_accelerator", "tpu-v5-lite-podslice"),
                tpu_topology=self.get_body_argument(
                    "tpu_topology", "2x4"),
            )],
            termination={"chief": {"replicaName": "TPU_WORKER",
                                   "replicaIndex": 0}},
        )
        errors = validate_tpujob(job)
        if errors:
            return self.write_json({"error": "invalid TPUJob",
                                    "details": errors}, 400)
        loop = tornado.ioloop.IOLoop.current()
        try:
            await loop.run_in_executor(None, self.api.create, job)
        except Exception as e:  # noqa: BLE001
            return self.write_json({"error": str(e)}, _create_error_code(e))
        self.redirect("/tpujobs/ui/")


DEFAULT_TRACE_ROOT = "/tmp/kft-profile"


def make_app(api, trace_root: str = DEFAULT_TRACE_ROOT,
             collector=None, alerts=None) -> tornado.web.Application:
    """``collector``/``alerts`` (obs/collector.Collector +
    obs/slo.AlertManager) enable the in-process telemetry pipeline:
    /tpujobs/api/slo and the Fleet health page read them live; without
    them the handlers fall back to the ConfigMap a sidecar collector
    publishes. The caller owns the collector thread's lifecycle."""
    return tornado.web.Application([
        (r"/healthz", HealthHandler),
        (r"/metrics", MetricsHandler),
        (r"/tpujobs/api/tpujob", JobListHandler),
        (r"/tpujobs/api/tpujob/([^/]+)/([^/]+)", JobDetailHandler),
        (r"/tpujobs/api/tpujob/([^/]+)/([^/]+)/logs/([^/]+)",
         PodLogsHandler),
        (r"/tpujobs/api/traces", TraceListHandler),
        (r"/tpujobs/api/spans", ChromeTraceHandler),
        (r"/tpujobs/api/operator", OperatorMetricsHandler),
        (r"/tpujobs/api/fleet", FleetHandler),
        (r"/tpujobs/api/tenants", TenantsHandler),
        (r"/tpujobs/api/slo", SloHandler),
        (r"/tpujobs/api/trace", TraceIndexHandler),
        (r"/tpujobs/api/trace/([^/]+)", TraceDetailHandler),
        (r"/tpujobs/ui/?", UIHandler),
        (r"/tpujobs/ui/health", FleetHealthUIHandler),
        (r"/tpujobs/ui/waterfall", WaterfallUIHandler),
        (r"/tpujobs/ui/job/([^/]+)/([^/]+)", UIJobDetailHandler),
        (r"/tpujobs/ui/create", UICreateHandler),
        (r"/", tornado.web.RedirectHandler, {"url": "/tpujobs/ui/"}),
    ], api=api, trace_root=trace_root, collector=collector,
       alerts=alerts, log_function=access_log_function("dashboard"))


def _build_telemetry(args, api):
    """Dashboard-resident collector + SLO evaluator from the
    --collect_* flags (None, None when no targets were asked for)."""
    if not (args.collect_endpoints or args.collect_static):
        return None, None
    from kubeflow_tpu.obs.collector import (
        Collector,
        SpanStore,
        parse_static_targets,
    )
    from kubeflow_tpu.obs.slo import AlertManager, default_slos

    source = None
    if args.collect_endpoints:
        from kubeflow_tpu.scaling.endpoints import FileEndpointSource

        source = FileEndpointSource(args.collect_endpoints)
    static = parse_static_targets(args.collect_static or "")
    # The dashboard-resident collector always assembles traces too
    # (SpanStore is bounded; the Waterfall page reads it) — every
    # cycle scrapes each target's /tracez next to its /metrics.
    collector = Collector(source=source, static_targets=static,
                          interval_s=args.collect_interval,
                          span_store=SpanStore())
    alerts = AlertManager(collector.store, default_slos(),
                          api=api, namespace=args.namespace)
    collector.on_cycle.append(alerts.evaluate)
    return collector, alerts


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpujob-dashboard")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--fake", action="store_true",
                        help="serve an in-memory apiserver (tests/demo)")
    parser.add_argument("--trace_root", default=DEFAULT_TRACE_ROOT,
                        help="shared dir (volume-mounted in-cluster) "
                             "where trainer --profile_dir traces land; "
                             "listed at /tpujobs/api/traces")
    parser.add_argument("--namespace", default="default",
                        help="namespace alert Events/ConfigMap land in")
    parser.add_argument("--collect_endpoints", default=None,
                        help="serving-fleet endpoints JSON to scrape "
                             "(the autoscaler-maintained file); "
                             "enables the in-process collector")
    parser.add_argument("--collect_static", default=None,
                        help="static scrape targets "
                             "addr[=job][,addr[=job]...] (router, "
                             "operator metrics port, ...)")
    parser.add_argument("--collect_interval", type=float, default=5.0)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.fake:
        from kubeflow_tpu.operator.fake import FakeApiServer

        api = FakeApiServer()
    else:
        from kubeflow_tpu.operator.controller import KubectlClient

        api = KubectlClient()
    collector, alerts = _build_telemetry(args, api)
    if collector is not None:
        collector.start()
        logger.info("fleet telemetry collector started (interval "
                    "%.1fs)", collector.interval_s)
    app = make_app(api, trace_root=args.trace_root,
                   collector=collector, alerts=alerts)
    app.listen(args.port)
    logger.info("tpujob-dashboard listening on :%d", args.port)
    try:
        tornado.ioloop.IOLoop.current().start()
    finally:
        if collector is not None:
            collector.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
