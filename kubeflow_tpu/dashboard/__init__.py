from kubeflow_tpu.dashboard.server import make_app, main  # noqa: F401
