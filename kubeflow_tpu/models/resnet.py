# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""ResNet family (v1.5) in Flax, TPU-first.

The flagship benchmark model — the rebuild of the reference's
``tf_cnn_benchmarks.py --model=resnet50`` path (invoked via
``kubeflow/tf-job/prototypes/tf-cnn-benchmarks.jsonnet:36-43``).

TPU design notes:
- bfloat16 activations/compute, float32 params and BN statistics: the
  MXU natively consumes bf16; keeping params fp32 preserves SGD
  accuracy without loss scaling.
- NHWC layout (XLA:TPU's preferred conv layout; the reference only
  used NHWC as a CPU *fallback*, ``tf-cnn-benchmarks.jsonnet:50-54``).
- No data-dependent Python control flow — the whole net traces to one
  XLA program; stage loops unroll at trace time (static depth).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from kubeflow_tpu.models.registry import ModelEntry, register_model
from kubeflow_tpu.ops.batch_norm import GhostBatchNorm

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 → 3x3 → 1x1 bottleneck with projection shortcut (v1.5:
    stride on the 3x3, not the 1x1)."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides, name="conv2")(y)
        y = self.norm(name="bn2")(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1), name="conv3")(y)
        # Zero-init the last BN scale: residual branches start as
        # identity, the standard large-batch trick.
        y = self.norm(scale_init=nn.initializers.zeros, name="bn3")(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="bn_proj")(residual)
        return self.act(residual + y)


def space_to_depth(x: jax.Array, block: int = 2) -> jax.Array:
    """[B, H, W, C] → [B, H/b, W/b, b²·C]; channel order (a, b, c)
    with a/b the within-block spatial offsets (the order
    :func:`stem_kernel_to_s2d` assumes)."""
    bsz, h, w, c = x.shape
    x = x.reshape(bsz, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(bsz, h // block, w // block, block * block * c)


def stem_kernel_to_s2d(w7: jax.Array) -> jax.Array:
    """Reparametrize a (7,7,C,O) stride-2 stem kernel into the
    equivalent (4,4,4C,O) stride-1 kernel over space-to-depth input.

    Derivation: with SAME padding (2 left, 3 right) the original
    output is y[i,j] = Σ w[u,v,c]·x[2i+u−2, 2j+v−2, c]. Writing
    u = 2a' + a (a ∈ {0,1} the s2d channel offset, a' the s2d spatial
    tap −1..2) maps every (u,v) into a 4×4 window over s2d pixels with
    padding (1,2); u=7 taps don't exist, so the 7×7 kernel is
    zero-padded to 8×8 first.
    """
    k, _, c, o = w7.shape
    assert k == 7, w7.shape
    w8 = jnp.pad(w7, ((0, 1), (0, 1), (0, 0), (0, 0)))
    # [8,8,C,O] → [4,2(a),4,2(b),C,O] → [4,4,2,2,C,O] → [4,4,4C,O]
    w = w8.reshape(4, 2, 4, 2, c, o).transpose(0, 2, 1, 3, 4, 5)
    return w.reshape(4, 4, 4 * c, o)


class ResNet(nn.Module):
    """ResNet v1.5 for NHWC image batches.

    ``stem``: "conv7" (the textbook 7×7/s2) or "s2d" — the MLPerf
    space-to-depth reparametrization: mathematically the same function
    (see :func:`stem_kernel_to_s2d`), but the conv sees 12 input
    channels at 112² instead of 3 at 224², a far better MXU shape.
    """

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    stem: str = "conv7"
    # Training BN statistics over the first N batch rows (0 = all):
    # ghost-batch estimation — the step is BN-stat-HBM-bound, so this
    # is the measured throughput lever; needs a shuffled pipeline
    # (ops/batch_norm.py, PERF.md).
    bn_stat_rows: int = 0

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
        )
        norm = functools.partial(
            GhostBatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            stat_rows=self.bn_stat_rows,
        )
        act = nn.relu

        x = x.astype(self.dtype)
        if self.stem == "s2d":
            x = space_to_depth(x)
            x = conv(self.width, (4, 4), (1, 1),
                     padding=((1, 2), (1, 2)), name="conv_init")(x)
        elif self.stem == "conv7":
            x = conv(self.width, (7, 7), (2, 2), name="conv_init")(x)
        else:
            raise ValueError(
                f"unknown stem {self.stem!r}; expected 'conv7' or 's2d'")
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(self.stage_sizes):
            for block in range(n_blocks):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                x = BottleneckBlock(
                    filters=self.width * 2 ** stage,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=act,
                    name=f"stage{stage + 1}_block{block + 1}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        # Head in fp32: the final matmul + softmax is tiny; fp32 keeps
        # logits numerically clean for the loss.
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(
            x.astype(jnp.float32)
        )
        return x


def resnet50(num_classes: int = 1000, dtype: Any = jnp.bfloat16,
             stem: str = "conv7", bn_stat_rows: int = 0) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes,
                  dtype=dtype, stem=stem, bn_stat_rows=bn_stat_rows)


def resnet101(num_classes: int = 1000, dtype: Any = jnp.bfloat16,
              bn_stat_rows: int = 0) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 23, 3), num_classes=num_classes,
                  dtype=dtype, bn_stat_rows=bn_stat_rows)


def resnet18ish(num_classes: int = 10, dtype: Any = jnp.bfloat16,
                bn_stat_rows: int = 0) -> ResNet:
    """Small bottleneck net for tests/CI (not a literal ResNet-18)."""
    return ResNet(stage_sizes=(1, 1, 1, 1), num_classes=num_classes,
                  width=16, dtype=dtype, bn_stat_rows=bn_stat_rows)


register_model(ModelEntry("resnet50", "vision", resnet50, ((224, 224, 3), "bfloat16"), 1000))
register_model(ModelEntry("resnet101", "vision", resnet101, ((224, 224, 3), "bfloat16"), 1000))
register_model(ModelEntry("resnet-test", "vision", resnet18ish, ((32, 32, 3), "bfloat16"), 10))
