# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Model zoo for the TPU training engine.

The reference's models were external (tf_cnn_benchmarks cloned into the
training image, ``tf-controller-examples/tf-cnn/Dockerfile.template:17-27``;
inception SavedModel for serving). Here the benchmark models are
in-tree JAX code: ResNet-50 and Inception-v3 (the tf-cnn families),
ViT-B/16-L/16 (beyond-parity vision transformer, the tree's highest
measured MFU), BERT (multi-host baseline config) and a Llama-style
decoder (long context / notebook fine-tune config).
"""

from kubeflow_tpu.models.registry import get_model, list_models, register_model  # noqa: F401
