"""Model zoo for the TPU training engine.

The reference's models were external (tf_cnn_benchmarks cloned into the
training image, ``tf-controller-examples/tf-cnn/Dockerfile.template:17-27``;
inception SavedModel for serving). Here the benchmark models are
in-tree JAX code: ResNet-50 and Inception-v3 (the tf-cnn families),
ViT-B/16-L/16 (beyond-parity vision transformer, the tree's highest
measured MFU), BERT (multi-host baseline config) and a Llama-style
decoder (long context / notebook fine-tune config).
"""

from kubeflow_tpu.models.registry import get_model, list_models, register_model  # noqa: F401
