# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Model registry: name → (constructor, canonical input spec).

The tf-cnn prototype selected models by string flag
(``--model=resnet50``, reference
``kubeflow/tf-job/prototypes/tf-cnn-benchmarks.jsonnet:9,38``); this
registry is the typed equivalent the trainer CLI resolves against.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ModelEntry:
    name: str
    family: str  # "vision" | "language"
    make: Callable[..., Any]  # returns a flax Module
    input_spec: Tuple[Tuple[int, ...], str]  # (shape sans batch, dtype)
    num_classes_or_vocab: int
    # Benchmark sgd lr override for models whose training dynamics
    # reject the family default (no-norm classics NaN at the BN-era
    # 0.1); recorded here, next to the model, so new registrations
    # carry the fact with them.
    bench_lr: Optional[float] = None
    # Causal decoder with a generate/decode path (KV cache, greedy
    # decode export). family == "language" alone doesn't imply it:
    # BERT encoders are language models with no decode machinery.
    decoder: bool = False


_MODELS: Dict[str, ModelEntry] = {}


def register_model(entry: ModelEntry) -> None:
    if entry.name in _MODELS:
        raise ValueError(f"model {entry.name!r} already registered")
    _MODELS[entry.name] = entry


def _ensure_loaded() -> None:
    import importlib

    for mod in (
        "kubeflow_tpu.models.resnet",
        "kubeflow_tpu.models.inception",
        "kubeflow_tpu.models.vit",
        "kubeflow_tpu.models.bert",
        "kubeflow_tpu.models.llama",
        "kubeflow_tpu.models.classic_cnn",
    ):
        try:
            importlib.import_module(mod)
        except ModuleNotFoundError as e:
            if e.name != mod:
                raise


def get_model(name: str) -> ModelEntry:
    _ensure_loaded()
    try:
        return _MODELS[name]
    except KeyError:
        raise KeyError(
            f"unknown model {name!r}; available: {sorted(_MODELS)}"
        ) from None


def list_models() -> Dict[str, ModelEntry]:
    _ensure_loaded()
    return dict(_MODELS)
