# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""BERT encoder (base/large) in Flax, TPU-first.

BASELINE target model (multi-host pretraining step time; the reference
itself ships no sequence models — SURVEY §5 "long-context: absent").

TPU design notes:
- bf16 activations, fp32 params; attention softmax statistics in fp32
  (:mod:`kubeflow_tpu.ops.attention`).
- Every kernel carries *logical* axis names via ``nn.with_partitioning``
  so one model definition serves DP, FSDP, and Megatron TP — the rule
  table (:mod:`kubeflow_tpu.parallel.tensor_parallel`) decides the
  mesh mapping; GSPMD inserts the collectives.
- Static shapes end-to-end: padding is masked arithmetically
  (``attention_mask``), never sliced.
- ``attention_fn`` hook: dense by default; pass a sequence-parallel
  wrapper (:func:`kubeflow_tpu.parallel.ring_attention.
  make_sequence_parallel_attention`) for long-context runs.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from kubeflow_tpu.models.registry import ModelEntry, register_model
from kubeflow_tpu.ops.flash_attention import flash_attention

AttentionFn = Callable[..., jax.Array]


def _dense(features, axes, dtype, name=None, use_bias=True):
    return nn.Dense(
        features,
        dtype=dtype,
        use_bias=use_bias,
        kernel_init=nn.with_partitioning(
            nn.initializers.normal(0.02), axes
        ),
        name=name,
    )


class BertSelfAttention(nn.Module):
    num_heads: int
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[AttentionFn] = None

    @nn.compact
    def __call__(self, x, valid):
        d_model = x.shape[-1]
        head_dim = d_model // self.num_heads
        proj = functools.partial(
            _dense, dtype=self.dtype
        )
        q = proj(d_model, ("embed", "heads"), name="query")(x)
        k = proj(d_model, ("embed", "heads"), name="key")(x)
        v = proj(d_model, ("embed", "heads"), name="value")(x)
        split = lambda t: t.reshape(
            t.shape[0], t.shape[1], self.num_heads, head_dim
        )
        # Every attention impl (dense/blockwise/flash/ring/ulysses)
        # takes the padding mask as kv_segment_valid, so a custom
        # attention_fn (the sequence-parallel path) masks padded keys
        # exactly like the default — not silently attending to them.
        attn = self.attention_fn or flash_attention
        out = attn(split(q), split(k), split(v), kv_segment_valid=valid)
        out = out.reshape(out.shape[0], out.shape[1], d_model)
        return proj(d_model, ("heads", "embed"), name="out")(out)


class BertLayer(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[AttentionFn] = None

    @nn.compact
    def __call__(self, x, valid):
        # Post-LN (original BERT): residual → LayerNorm.
        attn_out = BertSelfAttention(
            self.num_heads, self.dtype, self.attention_fn, name="attention"
        )(x, valid)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_attn")(x + attn_out)
        h = _dense(self.mlp_dim, ("embed", "mlp"), self.dtype, "mlp_in")(x)
        h = nn.gelu(h, approximate=True)
        h = _dense(x.shape[-1], ("mlp", "embed"), self.dtype, "mlp_out")(h)
        return nn.LayerNorm(dtype=self.dtype, name="ln_mlp")(x + h)


class Bert(nn.Module):
    """BERT encoder + tied-embedding MLM head.

    ``__call__(input_ids, type_ids, valid)`` → MLM logits
    [batch, seq, vocab]. ``valid`` is the 0/1 attention mask.
    """

    vocab_size: int = 30522
    num_layers: int = 12
    d_model: int = 768
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    num_segments: int = 2
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[AttentionFn] = None

    @nn.compact
    def __call__(self, input_ids, type_ids=None, valid=None, train=True):
        del train  # no dropout in the pretraining benchmark config
        b, l = input_ids.shape
        if type_ids is None:
            type_ids = jnp.zeros_like(input_ids)
        # valid=None stays None: the no-padding case skips the mask
        # branch in every attention impl instead of carrying an
        # all-ones array through the kernel.

        embed = nn.Embed(
            self.vocab_size, self.d_model,
            embedding_init=nn.with_partitioning(
                nn.initializers.normal(0.02), ("vocab", "embed")
            ),
            dtype=self.dtype, name="tok_embed",
        )
        x = embed(input_ids)
        x = x + nn.Embed(
            self.max_len, self.d_model, dtype=self.dtype, name="pos_embed",
            embedding_init=nn.initializers.normal(0.02),
        )(jnp.arange(l)[None, :])
        x = x + nn.Embed(
            self.num_segments, self.d_model, dtype=self.dtype,
            name="seg_embed",
            embedding_init=nn.initializers.normal(0.02),
        )(type_ids)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_embed")(x)

        for i in range(self.num_layers):
            x = BertLayer(
                self.num_heads, self.mlp_dim, self.dtype,
                self.attention_fn, name=f"layer_{i}",
            )(x, valid)

        # MLM head: transform + tied output embedding (fp32 logits).
        h = _dense(self.d_model, (None, "embed"), self.dtype,
                   "mlm_transform")(x)
        h = nn.gelu(h, approximate=True)
        h = nn.LayerNorm(dtype=self.dtype, name="mlm_ln")(h)
        logits = embed.attend(h.astype(jnp.float32))
        logits = logits + self.param(
            "mlm_bias", nn.initializers.zeros, (self.vocab_size,), jnp.float32
        )
        return logits


def bert_base(**kw) -> Bert:
    return Bert(**kw)


def bert_large(**kw) -> Bert:
    return Bert(num_layers=24, d_model=1024, num_heads=16, mlp_dim=4096, **kw)


def bert_test(**kw) -> Bert:
    """Tiny config for CI (2 layers, d=64)."""
    kw.setdefault("vocab_size", 512)
    kw.setdefault("max_len", 128)
    return Bert(num_layers=2, d_model=64, num_heads=4, mlp_dim=128, **kw)


register_model(ModelEntry("bert-base", "language", bert_base, ((128,), "int32"), 30522))
register_model(ModelEntry("bert-large", "language", bert_large, ((128,), "int32"), 30522))
register_model(ModelEntry("bert-test", "language", bert_test, ((64,), "int32"), 512))
