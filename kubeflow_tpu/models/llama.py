# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Llama-family decoder (RMSNorm / RoPE / SwiGLU / GQA) in Flax, TPU-first.

BASELINE stretch target (Llama-2-7B fine-tune on a v5e slice). The
reference has no decoder models; this is greenfield, built on the same
logical-axis TP vocabulary as BERT (``parallel/tensor_parallel.py``)
and the fp32-statistics attention core (``ops/attention.py``).

Long-context is first-class: ``attention_fn`` accepts a sequence-
parallel wrapper (ring attention over the ``seq`` mesh axis,
``parallel/ring_attention.py``), and the default path is the fused
Pallas flash kernel (``ops/flash_attention.py``) so single-chip memory
stays O(L·block) instead of O(L²) at any length.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from kubeflow_tpu.models.registry import ModelEntry, register_model
from kubeflow_tpu.ops.attention import dense_attention
from kubeflow_tpu.ops.flash_attention import flash_attention
from kubeflow_tpu.ops.lora import LoRADense
from kubeflow_tpu.ops.moe import MoE

AttentionFn = Callable[..., jax.Array]


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # Variance in fp32 regardless of activation dtype.
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
        normed = x * jax.lax.rsqrt(var + self.eps).astype(x.dtype)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        return normed * scale.astype(self.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float = 10000.0) -> jax.Array:
    """Rotary embeddings for [B, L, H, D] (D even). fp32 trig."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[:, :, None, None].astype(jnp.float32) * freqs  # B,L,1,D/2
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    rotated = jnp.stack(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).reshape(x.shape)
    return rotated.astype(x.dtype)


def _dense(features, axes, dtype, name=None, lora_rank=0, lora_alpha=16.0):
    if lora_rank:
        return LoRADense(features, axes, dtype, lora_rank, lora_alpha,
                         name=name)
    return nn.Dense(
        features, dtype=dtype, use_bias=False,
        kernel_init=nn.with_partitioning(
            nn.initializers.normal(0.02), axes
        ),
        name=name,
    )


class LlamaAttention(nn.Module):
    num_heads: int
    num_kv_heads: int
    head_dim: int
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[AttentionFn] = None
    cache_size: int = 0  # >0 → autoregressive KV cache (generation)
    lora_rank: int = 0  # >0 → LoRA adapters on q/k/v/o (ops/lora.py)
    lora_alpha: float = 16.0

    @nn.compact
    def __call__(self, x, positions, pad_lengths=None,
                 decode_positions=None):
        b, l, d_model = x.shape
        q = _dense(self.num_heads * self.head_dim, ("embed", "heads"),
                   self.dtype, "q_proj", self.lora_rank, self.lora_alpha)(x)
        k = _dense(self.num_kv_heads * self.head_dim, ("embed", "kv"),
                   self.dtype, "k_proj", self.lora_rank, self.lora_alpha)(x)
        v = _dense(self.num_kv_heads * self.head_dim, ("embed", "kv"),
                   self.dtype, "v_proj", self.lora_rank, self.lora_alpha)(x)
        q = q.reshape(b, l, self.num_heads, self.head_dim)
        k = k.reshape(b, l, self.num_kv_heads, self.head_dim)
        v = v.reshape(b, l, self.num_kv_heads, self.head_dim)
        q = rope(q, positions, self.rope_theta)
        k = rope(k, positions, self.rope_theta)
        if self.cache_size:
            if self.attention_fn is not None:
                raise ValueError(
                    "cache_size and attention_fn are mutually exclusive: "
                    "the decode path always uses dense attention over the "
                    "cache, which would silently replace a sequence-"
                    "parallel attention_fn")
        elif decode_positions is not None:
            raise ValueError(
                "decode_positions requires a cache_size model (the "
                "slot-based decode engine writes each row's K/V into "
                "its own cache slot)")
        elif pad_lengths is not None:
            # Left-padding is a decode-path concept (batched generation
            # coalesces mixed-length prompts); the training/full-forward
            # paths have no cache slots to mask, and silently ignoring
            # the argument would attend over pad garbage.
            raise ValueError(
                "pad_lengths requires a cache_size model (batched "
                "generation left-pads into the KV cache)")
        if self.cache_size:
            # Decode path: append this call's K/V into the static-size
            # cache, attend over the valid prefix. All shapes static
            # (TPU rule); validity is arithmetic. The cache's TIME axis
            # is sized by whatever array rides the "cache" collection —
            # the classic path passes [b, cache_size, ...] buffers, the
            # slot engine passes page-gathered views whose padded tail
            # is masked, so both share one compiled program shape rule.
            cached_k = self.variable(
                "cache", "k", jnp.zeros,
                (b, self.cache_size, self.num_kv_heads, self.head_dim),
                self.dtype)
            cached_v = self.variable(
                "cache", "v", jnp.zeros,
                (b, self.cache_size, self.num_kv_heads, self.head_dim),
                self.dtype)
            index = self.variable(
                "cache", "index", lambda: jnp.zeros((), jnp.int32))
            slots = cached_k.value.shape[1]
            if decode_positions is not None:
                # Slot-engine decode (inference/engine/): every row
                # sits at its OWN cache position — rows joined the
                # persistent batch at different times — so the write
                # index is per-row ([B] int32), not the shared scalar.
                # l == 1 is the classic decode step: the newest token
                # may attend to every valid slot, so validity alone IS
                # causality and the masked scores match the scalar
                # path's causal+valid composition bitwise. l > 1 is
                # the multi-token verify contract (speculative
                # decoding): row b's block token j sits at cache
                # position start[b] + j, so each query carries its own
                # causal frontier — the per-query [B, l, slots] mask.
                # The j-th row of the block's logits then equals the
                # single-token step's logits at the same position
                # bitwise (same masked score set, and every per-
                # position op is an independent dot over the same
                # operands).
                start = decode_positions  # [B] int32
                cached_k.value = jax.vmap(
                    lambda c, u, s: jax.lax.dynamic_update_slice(
                        c, u, (s, 0, 0)))(
                    cached_k.value, k.astype(self.dtype), start)
                cached_v.value = jax.vmap(
                    lambda c, u, s: jax.lax.dynamic_update_slice(
                        c, u, (s, 0, 0)))(
                    cached_v.value, v.astype(self.dtype), start)
                # The scalar index is meaningless across slots; leave
                # it untouched (the engine carries per-slot positions).
                def pos_valid(frontier):
                    # Validity at one per-row frontier: [B, slots].
                    v = (jnp.arange(slots)[None, :]
                         <= frontier[:, None]).astype(jnp.int32)
                    if pad_lengths is not None:
                        v = v * (jnp.arange(slots)[None, :]
                                 >= pad_lengths[:, None]
                                 ).astype(jnp.int32)
                    return v

                if l == 1:
                    out = dense_attention(
                        q, cached_k.value, cached_v.value,
                        causal=False, kv_segment_valid=pos_valid(start))
                else:
                    # Multi-token verify: per-query attention UNROLLED
                    # at the single-token shapes ([B, 1, H, D] query
                    # against the full cache). One [l, S] GEMM would
                    # be tidier, but its value contraction
                    # reassociates the S-sum differently than the
                    # l == 1 GEMV — a 1-ulp drift that breaks the
                    # engine's bitwise token contract. Unrolling keeps
                    # every kernel shape identical to the vanilla
                    # decode step's, which is what makes block row j's
                    # logits bitwise-equal to the one-token step at
                    # position start + j; the cost is per-query cache
                    # attention, negligible next to the weight read
                    # the verify forward amortizes.
                    out = jnp.concatenate([
                        dense_attention(
                            q[:, j:j + 1], cached_k.value,
                            cached_v.value, causal=False,
                            kv_segment_valid=pos_valid(
                                start + jnp.asarray(j, start.dtype)))
                        for j in range(l)], axis=1)
            else:
                start = index.value
                cached_k.value = jax.lax.dynamic_update_slice(
                    cached_k.value, k.astype(self.dtype), (0, start, 0, 0))
                cached_v.value = jax.lax.dynamic_update_slice(
                    cached_v.value, v.astype(self.dtype), (0, start, 0, 0))
                index.value = start + l
                valid = (jnp.arange(slots)[None, :]
                         < (start + l)).astype(jnp.int32)
                valid = jnp.broadcast_to(valid, (b, slots))
                if pad_lengths is not None:
                    # Batched mixed-length prompts are LEFT-padded: row
                    # i's first pad_lengths[i] cache slots hold
                    # pad-token K/V that must never receive attention
                    # mass. Slot order still equals time order per row
                    # (pads are "earliest"), so the scalar causal
                    # q_offset stays correct.
                    valid = valid * (jnp.arange(slots)[None, :]
                                     >= pad_lengths[:, None]
                                     ).astype(jnp.int32)
                out = dense_attention(
                    q, cached_k.value, cached_v.value, causal=True,
                    q_offset=start, kv_offset=0, kv_segment_valid=valid)
        elif self.attention_fn is not None:
            out = self.attention_fn(q, k, v)
        else:
            # Default: fused Pallas flash kernel (falls back to XLA
            # blockwise internally on non-dividing shapes), O(L·block)
            # memory at any length.
            out = flash_attention(q, k, v, causal=True)
        out = out.reshape(b, l, self.num_heads * self.head_dim)
        return _dense(d_model, ("heads", "embed"), self.dtype, "o_proj",
                      self.lora_rank, self.lora_alpha)(out)


class LlamaBlock(nn.Module):
    num_heads: int
    num_kv_heads: int
    head_dim: int
    mlp_dim: int
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[AttentionFn] = None
    num_experts: int = 0  # >0 → MoE FFN (expert-parallel)
    num_selected: int = 2
    cache_size: int = 0
    lora_rank: int = 0
    lora_alpha: float = 16.0

    @nn.compact
    def __call__(self, x, positions, pad_lengths=None,
                 decode_positions=None):
        h = RMSNorm(dtype=self.dtype, name="attn_norm")(x)
        x = x + LlamaAttention(
            self.num_heads, self.num_kv_heads, self.head_dim,
            self.rope_theta, self.dtype, self.attention_fn,
            self.cache_size, self.lora_rank, self.lora_alpha,
            name="attention",
        )(h, positions, pad_lengths, decode_positions)
        h = RMSNorm(dtype=self.dtype, name="mlp_norm")(x)
        if self.num_experts > 0:
            return x + MoE(
                num_experts=self.num_experts, mlp_dim=self.mlp_dim,
                num_selected=self.num_selected, dtype=self.dtype,
                name="moe",
            )(h)
        gate = _dense(self.mlp_dim, ("embed", "mlp"), self.dtype,
                      "gate_proj")(h)
        up = _dense(self.mlp_dim, ("embed", "mlp"), self.dtype, "up_proj")(h)
        h = nn.silu(gate) * up
        return x + _dense(x.shape[-1], ("mlp", "embed"), self.dtype,
                          "down_proj")(h)


class Llama(nn.Module):
    """Decoder-only LM: ``__call__(input_ids)`` → logits [B, L, vocab]."""

    vocab_size: int = 32000
    num_layers: int = 32
    d_model: int = 4096
    num_heads: int = 32
    num_kv_heads: int = 32
    mlp_dim: int = 11008
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[AttentionFn] = None
    remat: bool = False
    num_experts: int = 0  # >0 → MoE FFN in every block
    num_selected: int = 2
    cache_size: int = 0  # >0 → KV cache (inference/generate.py)
    lora_rank: int = 0  # >0 → LoRA fine-tuning (training/finetune.py)
    lora_alpha: float = 16.0

    @nn.compact
    def __call__(self, input_ids, positions=None, train=True,
                 pad_lengths=None, decode_positions=None):
        """``pad_lengths`` (optional, [B] int32, cache models only):
        per-row count of LEFT-pad slots in a batched mixed-length
        decode — those cache slots are masked out of attention
        (inference/generate.py owns the matching position offsets).

        ``decode_positions`` (optional, [B] int32, cache models only):
        per-row cache write index for slot-based decode — the
        continuous-batching engine (inference/engine/) keeps each
        slot at its own position instead of sharing the scalar cache
        index, so rows can join and retire mid-decode. With L == 1
        this is the classic decode step; with L > 1 it is the
        multi-token verify contract (speculative decoding): row b's
        block token j is written at ``decode_positions[b] + j`` and
        attends under its own per-query causal frontier, so block
        logits row j equal the one-token step's logits at the same
        position bitwise."""
        del train
        b, l = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(l)[None, :], (b, l))
        x = nn.Embed(
            self.vocab_size, self.d_model,
            embedding_init=nn.with_partitioning(
                nn.initializers.normal(0.02), ("vocab", "embed")
            ),
            dtype=self.dtype, name="tok_embed",
        )(input_ids)
        block_cls = LlamaBlock
        if self.remat:
            # Rematerialize each block on the backward pass: the
            # FLOPs-for-HBM trade that makes 7B+ fit a v5e slice.
            block_cls = nn.remat(LlamaBlock)
        head_dim = self.d_model // self.num_heads
        for i in range(self.num_layers):
            x = block_cls(
                self.num_heads, self.num_kv_heads, head_dim, self.mlp_dim,
                self.rope_theta, self.dtype, self.attention_fn,
                self.num_experts, self.num_selected, self.cache_size,
                self.lora_rank, self.lora_alpha,
                name=f"layer_{i}",
            )(x, positions, pad_lengths, decode_positions)
        x = RMSNorm(dtype=self.dtype, name="final_norm")(x)
        logits = _dense(self.vocab_size, ("embed", "vocab"), jnp.float32,
                        "lm_head")(x.astype(jnp.float32))
        return logits


def llama2_7b(**kw) -> Llama:
    return Llama(**kw)


def llama2_13b(**kw) -> Llama:
    return Llama(num_layers=40, d_model=5120, num_heads=40, num_kv_heads=40,
                 mlp_dim=13824, **kw)


def llama3_8b(**kw) -> Llama:
    return Llama(vocab_size=128256, num_layers=32, d_model=4096,
                 num_heads=32, num_kv_heads=8, mlp_dim=14336,
                 rope_theta=500000.0, **kw)


def llama_test(**kw) -> Llama:
    """Tiny GQA config for CI."""
    kw.setdefault("vocab_size", 512)
    return Llama(num_layers=2, d_model=64, num_heads=4, num_kv_heads=2,
                 mlp_dim=128, **kw)


def llama_moe_test(**kw) -> Llama:
    """Tiny MoE config for CI (4 experts, top-2, expert-parallel)."""
    kw.setdefault("vocab_size", 512)
    kw.setdefault("num_experts", 4)
    return Llama(num_layers=2, d_model=64, num_heads=4, num_kv_heads=2,
                 mlp_dim=128, **kw)


def llama_moe_bench(**kw) -> Llama:
    """Single-chip MoE pricing config: 8 experts, top-2. Its ACTIVE
    FLOPs per token equal llama-moe-dense-twin's (2 selected experts
    × mlp 3584 = the twin's dense mlp 7168), so the tokens/s ratio
    between the two directly prices the router + capacity-dispatch
    overhead (VERDICT-r4 next #6; bench.py extras, PERF.md)."""
    kw.setdefault("vocab_size", 8192)
    kw.setdefault("num_experts", 8)
    return Llama(num_layers=4, d_model=1024, num_heads=16,
                 num_kv_heads=8, mlp_dim=3584, **kw)


def llama_moe_dense_twin(**kw) -> Llama:
    """FLOP-matched dense twin of llama_moe_bench (see above)."""
    kw.setdefault("vocab_size", 8192)
    return Llama(num_layers=4, d_model=1024, num_heads=16,
                 num_kv_heads=8, mlp_dim=7168, **kw)


register_model(ModelEntry("llama2-7b", "language", llama2_7b, ((2048,), "int32"), 32000,
                          decoder=True))
register_model(ModelEntry("llama2-13b", "language", llama2_13b, ((2048,), "int32"), 32000,
                          decoder=True))
register_model(ModelEntry("llama3-8b", "language", llama3_8b, ((2048,), "int32"), 128256,
                          decoder=True))
register_model(ModelEntry("llama-test", "language", llama_test, ((128,), "int32"), 512,
                          decoder=True))
register_model(ModelEntry("llama-moe-test", "language", llama_moe_test, ((128,), "int32"), 512,
                          decoder=True))
register_model(ModelEntry("llama-moe-bench", "language", llama_moe_bench,
                          ((1024,), "int32"), 8192, decoder=True))
register_model(ModelEntry("llama-moe-dense-twin", "language",
                          llama_moe_dense_twin, ((1024,), "int32"), 8192,
                          decoder=True))
