# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Classic CNN zoo: VGG-16 and AlexNet, TPU-first (NHWC, bf16).

The reference's benchmark harness selected models by string flag
(``--model`` on tf_cnn_benchmarks, surfaced by the tpu-cnn prototype —
reference ``kubeflow/tf-job/prototypes/tf-cnn-benchmarks.jsonnet:8-9``
``@optionalParam model string resnet50``); resnet50/inception3 ship in
:mod:`resnet` / :mod:`inception`, and these two complete the flag's
classic values. TPU notes: both are giant-FC models — VGG-16 carries
~90 % of its parameters in three dense layers and AlexNet ~95 % —
which map straight onto the MXU as large matmuls, so unlike the
BN-bound resnet these run close to FLOP-limited. Dropout is omitted
(the harness measures throughput with synthetic labels; adding rng
plumbing for a regularizer the benchmark never evaluates would change
the trainer contract for nothing — same choice the no-BN VGG of the
original harness made).
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from kubeflow_tpu.models.registry import ModelEntry, register_model


class VGG(nn.Module):
    """Stacked 3×3 conv stages + two 4096-wide FC layers (VGG-A..E
    shape; ``stage_sizes`` picks the depth — (2,2,3,3,3) = VGG-16)."""

    stage_sizes: Sequence[int] = (2, 2, 3, 3, 3)
    widths: Sequence[int] = (64, 128, 256, 512, 512)
    num_classes: int = 1000
    dense_width: int = 4096
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train  # no BN/dropout: train == eval (docstring)
        conv = functools.partial(nn.Conv, kernel_size=(3, 3),
                                 padding="SAME", dtype=self.dtype)
        x = x.astype(self.dtype)
        for stage, (depth, width) in enumerate(
                zip(self.stage_sizes, self.widths)):
            for i in range(depth):
                x = nn.relu(conv(width, name=f"conv{stage}_{i}")(x))
            x = nn.max_pool(x, (2, 2), (2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.dense_width, dtype=self.dtype,
                             name="fc1")(x))
        x = nn.relu(nn.Dense(self.dense_width, dtype=self.dtype,
                             name="fc2")(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x.astype(jnp.float32))


class AlexNet(nn.Module):
    """Five convs + three FC layers (the 2012 single-tower shape the
    benchmark harness used; LRN dropped — it predates BN and buys
    nothing on modern hardware)."""

    num_classes: int = 1000
    dense_width: int = 4096
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(96, (11, 11), (4, 4), padding="SAME",
                            dtype=self.dtype, name="conv1")(x))
        x = nn.max_pool(x, (3, 3), (2, 2))
        x = nn.relu(nn.Conv(256, (5, 5), padding="SAME",
                            dtype=self.dtype, name="conv2")(x))
        x = nn.max_pool(x, (3, 3), (2, 2))
        x = nn.relu(nn.Conv(384, (3, 3), padding="SAME",
                            dtype=self.dtype, name="conv3")(x))
        x = nn.relu(nn.Conv(384, (3, 3), padding="SAME",
                            dtype=self.dtype, name="conv4")(x))
        x = nn.relu(nn.Conv(256, (3, 3), padding="SAME",
                            dtype=self.dtype, name="conv5")(x))
        x = nn.max_pool(x, (3, 3), (2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.dense_width, dtype=self.dtype,
                             name="fc1")(x))
        x = nn.relu(nn.Dense(self.dense_width, dtype=self.dtype,
                             name="fc2")(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x.astype(jnp.float32))


def vgg16(num_classes: int = 1000, dtype: Any = jnp.bfloat16) -> VGG:
    return VGG(num_classes=num_classes, dtype=dtype)


def vgg_test(num_classes: int = 10, dtype: Any = jnp.bfloat16) -> VGG:
    """3-stage narrow VGG for 32² CI inputs."""
    return VGG(stage_sizes=(1, 1, 1), widths=(8, 16, 32),
               num_classes=num_classes, dense_width=64, dtype=dtype)


def alexnet(num_classes: int = 1000, dtype: Any = jnp.bfloat16
            ) -> AlexNet:
    return AlexNet(num_classes=num_classes, dtype=dtype)


# bench_lr: no normalization layers anywhere in these nets — they
# diverge (NaN within ~15 steps, measured) at the BN-era sgd 0.1;
# 0.01 is their classic training rate.
register_model(ModelEntry(
    "vgg16", "vision", vgg16, ((224, 224, 3), "bfloat16"), 1000,
    bench_lr=0.01))
register_model(ModelEntry(
    "vgg-test", "vision", vgg_test, ((32, 32, 3), "bfloat16"), 10,
    bench_lr=0.01))
register_model(ModelEntry(
    "alexnet", "vision", alexnet, ((224, 224, 3), "bfloat16"), 1000,
    bench_lr=0.01))
