# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Inception-v3 in Flax, TPU-first.

The reference's serving demo model: its golden E2E test runs gRPC
Predict against an inception SavedModel and compares top-5
classes/scores textproto byte-for-byte
(``testing/test_tf_serving.py:104-108``, golden at
``components/k8s-model-server/images/test-worker/result.txt``). This
is the equivalent architecture for the TPU serving path — same input
contract (299×299×3) and head — built NHWC/bf16 like
:mod:`kubeflow_tpu.models.resnet` (weights are not ported; the golden
mechanism, not the 2015 checkpoint, is the parity surface).
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp

from kubeflow_tpu.models.registry import ModelEntry, register_model
from kubeflow_tpu.ops.batch_norm import GhostBatchNorm


class ConvBN(nn.Module):
    """conv → BN → relu (inception's BasicConv2d)."""

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"
    dtype: Any = jnp.bfloat16
    bn_stat_rows: int = 0  # ghost-BN stats cap; 0 = exact BN

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.Conv(
            self.features, self.kernel, self.strides,
            padding=self.padding, use_bias=False, dtype=self.dtype,
            name="conv",
        )(x)
        # GhostBatchNorm == nn.BatchNorm bit-for-bit at stat_rows=0
        # (same param/collection layout — tests/test_batch_norm.py);
        # stat_rows>0 is the BN-stat-HBM lever measured on resnet
        # (PERF.md), same single-chip caveats.
        x = GhostBatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-3,
            dtype=self.dtype, stat_rows=self.bn_stat_rows, name="bn",
        )(x)
        return nn.relu(x)


def _pool(x, kind: str):
    if kind == "max":
        return nn.max_pool(x, (3, 3), (1, 1), "SAME")
    return nn.avg_pool(x, (3, 3), (1, 1), "SAME")


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.bfloat16
    bn_stat_rows: int = 0

    @nn.compact
    def __call__(self, x, train: bool):
        conv = functools.partial(ConvBN, dtype=self.dtype,
                                 bn_stat_rows=self.bn_stat_rows)
        b1 = conv(64, (1, 1), name="b1x1")(x, train)
        b5 = conv(48, (1, 1), name="b5x5_1")(x, train)
        b5 = conv(64, (5, 5), name="b5x5_2")(b5, train)
        b3 = conv(64, (1, 1), name="b3x3dbl_1")(x, train)
        b3 = conv(96, (3, 3), name="b3x3dbl_2")(b3, train)
        b3 = conv(96, (3, 3), name="b3x3dbl_3")(b3, train)
        bp = conv(self.pool_features, (1, 1), name="bpool")(
            _pool(x, "avg"), train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    """Grid reduction 35→17."""

    dtype: Any = jnp.bfloat16
    bn_stat_rows: int = 0

    @nn.compact
    def __call__(self, x, train: bool):
        conv = functools.partial(ConvBN, dtype=self.dtype,
                                 bn_stat_rows=self.bn_stat_rows)
        b3 = conv(384, (3, 3), (2, 2), "VALID", name="b3x3")(x, train)
        bd = conv(64, (1, 1), name="b3x3dbl_1")(x, train)
        bd = conv(96, (3, 3), name="b3x3dbl_2")(bd, train)
        bd = conv(96, (3, 3), (2, 2), "VALID", name="b3x3dbl_3")(bd, train)
        bp = nn.max_pool(x, (3, 3), (2, 2), "VALID")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    """Factorized 7×7 branches."""

    c7: int
    dtype: Any = jnp.bfloat16
    bn_stat_rows: int = 0

    @nn.compact
    def __call__(self, x, train: bool):
        conv = functools.partial(ConvBN, dtype=self.dtype,
                                 bn_stat_rows=self.bn_stat_rows)
        c7 = self.c7
        b1 = conv(192, (1, 1), name="b1x1")(x, train)
        b7 = conv(c7, (1, 1), name="b7x7_1")(x, train)
        b7 = conv(c7, (1, 7), name="b7x7_2")(b7, train)
        b7 = conv(192, (7, 1), name="b7x7_3")(b7, train)
        bd = conv(c7, (1, 1), name="b7x7dbl_1")(x, train)
        bd = conv(c7, (7, 1), name="b7x7dbl_2")(bd, train)
        bd = conv(c7, (1, 7), name="b7x7dbl_3")(bd, train)
        bd = conv(c7, (7, 1), name="b7x7dbl_4")(bd, train)
        bd = conv(192, (1, 7), name="b7x7dbl_5")(bd, train)
        bp = conv(192, (1, 1), name="bpool")(_pool(x, "avg"), train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    """Grid reduction 17→8."""

    dtype: Any = jnp.bfloat16
    bn_stat_rows: int = 0

    @nn.compact
    def __call__(self, x, train: bool):
        conv = functools.partial(ConvBN, dtype=self.dtype,
                                 bn_stat_rows=self.bn_stat_rows)
        b3 = conv(192, (1, 1), name="b3x3_1")(x, train)
        b3 = conv(320, (3, 3), (2, 2), "VALID", name="b3x3_2")(b3, train)
        b7 = conv(192, (1, 1), name="b7x7x3_1")(x, train)
        b7 = conv(192, (1, 7), name="b7x7x3_2")(b7, train)
        b7 = conv(192, (7, 1), name="b7x7x3_3")(b7, train)
        b7 = conv(192, (3, 3), (2, 2), "VALID", name="b7x7x3_4")(b7, train)
        bp = nn.max_pool(x, (3, 3), (2, 2), "VALID")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    """Expanded-filter-bank output blocks."""

    dtype: Any = jnp.bfloat16
    bn_stat_rows: int = 0

    @nn.compact
    def __call__(self, x, train: bool):
        conv = functools.partial(ConvBN, dtype=self.dtype,
                                 bn_stat_rows=self.bn_stat_rows)
        b1 = conv(320, (1, 1), name="b1x1")(x, train)
        b3 = conv(384, (1, 1), name="b3x3_1")(x, train)
        b3 = jnp.concatenate([
            conv(384, (1, 3), name="b3x3_2a")(b3, train),
            conv(384, (3, 1), name="b3x3_2b")(b3, train),
        ], axis=-1)
        bd = conv(448, (1, 1), name="b3x3dbl_1")(x, train)
        bd = conv(384, (3, 3), name="b3x3dbl_2")(bd, train)
        bd = jnp.concatenate([
            conv(384, (1, 3), name="b3x3dbl_3a")(bd, train),
            conv(384, (3, 1), name="b3x3dbl_3b")(bd, train),
        ], axis=-1)
        bp = conv(192, (1, 1), name="bpool")(_pool(x, "avg"), train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    """Inception-v3 for NHWC image batches (299×299×3 canonical)."""

    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    bn_stat_rows: int = 0

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(ConvBN, dtype=self.dtype,
                                 bn_stat_rows=self.bn_stat_rows)
        x = x.astype(self.dtype)
        x = conv(32, (3, 3), (2, 2), "VALID", name="stem1")(x, train)
        x = conv(32, (3, 3), padding="VALID", name="stem2")(x, train)
        x = conv(64, (3, 3), name="stem3")(x, train)
        x = nn.max_pool(x, (3, 3), (2, 2), "VALID")
        x = conv(80, (1, 1), padding="VALID", name="stem4")(x, train)
        x = conv(192, (3, 3), padding="VALID", name="stem5")(x, train)
        x = nn.max_pool(x, (3, 3), (2, 2), "VALID")

        rows = self.bn_stat_rows
        for i, pool_features in enumerate((32, 64, 64)):
            x = InceptionA(pool_features, self.dtype, rows,
                           name=f"mixed5{'bcd'[i]}")(x, train)
        x = InceptionB(self.dtype, rows, name="mixed6a")(x, train)
        for i, c7 in enumerate((128, 160, 160, 192)):
            x = InceptionC(c7, self.dtype, rows,
                           name=f"mixed6{'bcde'[i]}")(x, train)
        x = InceptionD(self.dtype, rows, name="mixed7a")(x, train)
        x = InceptionE(self.dtype, rows, name="mixed7b")(x, train)
        x = InceptionE(self.dtype, rows, name="mixed7c")(x, train)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(
            x.astype(jnp.float32))
        return x


def inception_v3(num_classes: int = 1000, dtype: Any = jnp.bfloat16,
                 bn_stat_rows: int = 0) -> InceptionV3:
    return InceptionV3(num_classes=num_classes, dtype=dtype,
                       bn_stat_rows=bn_stat_rows)


register_model(ModelEntry(
    "inception-v3", "vision", inception_v3, ((299, 299, 3), "bfloat16"), 1000
))
