# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Vision Transformer (ViT) family, TPU-first.

Beyond-parity model family (the reference's vision set was
CNN-only — tf_cnn_benchmarks resnet/inception): a pre-LN ViT whose
encoder reuses the same TPU conventions as the BERT stack
(``models/bert.py``): bf16 activations with fp32 LayerNorm/head,
partitioning-annotated kernels so the tensor-parallel rule table
applies unchanged, the shared attention entry point (which routes
short token counts like 224²/p16's 196 to the XLA blockwise path —
the Pallas flash kernel needs block-divisible lengths and only
engages for longer/padded sequences), and zero data-dependent
control flow (static patch grid, unrolled depth).

TPU design notes:
- Patch embedding is a stride-p conv — one big MXU matmul of shape
  [B·(H/p)·(W/p), p²·C] × [p²·C, D]; no gather/reshape scatter.
- Mean-pool head (no CLS token): keeps every token's FLOPs useful
  and the sequence length a clean multiple of the 128-lane width
  (196 tokens for 224²/p16 pads poorly; mean-pool is insensitive).
- Pre-LN blocks (ViT standard), GELU MLP, learned 2D-flattened
  position embeddings.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from kubeflow_tpu.models.registry import ModelEntry, register_model
from kubeflow_tpu.ops.flash_attention import flash_attention

AttentionFn = Callable[..., jax.Array]


def _dense(features, axes, dtype, name=None, use_bias=True):
    return nn.Dense(
        features, dtype=dtype, use_bias=use_bias,
        kernel_init=nn.with_partitioning(
            nn.initializers.normal(0.02), axes),
        name=name,
    )


class ViTBlock(nn.Module):
    """Pre-LN transformer block: x + MHA(LN(x)); x + MLP(LN(x))."""

    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[AttentionFn] = None

    @nn.compact
    def __call__(self, x):
        d_model = x.shape[-1]
        head_dim = d_model // self.num_heads
        proj = functools.partial(_dense, dtype=self.dtype)

        h = nn.LayerNorm(dtype=self.dtype, name="ln_attn")(x)
        q = proj(d_model, ("embed", "heads"), name="query")(h)
        k = proj(d_model, ("embed", "heads"), name="key")(h)
        v = proj(d_model, ("embed", "heads"), name="value")(h)
        split = lambda t: t.reshape(  # noqa: E731
            t.shape[0], t.shape[1], self.num_heads, head_dim)
        attn = self.attention_fn or flash_attention
        out = attn(split(q), split(k), split(v))
        out = out.reshape(out.shape[0], out.shape[1], d_model)
        x = x + proj(d_model, ("heads", "embed"), name="out")(out)

        h = nn.LayerNorm(dtype=self.dtype, name="ln_mlp")(x)
        h = _dense(self.mlp_dim, ("embed", "mlp"), self.dtype,
                   "mlp_in")(h)
        h = nn.gelu(h, approximate=True)
        return x + _dense(d_model, ("mlp", "embed"), self.dtype,
                          "mlp_out")(h)


class ViT(nn.Module):
    """``__call__(images, train=...)`` → logits [B, num_classes].

    Images are NHWC with H, W divisible by ``patch``.
    """

    num_classes: int = 1000
    patch: int = 16
    num_layers: int = 12
    d_model: int = 768
    num_heads: int = 12
    mlp_dim: int = 3072
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[AttentionFn] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train  # no dropout in the benchmark config (BERT parity)
        b, h, w, _ = x.shape
        if h % self.patch or w % self.patch:
            raise ValueError(
                f"image {h}x{w} not divisible by patch {self.patch}")
        x = x.astype(self.dtype)
        # Stride-p conv patch embedding — lowers to one MXU matmul.
        x = nn.Conv(
            self.d_model, (self.patch, self.patch),
            strides=(self.patch, self.patch), padding="VALID",
            dtype=self.dtype,
            kernel_init=nn.with_partitioning(
                nn.initializers.normal(0.02),
                (None, None, None, "embed")),
            name="patch_embed",
        )(x)
        tokens = (h // self.patch) * (w // self.patch)
        x = x.reshape(b, tokens, self.d_model)
        pos = self.param(
            "pos_embed",
            nn.with_partitioning(nn.initializers.normal(0.02),
                                 (None, "embed")),
            (tokens, self.d_model))
        x = x + pos.astype(self.dtype)[None, :, :]

        for i in range(self.num_layers):
            x = ViTBlock(self.num_heads, self.mlp_dim, self.dtype,
                         self.attention_fn, name=f"layer_{i}")(x)

        x = nn.LayerNorm(dtype=self.dtype, name="ln_final")(x)
        x = jnp.mean(x, axis=1)  # mean-pool over tokens
        # Head in fp32 (numerically clean logits; resnet.py parity).
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        name="head")(x.astype(jnp.float32))


def vit_base16(**kw) -> ViT:
    return ViT(**kw)


def vit_large16(**kw) -> ViT:
    return ViT(num_layers=24, d_model=1024, num_heads=16,
               mlp_dim=4096, **kw)


def vit_test(**kw) -> ViT:
    """Tiny config for CI: 8x8 patches over 32x32 → 16 tokens."""
    kw.setdefault("num_classes", 10)
    return ViT(patch=8, num_layers=2, d_model=64, num_heads=4,
               mlp_dim=128, **kw)


register_model(ModelEntry(
    "vit-b16", "vision", vit_base16, ((224, 224, 3), "bfloat16"), 1000))
register_model(ModelEntry(
    "vit-l16", "vision", vit_large16, ((224, 224, 3), "bfloat16"), 1000))
register_model(ModelEntry(
    "vit-test", "vision", vit_test, ((32, 32, 3), "bfloat16"), 10))
