# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""E2E deploy test: apply kubeflow-core, wait for the control plane.

Reference: ``testing/test_deploy.py`` — create namespace (``:43-69``),
``ks generate core`` + apply (``:148-171``), wait for the
``tf-job-operator`` Deployment and ``tf-hub`` StatefulSet
(``:173-182``), teardown deletes the namespace (``:219-224``), all
wrapped in junit cases (``:231-248``).
"""

from __future__ import annotations

import argparse
import logging
import sys
import time
from typing import List

from kubeflow_tpu.manifests import k8s
from kubeflow_tpu.params.registry import get_prototype
from kubeflow_tpu.utils import junit

logger = logging.getLogger(__name__)

OPERATOR_DEPLOYMENT = "tpujob-operator"
HUB_STATEFULSET = "tpu-hub"
SERVING_NAME = "tpu-serving"


def make_client(fake: bool):
    if fake:
        from kubeflow_tpu.operator.fake import FakeApiServer

        return FakeApiServer()
    from kubeflow_tpu.operator.controller import KubectlClient

    return KubectlClient()


def core_objects(namespace: str) -> List[dict]:
    return get_prototype("kubeflow-core").build({"namespace": namespace})


def setup(api, namespace: str, *, fake: bool,
          timeout_s: float = 300.0) -> None:
    from kubeflow_tpu.operator.fake import Conflict, NotFound

    try:
        api.get("Namespace", "", namespace)
    except (NotFound, RuntimeError):
        api.create(k8s.namespace_obj(namespace))
    for obj in core_objects(namespace):
        try:
            api.create(obj)
        except Conflict:  # already exists on a re-run
            pass
        except RuntimeError as e:  # pre-taxonomy kubectl surface
            if "AlreadyExists" not in str(e):
                raise
    deadline = time.monotonic() + (0 if fake else timeout_s)
    while True:
        try:
            deploy = api.get("Deployment", namespace, OPERATOR_DEPLOYMENT)
            hub = api.get("StatefulSet", namespace, HUB_STATEFULSET)
            if fake:
                break  # fake apiserver has no kubelet; existence is ready
            if (deploy.get("status", {}).get("readyReplicas", 0) >= 1
                    and hub.get("status", {}).get("readyReplicas", 0) >= 1):
                break
        except NotFound:
            pass
        if time.monotonic() > deadline:
            raise AssertionError(
                f"control plane not ready in {timeout_s}s")
        time.sleep(5)
    logger.info("control plane ready in %s", namespace)


def deploy_serving(api, namespace: str, *, fake: bool,
                   model_path: str = "gs://kubeflow-tpu-models/resnet",
                   timeout_s: float = 300.0) -> None:
    """Apply the tpu-serving prototype and wait for the server to come
    up — kubeflow-core alone never creates the serving Service the
    serving e2e targets (reference ``test_deploy.py deploy_model``,
    ``:184-217``)."""
    from kubeflow_tpu.operator.fake import Conflict, NotFound

    objs = get_prototype("tpu-serving").build({
        "name": SERVING_NAME, "namespace": namespace,
        "model_path": model_path,
        # The serving e2e queries /v1/models/resnet; without this the
        # server would default model_name to the component name.
        "model_name": "resnet",
    })
    for obj in objs:
        try:
            api.create(obj)
        except Conflict:
            pass
        except RuntimeError as e:
            if "AlreadyExists" not in str(e):
                raise
    deadline = time.monotonic() + (0 if fake else timeout_s)
    while True:
        try:
            deploy = api.get("Deployment", namespace, SERVING_NAME)
            if fake or deploy.get("status", {}).get("readyReplicas", 0) >= 1:
                break
        except NotFound:
            pass
        if time.monotonic() > deadline:
            raise AssertionError(f"serving not ready in {timeout_s}s")
        time.sleep(5)
    logger.info("serving %s ready in %s", SERVING_NAME, namespace)


def teardown(api, namespace: str) -> None:
    api.delete("Namespace", "", namespace)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kft-e2e-deploy")
    parser.add_argument("command",
                        choices=["setup", "deploy-serving", "teardown"])
    parser.add_argument("--namespace", default="kubeflow-e2e")
    parser.add_argument("--junit_path", default=None)
    parser.add_argument("--fake", action="store_true")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    api = make_client(args.fake)
    if args.command == "setup":
        case = junit.run_case(
            "deploy-kubeflow-core",
            lambda: setup(api, args.namespace, fake=args.fake))
    elif args.command == "deploy-serving":
        case = junit.run_case(
            "deploy-serving",
            lambda: deploy_serving(api, args.namespace, fake=args.fake))
    else:
        case = junit.run_case(
            "teardown", lambda: teardown(api, args.namespace))
    if args.junit_path:
        junit.write_report(args.junit_path, "e2e-deploy", [case])
    if not case.ok:
        print(case.failure or case.error, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
