# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""E2E dashboard test: boot the tpujob-dashboard process, assert the
UI and API respond (junit-reported, like every citest tier).

Fake mode runs the server with its in-memory apiserver — the hermetic
equivalent of checking the reference's TFJob UI Deployment
(tf-job.libsonnet:271-458) came up behind Ambassador. Real mode
targets the in-cluster Service.
"""

from __future__ import annotations

import argparse
import json
import logging
import subprocess
import sys
import time
import urllib.error
import urllib.request

from kubeflow_tpu.utils import junit

logger = logging.getLogger(__name__)


def check_dashboard(base_url: str, *, retries: int = 30,
                    retry_delay_s: float = 5.0) -> None:
    # Nothing upstream waits for the dashboard Deployment to become
    # ready (deploy setup waits on operator + hub only), so real-mode
    # runs retry through pod startup instead of racing it.
    last: Exception = RuntimeError("no attempt")
    for attempt in range(retries):
        try:
            with urllib.request.urlopen(f"{base_url}/healthz",
                                        timeout=5) as r:
                assert r.status == 200
            break
        except OSError as e:
            last = e
            logger.info("dashboard not up yet (attempt %d): %s",
                        attempt + 1, e)
            time.sleep(retry_delay_s)
    else:
        raise last
    with urllib.request.urlopen(f"{base_url}/tpujobs/api/tpujob",
                                timeout=10) as r:
        payload = json.load(r)
        assert "items" in payload, payload
    with urllib.request.urlopen(f"{base_url}/tpujobs/ui/", timeout=10) as r:
        page = r.read().decode()
        assert "TPUJobs" in page
        assert "/tpujobs/ui/create" in page  # the create form is served
    logger.info("dashboard ok: %d job(s) listed", len(payload["items"]))


def check_write_path(base_url: str) -> None:
    """Create → read back → delete, over the wire (the reference UI's
    job lifecycle, tf-job.libsonnet:271-458)."""
    from kubeflow_tpu.manifests.tpujob import replica_spec, tpu_job

    job = tpu_job(
        "citest-created", "default",
        [replica_spec("TPU_WORKER", 2,
                      image="ghcr.io/kubeflow-tpu/trainer:v0.1.0",
                      tpu_accelerator="tpu-v5-lite-podslice",
                      tpu_topology="2x4")],
        termination={"chief": {"replicaName": "TPU_WORKER",
                               "replicaIndex": 0}})
    req = urllib.request.Request(
        f"{base_url}/tpujobs/api/tpujob", data=json.dumps(job).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 201, r.status
    with urllib.request.urlopen(
            f"{base_url}/tpujobs/api/tpujob/default/citest-created",
            timeout=10) as r:
        detail = json.load(r)
        assert detail["summary"]["name"] == "citest-created"
        assert "pods" in detail and "conditions" in detail
    # Per-pod drill-down UI + log proxy routes (VERDICT-r4 #8): the
    # detail page renders, and the log endpoint enforces the
    # job-membership contract (404 for a pod not in the gang).
    with urllib.request.urlopen(
            f"{base_url}/tpujobs/ui/job/default/citest-created",
            timeout=10) as r:
        page = r.read().decode()
        assert "Replicas" in page and "Conditions" in page
    try:
        urllib.request.urlopen(
            f"{base_url}/tpujobs/api/tpujob/default/citest-created"
            f"/logs/ghost-pod", timeout=10)
        raise AssertionError("log proxy served a pod outside the gang")
    except urllib.error.HTTPError as e:
        assert e.code == 404, e.code
    req = urllib.request.Request(
        f"{base_url}/tpujobs/api/tpujob/default/citest-created",
        method="DELETE")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 200
    logger.info("dashboard write path ok: create → get → delete")


def run_fake(port: int = 19402) -> None:
    proc = subprocess.Popen(
        [sys.executable, "-m", "kubeflow_tpu.dashboard.server",
         "--port", str(port), "--fake"])
    try:
        for _ in range(30):
            time.sleep(0.5)
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=2)
                break
            except OSError:
                pass
        else:
            raise AssertionError("dashboard never became healthy")
        check_dashboard(f"http://127.0.0.1:{port}", retries=3,
                        retry_delay_s=1.0)
        check_write_path(f"http://127.0.0.1:{port}")
    finally:
        proc.kill()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kft-e2e-dashboard")
    parser.add_argument("--namespace", default="kubeflow-e2e")
    parser.add_argument("--service", default="tpujob-dashboard")
    parser.add_argument("--junit_path", default=None)
    parser.add_argument("--fake", action="store_true")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.fake:
        fn = run_fake
    else:
        url = f"http://{args.service}.{args.namespace}.svc.cluster.local:80"
        fn = lambda: check_dashboard(url)  # noqa: E731
    case = junit.run_case("dashboard-ui", fn)
    if args.junit_path:
        junit.write_report(args.junit_path, "e2e-dashboard", [case])
    if not case.ok:
        print(case.failure or case.error, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
