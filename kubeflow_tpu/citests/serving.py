# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""E2E serving test: Predict against the model server, golden compare.

Reference: ``testing/test_tf_serving.py`` — in-cluster gRPC Predict
with a fixed JPEG, 3 retries (``:90-102``), golden-file equality
(``:104-108``), junit output. Here: REST predict with a fixed seeded
input PLUS the native-gRPC PredictionService verbs (Predict, Classify,
GetModelMetadata) through a real grpc channel; in ``--fake`` mode a
local server process on an exported deterministic model stands in for
the cluster service.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import time
import urllib.error
import urllib.request

import numpy as np

from kubeflow_tpu.utils import junit

logger = logging.getLogger(__name__)

RETRIES = 3


def predict(url: str, payload: dict, timeout_s: float = 30.0) -> dict:
    last: Exception = RuntimeError("no attempt")
    for attempt in range(RETRIES):
        try:
            req = urllib.request.Request(
                url, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                return json.load(resp)
        except (urllib.error.URLError, OSError) as e:
            last = e
            logger.warning("predict attempt %d failed: %s", attempt + 1, e)
            time.sleep(5)
    raise last


def golden_check(base_url: str, model_name: str) -> None:
    rng = np.random.RandomState(42)
    image = (rng.randint(0, 256, (1, 32, 32, 3)) / 255.0).astype(np.float32)
    resp = predict(f"{base_url}/v1/models/{model_name}:classify",
                   {"instances": image.tolist()})
    preds = resp["predictions"]
    assert len(preds) == 1 and "classes" in preds[0] and "scores" in preds[0]
    scores = np.asarray(preds[0]["scores"], np.float64)
    assert np.all(np.diff(scores) <= 1e-9), "scores must be sorted desc"
    assert abs(scores.sum()) <= 1.0 + 1e-6
    logger.info("golden predict ok: top classes %s", preds[0]["classes"])


def grpc_check(address: str, model_name: str) -> None:
    """Drive the native gRPC surface — the reference's actual serving
    contract (tf-serving.libsonnet:106-111) — through a real channel:
    GetModelMetadata (the proxy's bootstrap call), Predict, Classify."""
    import numpy as np

    from kubeflow_tpu.serving import client

    signatures = client.grpc_get_metadata(address, model_name)
    assert "serving_default" in signatures, signatures
    sig = signatures["serving_default"]
    assert sig["inputs"], "GetModelMetadata returned no input tensors"
    logger.info("grpc GetModelMetadata ok: %s", sorted(signatures))

    rng = np.random.RandomState(42)
    image = (rng.randint(0, 256, (1, 32, 32, 3)) / 255.0).astype(np.float32)
    input_name = next(iter(sig["inputs"]))
    outputs = client.grpc_predict(address, model_name, {input_name: image})
    assert outputs, "grpc Predict returned no outputs"
    logger.info("grpc Predict ok: outputs %s", sorted(outputs))

    rows = client.grpc_classify(
        address, model_name, [{input_name: image.reshape(-1)}])
    assert len(rows) == 1 and rows[0], rows
    scores = [score for _, score in rows[0]]
    assert all(b <= a + 1e-9 for a, b in zip(scores, scores[1:])), \
        "classify scores must be sorted desc"
    logger.info("grpc Classify ok: top label %s", rows[0][0][0])


def _served_versions(base_url: str, model_name: str) -> list:
    with urllib.request.urlopen(f"{base_url}/v1/models/{model_name}",
                                timeout=5) as resp:
        status = json.load(resp)
    return sorted(int(s["version"])
                  for s in status["model_version_status"])


def _wait_for_version(base_url: str, model_name: str, version: int,
                      timeout_s: float = 120.0) -> None:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        try:
            if version in _served_versions(base_url, model_name):
                return
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(1)
    raise AssertionError(
        f"version {version} never became AVAILABLE on {model_name}")


def rollback_check(base: "pathlib.Path", base_url: str,
                   model_name: str) -> None:
    """Publish v2/v3, pin v1 through eviction (load-on-demand), against
    the live server — the version-policy data path over the wire
    (reference version-dir contract,
    components/k8s-model-server/README.md:95-105)."""
    import shutil

    rng = np.random.RandomState(42)
    image = (rng.randint(0, 256, (1, 32, 32, 3)) / 255.0).astype(np.float32)
    pin1 = f"{base_url}/v1/models/{model_name}/versions/1:classify"

    # Publish v2 (identical weights; the lifecycle is what's under
    # test); the 1 s poll hot-loads it.
    shutil.copytree(str(base / "1"), str(base / "2"))
    _wait_for_version(base_url, model_name, 2)
    resp = predict(pin1, {"instances": image.tolist()})
    assert resp["model_spec"]["version"] == "1", resp.get("model_spec")
    logger.info("pinned v1 ok while v2 is default")

    # Publish v3: the latest-policy reload evicts v1 ({3,2} stay)...
    shutil.copytree(str(base / "1"), str(base / "3"))
    _wait_for_version(base_url, model_name, 3)
    served = _served_versions(base_url, model_name)
    assert 1 not in served, f"v1 should be evicted, got {served}"
    # ...but pinned-v1 traffic (rollback clients) still works: the
    # server loads it back on demand.
    resp = predict(pin1, {"instances": image.tolist()}, timeout_s=120.0)
    assert resp["model_spec"]["version"] == "1", resp.get("model_spec")
    assert 1 in _served_versions(base_url, model_name)
    logger.info("load-on-demand rollback target ok (v1 after eviction)")


def pinned_policy_check(base_url: str, model_name: str) -> None:
    """Against a server booted with --version_policy specific:1 while
    v1..v3 sit on disk: v1 is the default serve, unpinned versions are
    rejected — the operator's rollback flow."""
    rng = np.random.RandomState(42)
    image = (rng.randint(0, 256, (1, 32, 32, 3)) / 255.0).astype(np.float32)
    served = _served_versions(base_url, model_name)
    assert served == [1], f"specific:1 must serve exactly [1], got {served}"
    resp = predict(f"{base_url}/v1/models/{model_name}:classify",
                   {"instances": image.tolist()})
    assert resp["model_spec"]["version"] == "1", resp.get("model_spec")
    req = urllib.request.Request(
        f"{base_url}/v1/models/{model_name}/versions/3:classify",
        data=json.dumps({"instances": image.tolist()}).encode(),
        headers={"Content-Type": "application/json"})
    try:
        urllib.request.urlopen(req, timeout=30)
        raise AssertionError("unpinned version 3 must be rejected")
    except urllib.error.HTTPError as e:
        assert e.code == 404, e.code
    logger.info("rollback policy ok (specific:1 serves v1, rejects v3)")


def run_fake() -> None:
    """Local stand-in: export a deterministic model, boot the real
    server binary, golden-predict against it over REST and native
    gRPC."""
    import os
    import pathlib
    import subprocess
    import tempfile

    import jax

    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.serving.export import export_model
    from kubeflow_tpu.serving.signature import (
        ModelMetadata,
        Signature,
        TensorSpec,
    )

    base = pathlib.Path(tempfile.mkdtemp()) / "resnet"
    meta = ModelMetadata(
        model_name="resnet", registry_name="resnet-test",
        model_kwargs={"num_classes": 10, "dtype": "float32"},
        signatures={"serving_default": Signature(
            method="classify",
            inputs={"images": TensorSpec("float32", (-1, 32, 32, 3))},
            outputs={"classes": TensorSpec("int32", (-1, 5)),
                     "scores": TensorSpec("float32", (-1, 5))})})
    module = get_model("resnet-test").make(num_classes=10, dtype="float32")
    variables = module.init(
        jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32),
        train=False)
    export_model(str(base), 1, meta, variables)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    grpc_port, rest_port = 19300, 19301
    base_url = f"http://127.0.0.1:{rest_port}"

    def boot(*extra_args):
        return subprocess.Popen(
            [sys.executable, "-m", "kubeflow_tpu.serving.server",
             "--port", str(grpc_port), "--rest_port", str(rest_port),
             "--model_name", "resnet",
             "--model_base_path", str(base), "--poll_interval", "1",
             # Small bucket set: load-time warmup compiles every bucket.
             "--max_batch", "4", *extra_args],
            env=env)

    def wait_healthy():
        for _ in range(120):
            try:
                if urllib.request.urlopen(f"{base_url}/healthz",
                                          timeout=1).status == 200:
                    return
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(1)
        raise AssertionError("local model server never became healthy")

    def drain(proc):
        # Graceful shutdown: SIGTERM (what the kubelet sends) must
        # drain and exit 0 within the grace period, not require KILL.
        import signal

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
        assert rc == 0, f"server exited {rc} on SIGTERM"

    proc = boot()
    try:
        wait_healthy()
        golden_check(base_url, "resnet")
        grpc_check(f"127.0.0.1:{grpc_port}", "resnet")
        rollback_check(base, base_url, "resnet")
        drain(proc)
        logger.info("graceful shutdown ok (exit 0 on SIGTERM)")
    finally:
        proc.kill()

    # Operator rollback: reboot the same base path pinned to v1 while
    # v1..v3 (plus v1's on-demand reload) sit on disk.
    proc = boot("--version_policy", "specific:1")
    try:
        wait_healthy()
        pinned_policy_check(base_url, "resnet")
        drain(proc)
    finally:
        proc.kill()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kft-e2e-serving")
    parser.add_argument("--namespace", default="kubeflow-e2e")
    parser.add_argument("--service", default="tpu-serving")
    parser.add_argument("--model_name", default="resnet")
    parser.add_argument("--junit_path", default=None)
    parser.add_argument("--fake", action="store_true")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.fake:
        fn = run_fake
    else:
        host = f"{args.service}.{args.namespace}.svc.cluster.local"

        def fn() -> None:
            golden_check(f"http://{host}:8500", args.model_name)
            grpc_check(f"{host}:9000", args.model_name)
    case = junit.run_case("serving-predict", fn)
    if args.junit_path:
        junit.write_report(args.junit_path, "e2e-serving", [case])
    if not case.ok:
        print(case.failure or case.error, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
