"""CI E2E test drivers (the reference's ``testing/*.py`` tier).

Each module is an Argo-step entrypoint (see manifests/ci.py) that
emits junit XML. All drivers take ``--fake`` to run against the
in-process fake apiserver / a local server — the hermetic tier the
reference never had (SURVEY §4: its distributed tests required a live
GKE cluster).
"""
