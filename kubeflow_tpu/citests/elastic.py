# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Elastic-kill citest (r16): kill 1 of 4 gang hosts mid-run; the job
must RESIZE instead of die and converge to the same seeded loss curve.

Two halves, both hermetic:

- **Control plane** (``elastic-resize``): a 4-worker elastic TPUJob
  (minReplicas=2) on the fake apiserver loses one drained worker; the
  reconciler must keep the job Running (no restart-budget burn, the
  Restarting phase never materializes), roll the gang to 3 workers
  with fresh env, and never create a duplicate pod (every pod CREATE
  attempt lands exactly once — asserted from the apiserver request
  log).

- **Data plane** (``elastic-training``): a seeded llama-test causal-LM
  run with continuous sharded checkpointing (4 emulated hosts, shard
  write every step). The run is killed after step 5 — state discarded,
  like a lost host — and resumed on a SMALLER 3-device dp mesh by
  restoring + resharding from the continuous shards. The resumed run
  must lose < 2 steps and converge to the uninterrupted reference loss
  curve (same global batch ⇒ same math; cross-mesh reduce reassociation
  bounded by a documented tolerance).

Wired into the e2e CI DAG as the ``elastic-kill-test`` step
(manifests/ci.py) and driven by tests/test_ci.py.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import tempfile

from kubeflow_tpu.utils import junit

logger = logging.getLogger(__name__)

# The data-plane half shards a dp mesh over 4 virtual CPU devices;
# must land before the first jax import in this process.
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

WORKERS = 4
MIN_REPLICAS = 2
KILL_AFTER_STEP = 5
TOTAL_STEPS = 10
GLOBAL_BATCH = 12
SEQ_LEN = 16
# Cross-mesh tolerance: restoring onto a different dp factorization
# reassociates the gradient all-reduce, so the curves match to float32
# reduction noise, not bitwise (same-mesh restores ARE bitwise — see
# tests/test_checkpoint_sharded.py).
LOSS_RTOL = 5e-4


def control_plane_case() -> None:
    from kubeflow_tpu.manifests.tpujob import (
        replica_spec,
        termination_policy,
        tpu_job,
    )
    from kubeflow_tpu.operator.fake import FakeApiServer
    from kubeflow_tpu.operator.reconciler import (
        JOB_LABEL,
        RESIZED_CONDITION,
        Reconciler,
    )
    from kubeflow_tpu.training.launcher import DRAIN_EXIT_CODE

    api = FakeApiServer()
    spec = replica_spec(
        "TPU_WORKER", WORKERS, image="citest:img",
        tpu_accelerator="tpu-v5-lite-podslice", tpu_topology="1x1",
        chips_per_worker=1)
    job = tpu_job("elastic-kill", "default", [spec],
                  termination=termination_policy("TPU_WORKER", 0),
                  min_replicas=MIN_REPLICAS, max_replicas=WORKERS)
    job["metadata"]["uid"] = "uid-elastic-kill"
    api.create(job)

    rec = Reconciler(api)

    def reconcile():
        return rec.reconcile(api.get("TPUJob", "default",
                                     "elastic-kill"))

    reconcile()
    pods = api.list("Pod", "default", {JOB_LABEL: "elastic-kill"})
    assert len(pods) == WORKERS, len(pods)
    api.set_all_pod_phases("default", "Running")
    assert reconcile() == "Running"

    # Spot-kill one host mid-run (drain exit: finished its step,
    # checkpointed, exited 77).
    victim = sorted(p["metadata"]["name"] for p in pods)[2]
    api.set_pod_terminated("default", victim, DRAIN_EXIT_CODE)

    # The resize roll: begin (teardown) → hold → recreate → settle.
    for _ in range(6):
        phase = reconcile()
        assert phase == "Running", f"job left Running: {phase!r}"
        pods = api.list("Pod", "default", {JOB_LABEL: "elastic-kill"})
        if len(pods) == WORKERS - 1:
            api.set_all_pod_phases("default", "Running")
    phase = reconcile()

    status = api.get("TPUJob", "default", "elastic-kill")["status"]
    conds = {c["type"]: c["status"] for c in status["conditions"]}
    assert phase == "Running", phase
    assert int(status.get("restartCount", 0)) == 0, status
    assert int(status.get("currentReplicas", 0)) == WORKERS - 1, status
    assert conds.get(RESIZED_CONDITION) == "True", conds
    # The job never even ENTERED Restarting — the phase condition was
    # never materialized.
    assert "Restarting" not in conds, conds

    pods = api.list("Pod", "default", {JOB_LABEL: "elastic-kill"})
    names = sorted(p["metadata"]["name"] for p in pods)
    assert len(names) == len(set(names)) == WORKERS - 1, names
    # Zero duplicate pods across the whole episode: every pod CREATE
    # the controller attempted landed exactly once (4 at birth +
    # 3 on the resize roll; a duplicate attempt would show as an
    # extra create in the request log, Conflict-swallowed or not).
    creates = api.request_count(verb="create", kind="Pod")
    assert creates == WORKERS + (WORKERS - 1), creates
    # The rolled gang's env reflects the new world size.
    for pod in pods:
        env = {e["name"]: str(e.get("value"))
               for e in pod["spec"]["containers"][0]["env"]}
        assert env["KFT_NUM_PROCESSES"] == str(WORKERS - 1), env


def training_resume_case() -> None:
    import jax
    import optax

    from kubeflow_tpu.models.llama import llama_test
    from kubeflow_tpu.parallel.mesh import (
        MeshSpec,
        build_mesh,
        respec_for_devices,
    )
    from kubeflow_tpu.training.checkpoint import (
        ContinuousCheckpointConfig,
        ShardedCheckpointer,
    )
    from kubeflow_tpu.training.data import synthetic_causal_lm
    from kubeflow_tpu.training.lm import (
        create_lm_state,
        make_lm_train_step,
        place_lm_batch,
    )

    devices = jax.devices()
    assert len(devices) >= WORKERS, (
        f"need >= {WORKERS} virtual devices, got {len(devices)} "
        f"(XLA_FLAGS must land before jax imports)")

    model = llama_test()
    vocab = 512

    def batches():
        return synthetic_causal_lm(GLOBAL_BATCH, SEQ_LEN, vocab, seed=7)

    def build(mesh):
        gen = batches()
        sample = next(gen)
        state, shardings = create_lm_state(
            model, optax.adamw(1e-3), jax.random.PRNGKey(3), sample,
            mesh)
        step_fn = make_lm_train_step(mesh, shardings,
                                     objective="causal", donate=False)
        return state, step_fn, gen, sample

    def run(mesh, state, step_fn, gen, first_batch, start, stop,
            checkpointers=()):
        losses = {}
        batch = first_batch
        consumed = 0
        # Deterministic stream: batch k feeds step k+1.
        while consumed < start:
            batch = next(gen)
            consumed += 1
        for step in range(start, stop):
            placed = place_lm_batch(mesh, batch)
            state, metrics = step_fn(state, placed)
            losses[step + 1] = float(metrics["loss"])
            for ckpt in checkpointers:
                ckpt.save(step + 1, state, force=True)
            if step + 1 < stop:
                batch = next(gen)
        return state, losses

    # Reference: uninterrupted seeded run on the 4-device dp mesh.
    mesh4 = build_mesh(MeshSpec(data=WORKERS), devices[:WORKERS])
    state, step_fn, gen, sample = build(mesh4)
    _, ref_losses = run(mesh4, state, step_fn, gen, sample, 0,
                        TOTAL_STEPS)

    # Elastic run: continuous sharded checkpoints from 4 emulated
    # hosts (one checkpointer per host over one directory — the
    # manifest commits only after every host's shard lands).
    ckpt_dir = tempfile.mkdtemp(prefix="kft-elastic-")
    checkpointers = [
        ShardedCheckpointer(ContinuousCheckpointConfig(
            directory=ckpt_dir, save_interval_steps=1,
            num_hosts=WORKERS, host_id=h, min_shard_size=64,
            mesh_shape={"data": WORKERS}))
        for h in range(WORKERS)]
    state, step_fn, gen, sample = build(mesh4)
    _, pre_losses = run(mesh4, state, step_fn, gen, sample, 0,
                        KILL_AFTER_STEP, checkpointers=checkpointers)
    for ckpt in checkpointers:
        assert ckpt.wait(30.0), "shard writes never became durable"
        ckpt.close()
    del state  # the "kill": host 3 is gone, in-memory state lost

    # Resume on the SURVIVING 3 hosts: rebuild the mesh at the new
    # device count, restore + reshard from the continuous shards.
    new_spec = respec_for_devices(MeshSpec(data=WORKERS), WORKERS - 1)
    mesh3 = build_mesh(new_spec, devices[:WORKERS - 1])
    fresh, step_fn3, gen3, sample3 = build(mesh3)
    reader = ShardedCheckpointer(ContinuousCheckpointConfig(
        directory=ckpt_dir, num_hosts=1, host_id=0))
    restored_step = reader.latest_step()
    assert restored_step is not None
    lost = KILL_AFTER_STEP - restored_step
    assert 0 <= lost < 2, (
        f"lost {lost} steps (kill at {KILL_AFTER_STEP}, restored "
        f"{restored_step}) — acceptance is < 2")
    resumed = reader.restore(fresh)
    reader.close()
    assert int(resumed.step) == restored_step

    _, post_losses = run(mesh3, resumed, step_fn3, gen3, sample3,
                         restored_step, TOTAL_STEPS)

    # The resumed curve matches the uninterrupted reference within
    # the documented cross-mesh tolerance.
    for step in sorted(post_losses):
        ref = ref_losses[step]
        got = post_losses[step]
        assert abs(got - ref) <= LOSS_RTOL * max(1.0, abs(ref)), (
            f"step {step}: resumed loss {got} vs reference {ref}")
    # And the pre-kill prefix was bitwise-identical (same mesh).
    for step in sorted(pre_losses):
        assert pre_losses[step] == ref_losses[step], (
            step, pre_losses[step], ref_losses[step])


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kft-e2e-elastic")
    parser.add_argument("--junit_path", default=None)
    parser.add_argument("--fake", action="store_true",
                        help="hermetic mode (the only mode: both "
                             "halves are cluster-free by design)")
    parser.add_argument("--skip_training", action="store_true",
                        help="control-plane half only (no jax)")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    cases = [junit.run_case("elastic-resize", control_plane_case)]
    if not args.skip_training:
        cases.append(junit.run_case("elastic-training",
                                    training_resume_case))
    if args.junit_path:
        junit.write_report(args.junit_path, "e2e-elastic", cases)
    failed = [c for c in cases if not c.ok]
    for case in failed:
        print(case.failure or case.error, file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
