# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Leader-failover-mid-restart citest (the last open VERDICT-r5 item).

The nastiest handover window: leader A has torn a faulted gang down
(phase ``Restarting``, zero pods on the cluster) and CRASHES before
recreating it — no clean lease release, no final status write. The
standby B must win the lease after expiry, resync its informer caches
from the apiserver (a fresh leader must never trust a cache that may
predate the dead leader's last writes), and finish the restart:
exactly one gang's worth of pods, never a duplicate, restart budget
counted once.

Hermetic by construction — the crash is simulated by severing A's
lease client and halting its threads, so the lease stays held until
it expires, exactly like a SIGKILLed pod. Wired into the e2e CI DAG
as the ``leader-failover-test`` step (manifests/ci.py).
"""

from __future__ import annotations

import argparse
import logging
import sys
import threading
import time

from kubeflow_tpu.manifests.tpujob import (
    KIND,
    replica_spec,
    termination_policy,
    tpu_job,
)
from kubeflow_tpu.operator.controller import WatchController
from kubeflow_tpu.operator.fake import FakeApiServer, ServerError
from kubeflow_tpu.operator.leader import LeaderElector
from kubeflow_tpu.operator.reconciler import JOB_LABEL
from kubeflow_tpu.operator.workqueue import ExponentialBackoff
from kubeflow_tpu.utils import junit

logger = logging.getLogger(__name__)

JOB = "lf-restart"
WORKERS = 2
LEASE_SECONDS = 1.0


class _SeveredClient:
    """Stands in for a crashed process's apiserver connection: every
    call fails, so the dying elector can neither renew NOR release —
    the lease must expire on its own, like a SIGKILL."""

    def __getattr__(self, name):
        def dead(*args, **kwargs):
            raise ServerError("connection severed (simulated crash)")

        return dead


def _wait_for(predicate, timeout: float, interval: float = 0.02) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def _controller(api, identity: str) -> tuple:
    elector = LeaderElector(api, identity=identity,
                            lease_seconds=LEASE_SECONDS)
    ctl = WatchController(
        api, relist_seconds=0.3, workers=2, elector=elector,
        backoff=ExponentialBackoff(base=0.02, cap=0.5))
    thread = threading.Thread(target=ctl.run, daemon=True,
                              name=f"ctl-{identity}")
    thread.start()
    return ctl, elector, thread


def _pods(api):
    with api.as_kubelet():
        return api._list("Pod", "default", {JOB_LABEL: JOB})


def _phase(api) -> str:
    with api.as_kubelet():
        return api.get(KIND, "default", JOB).get(
            "status", {}).get("phase", "")


def run_failover_scenario() -> None:
    api = FakeApiServer()
    ctl_a, elector_a, thread_a = _controller(api, "operator-a")
    ctl_b, elector_b, thread_b = _controller(api, "operator-b")
    try:
        assert _wait_for(elector_a.is_leader, 5.0), \
            "first controller never took the lease"
        assert not elector_b.is_leader()

        # A healthy running gang.
        spec = replica_spec(
            "TPU_WORKER", WORKERS, image="img:1",
            tpu_accelerator="tpu-v5-lite-podslice", tpu_topology="2x4")
        job = tpu_job(JOB, "default", [spec],
                      termination=termination_policy("TPU_WORKER", 0))
        job["metadata"]["uid"] = "uid-lf"
        with api.as_kubelet():
            api.create(job)
        assert _wait_for(lambda: len(_pods(api)) == WORKERS, 5.0), \
            "gang never created"
        with api.as_kubelet():
            api.set_all_pod_phases("default", "Running",
                                   {JOB_LABEL: JOB})
        assert _wait_for(lambda: _phase(api) == "Running", 5.0)

        # Wedge recreation, then fault a pod: A tears the gang down
        # (Restarting, zero pods) and stalls exactly mid-restart.
        block = api.faults.add_rule(
            lambda: ServerError("create blocked (mid-restart window)"),
            verbs=("create",), kind="Pod", name=f"^{JOB}-")
        with api.as_kubelet():
            api.set_pod_phase("default", f"{JOB}-tpu-worker-1",
                              "Failed")
        assert _wait_for(
            lambda: _phase(api) == "Restarting" and not _pods(api),
            5.0), "leader never reached the mid-restart window"

        # CRASH the leader: sever its lease client (renewal and the
        # shutdown release both fail → the lease stays held until it
        # expires) and halt its loops.
        relists_before = ctl_b.informers[KIND].relists
        elector_a.api = _SeveredClient()
        ctl_a.stop.set()
        block.times = block.fired  # the cluster heals as A dies

        # B must win the expired lease and finish the restart — and
        # never create a duplicate: the pod count may only climb to
        # the gang size, exactly once.
        assert _wait_for(elector_b.is_leader,
                         LEASE_SECONDS * 4 + 5.0), \
            "standby never took over the expired lease"
        max_pods = 0
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            count = len(_pods(api))
            max_pods = max(max_pods, count)
            assert count <= WORKERS, \
                f"duplicate pods after failover: {count} > {WORKERS}"
            if count == WORKERS:
                break
            time.sleep(0.02)
        assert max_pods == WORKERS, "new leader never finished the restart"

        # Fresh leadership forced an informer resync from the server
        # (the loop notices the request within one watch timeout).
        assert _wait_for(
            lambda: ctl_b.informers[KIND].relists > relists_before,
            5.0), "new leader never resynced its informers"

        # And the restarted gang converges under the new leader.
        with api.as_kubelet():
            api.set_all_pod_phases("default", "Running",
                                   {JOB_LABEL: JOB})
        assert _wait_for(lambda: _phase(api) == "Running", 5.0)
        with api.as_kubelet():
            status = api.get(KIND, "default", JOB)["status"]
        assert int(status.get("restartCount", 0)) == 1, status
        names = sorted(p["metadata"]["name"] for p in _pods(api))
        assert names == sorted(
            f"{JOB}-tpu-worker-{i}" for i in range(WORKERS)), names
    finally:
        ctl_a.stop.set()
        ctl_b.stop.set()
        thread_a.join(timeout=10)
        thread_b.join(timeout=10)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kft-e2e-leader-failover")
    parser.add_argument("--junit_path", default=None)
    parser.add_argument("--fake", action="store_true",
                        help="accepted for DAG-step symmetry; this "
                             "citest is hermetic by construction")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    case = junit.run_case("leader-failover-mid-restart",
                          run_failover_scenario)
    if args.junit_path:
        junit.write_report(args.junit_path, "e2e-leader-failover",
                           [case])
    if not case.ok:
        print(case.failure or case.error, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
