# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""CI artifact plumbing: PR symlink + junit/log upload.

Reference: the create-pr-symlink and copy-artifacts steps
(``testing/workflows/components/workflows.libsonnet:163-175,218-225``)
that fed junit XML to gubernator via GCS. ``copy`` shells out to
gsutil when present and otherwise copies to a local dir (minikube-
style runs).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import shutil
import subprocess
from pathlib import Path

logger = logging.getLogger(__name__)


def artifacts_dir() -> Path:
    return Path(os.environ.get("KFT_ARTIFACTS_DIR", "artifacts"))


def create_pr_symlink() -> Path:
    """Record the PR→artifacts association gubernator expects: a
    metadata file naming the job run (symlinks don't survive GCS, the
    reference wrote a marker object too)."""
    out = artifacts_dir()
    out.mkdir(parents=True, exist_ok=True)
    marker = out / "pr_metadata.json"
    marker.write_text(json.dumps({
        "job": os.environ.get("JOB_NAME", "manual"),
        "pull": os.environ.get("PULL_NUMBER", ""),
        "commit": os.environ.get("PULL_PULL_SHA", ""),
    }, indent=2))
    return marker


def copy(bucket: str) -> None:
    src = artifacts_dir()
    if shutil.which("gsutil"):
        subprocess.check_call(
            ["gsutil", "-m", "cp", "-r", str(src),
             f"gs://{bucket}/{os.environ.get('JOB_NAME', 'manual')}/"])
        return
    dest = Path("/tmp/kft-artifacts") / bucket
    dest.mkdir(parents=True, exist_ok=True)
    shutil.copytree(src, dest / src.name, dirs_exist_ok=True)
    logger.info("gsutil unavailable; artifacts copied to %s", dest)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kft-ci-artifacts")
    parser.add_argument("command", choices=["create-pr-symlink", "copy"])
    parser.add_argument("--bucket", default="kubeflow-tpu-ci-results")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.command == "create-pr-symlink":
        create_pr_symlink()
    else:
        copy(args.bucket)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
