# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""CI artifact plumbing: PR symlink + junit/log/observability upload.

Reference: the create-pr-symlink and copy-artifacts steps
(``testing/workflows/components/workflows.libsonnet:163-175,218-225``)
that fed junit XML to gubernator via GCS. ``copy`` shells out to
gsutil when present and otherwise copies to a local dir (minikube-
style runs).

Observability trail: ``collect_obs`` sweeps the metrics JSONL and
span JSONL files a CI run's processes wrote under ``$KFT_OBS_DIR``
(plus a live dump of THIS process's registry/tracer) into the
artifacts dir, next to the junit XML — so every CI run leaves its
metrics and traces, not just pass/fail. ``copy`` calls it
automatically before upload.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import shutil
import subprocess
from pathlib import Path

logger = logging.getLogger(__name__)


def artifacts_dir() -> Path:
    return Path(os.environ.get("KFT_ARTIFACTS_DIR", "artifacts"))


def obs_dir() -> Path:
    """Where this run's processes drop metrics/span JSONL for CI to
    pick up (the drop-box contract: docs/observability.md)."""
    return Path(os.environ.get("KFT_OBS_DIR", "/tmp/kft-obs"))


def collect_obs() -> list:
    """Copy every metrics/span JSONL (and collector/alert JSON
    snapshot) under $KFT_OBS_DIR into ``<artifacts>/obs/``, and dump
    THIS process's live registry, span buffer, and any live telemetry
    collectors (store stats + SLO alert history) alongside. Returns
    the copied/created paths. Best-effort: a missing drop-box dir
    means an empty (but present) observability trail, never a failed
    CI step."""
    from kubeflow_tpu.obs import metrics as obs_metrics
    from kubeflow_tpu.obs import tracing as obs_tracing
    from kubeflow_tpu.obs.collector import live_collectors

    out = artifacts_dir() / "obs"
    out.mkdir(parents=True, exist_ok=True)
    copied = []
    src = obs_dir()
    if src.is_dir():
        for pattern in ("*.jsonl", "*.json"):
            for f in sorted(src.rglob(pattern)):
                # Flatten the relative path INTO the name: two
                # processes dropping server/spans.jsonl and
                # proxy/spans.jsonl must both survive the sweep, not
                # clobber each other.
                dest = out / "__".join(f.relative_to(src).parts)
                shutil.copyfile(f, dest)
                copied.append(dest)
    # Live dumps of THIS process under their own names — never the
    # sweep's namespace.
    metrics_path = out / "live_metrics.jsonl"
    obs_metrics.dump_jsonl(str(metrics_path))
    copied.append(metrics_path)
    spans_path = out / "live_spans.jsonl"
    obs_tracing.TRACER.dump_jsonl(str(spans_path))
    copied.append(spans_path)
    # Live telemetry collectors: scrape-target status + store stats,
    # plus every attached alert evaluator's state and transition
    # history (the alert trail a failed SLO assertion needs).
    for i, collector in enumerate(live_collectors()):
        state = collector.state()
        evaluators = [hook.__self__.state()
                      for hook in collector.on_cycle
                      if hasattr(hook, "__self__")
                      and hasattr(hook.__self__, "state")]
        if evaluators:
            state["alerts"] = evaluators
        path = out / f"collector_state_{i}.json"
        path.write_text(json.dumps(state, indent=1, sort_keys=True,
                                   default=str))
        copied.append(path)
        # Assembled traces + attribution reports (ISSUE 15): the
        # waterfall trail next to the junit XML — a failed latency
        # assertion ships the evidence of WHERE the time went.
        span_store = getattr(collector, "span_store", None)
        if span_store is None or not span_store.trace_count():
            continue
        from kubeflow_tpu.obs import trace as obs_trace

        traces = {}
        for row in span_store.trace_ids(limit=32):
            spans = span_store.trace(row["trace_id"])
            traces[row["trace_id"]] = {
                "request_id": row["request_id"],
                "attribution": obs_trace.attribution(spans),
                "waterfall": obs_trace.waterfall_lines(
                    obs_trace.assemble(spans)),
                "spans": spans,
            }
        path = out / f"collector_traces_{i}.json"
        path.write_text(json.dumps(
            {"store": span_store.state(), "traces": traces},
            indent=1, sort_keys=True, default=str))
        copied.append(path)
    logger.info("observability trail: %d file(s) under %s",
                len(copied), out)
    return copied


def create_pr_symlink() -> Path:
    """Record the PR→artifacts association gubernator expects: a
    metadata file naming the job run (symlinks don't survive GCS, the
    reference wrote a marker object too)."""
    out = artifacts_dir()
    out.mkdir(parents=True, exist_ok=True)
    marker = out / "pr_metadata.json"
    marker.write_text(json.dumps({
        "job": os.environ.get("JOB_NAME", "manual"),
        "pull": os.environ.get("PULL_NUMBER", ""),
        "commit": os.environ.get("PULL_PULL_SHA", ""),
    }, indent=2))
    return marker


def copy(bucket: str) -> None:
    src = artifacts_dir()
    collect_obs()  # the junit XML never travels without its obs trail
    if shutil.which("gsutil"):
        subprocess.check_call(
            ["gsutil", "-m", "cp", "-r", str(src),
             f"gs://{bucket}/{os.environ.get('JOB_NAME', 'manual')}/"])
        return
    dest = Path("/tmp/kft-artifacts") / bucket
    dest.mkdir(parents=True, exist_ok=True)
    shutil.copytree(src, dest / src.name, dirs_exist_ok=True)
    logger.info("gsutil unavailable; artifacts copied to %s", dest)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kft-ci-artifacts")
    parser.add_argument("command", choices=["create-pr-symlink", "copy",
                                            "collect-obs"])
    parser.add_argument("--bucket", default="kubeflow-tpu-ci-results")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.command == "create-pr-symlink":
        create_pr_symlink()
    elif args.command == "collect-obs":
        collect_obs()
    else:
        copy(args.bucket)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
