# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""E2E TPUJob test: submit a small training job, wait for success.

Reference: the tfjob-test step delegated to tf-operator's
``py.test_runner`` with a ``simple_tfjob`` component
(``testing/workflows/components/workflows.libsonnet:233-245``) — i.e.
multi-pod training verified by running it, small, on the cluster. In
``--fake`` mode the reconciler + fake apiserver stand in for the
cluster and pod phases are driven programmatically (fresh hermetic
tier; SURVEY §4).
"""

from __future__ import annotations

import argparse
import logging
import sys
import time

from kubeflow_tpu.citests.deploy import make_client
from kubeflow_tpu.params.registry import get_prototype
from kubeflow_tpu.utils import junit

logger = logging.getLogger(__name__)


def submit_and_wait(api, namespace: str, *, fake: bool,
                    timeout_s: float = 600.0) -> None:
    objs = get_prototype("tpu-cnn").build({
        "name": "e2e-tpu-cnn",
        "namespace": namespace,
        "model": "resnet-test",
        "batch_size": "32",
        "num_tpu_workers": "2",
        "tpu_accelerator": "tpu-v5-lite-podslice",
        "tpu_topology": "2x4",
    })
    job = next(o for o in objs if o["kind"] == "TPUJob")
    api.create(job)
    name = job["metadata"]["name"]

    if fake:
        from kubeflow_tpu.operator.reconciler import Reconciler

        rec = Reconciler(api)
        rec.reconcile(api.get("TPUJob", namespace, name))
        api.set_all_pod_phases(namespace, "Running")
        rec.reconcile(api.get("TPUJob", namespace, name))
        assert api.get("TPUJob", namespace, name)["status"]["phase"] == \
            "Running"
        api.set_all_pod_phases(namespace, "Succeeded")
        rec.reconcile(api.get("TPUJob", namespace, name))
    else:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            phase = api.get("TPUJob", namespace, name).get(
                "status", {}).get("phase")
            if phase in ("Succeeded", "Failed"):
                break
            time.sleep(10)

    phase = api.get("TPUJob", namespace, name)["status"]["phase"]
    assert phase == "Succeeded", f"TPUJob ended {phase!r}"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kft-e2e-tpujob")
    parser.add_argument("--namespace", default="kubeflow-e2e")
    parser.add_argument("--junit_path", default=None)
    parser.add_argument("--fake", action="store_true")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    api = make_client(args.fake)
    case = junit.run_case(
        "tpujob-train",
        lambda: submit_and_wait(api, args.namespace, fake=args.fake))
    if args.junit_path:
        junit.write_report(args.junit_path, "e2e-tpujob", [case])
    if not case.ok:
        print(case.failure or case.error, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
