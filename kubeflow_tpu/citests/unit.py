# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Unit-test CI step: run pytest with junit output.

Reference analogue: the jsonnet-test step (``testing/workflows/
components/workflows.libsonnet:226-232`` running ``test_jsonnet.py``)
plus the http-proxy ``make test`` tier — here one pytest invocation
covers both (manifest golden tests and runtime unit tests live in the
same suite).
"""

from __future__ import annotations

import argparse
import subprocess
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kft-ci-unit")
    parser.add_argument("--junit_path", default="junit_unit.xml")
    parser.add_argument("--tests", default="tests/")
    parser.add_argument("-k", dest="keyword", default=None)
    args = parser.parse_args(argv)
    cmd = [sys.executable, "-m", "pytest", args.tests, "-q",
           f"--junitxml={args.junit_path}"]
    if args.keyword:
        cmd += ["-k", args.keyword]
    return subprocess.call(cmd)


if __name__ == "__main__":
    raise SystemExit(main())
