"""TPU compute primitives (attention, fused kernels)."""
