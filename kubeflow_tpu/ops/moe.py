# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Mixture-of-Experts FFN with expert parallelism (GShard-style).

Greenfield vs the reference (SURVEY §2.5: no model parallelism of any
kind); fills the ``expert`` axis of the standard mesh
(:mod:`kubeflow_tpu.parallel.mesh`).

TPU-first design:
- **Static shapes everywhere**: top-k routing with a fixed per-expert
  capacity; over-capacity tokens are dropped (their FFN contribution
  is zero, and transformer blocks add the residual stream back, the
  Switch-Transformer convention). No dynamic gathers.
- **Dispatch/combine as einsums** against one-hot tensors: with tokens
  sharded over (data, fsdp) and expert weights sharded over the
  ``expert`` mesh axis (logical axis name ``"expert"`` in the rule
  table, parallel/tensor_parallel.py), GSPMD lowers these einsums to
  the all-to-all exchanges a hand-written MoE would issue — same
  recipe as TP: annotate, let XLA insert collectives.
- Router math in fp32; load-balance auxiliary loss sown into the
  ``"losses"`` collection (collect with
  ``mutable=["losses"]`` / ``nn.apply(..., mutable=...)``).
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


def compute_capacity(tokens: int, num_experts: int, num_selected: int,
                     capacity_factor: float) -> int:
    """Per-expert token slots: even share × capacity factor, floor 4
    and rounded up to a multiple of 4 (sublane-friendly)."""
    ideal = tokens * num_selected / num_experts
    capacity = int(ideal * capacity_factor) + 1
    return max(4, -(-capacity // 4) * 4)


def top_k_dispatch(probs: jax.Array, num_selected: int,
                   capacity: int) -> Tuple[jax.Array, jax.Array]:
    """Build the combine tensor for top-k routing with capacity.

    ``probs``: [T, E] fp32 router probabilities.
    Returns (combine [T, E, C] fp32, aux_fraction [E]): ``combine``
    carries the (renormalized) gate weight at each token's assigned
    (expert, slot); ``aux_fraction`` is the fraction of tokens whose
    i-th choice landed on each expert (for the balance loss).
    """
    t, e = probs.shape
    remaining = probs
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    taken = jnp.zeros((e,), jnp.int32)  # slots already filled per expert
    chosen_fraction = jnp.zeros((e,), jnp.float32)
    kept_gate_sum = jnp.zeros((t,), jnp.float32)
    for _ in range(num_selected):
        choice = jnp.argmax(remaining, axis=-1)  # [T]
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.int32)  # [T, E]
        # Arrival rank of each token within its chosen expert, offset
        # by slots previous rounds already filled.
        rank = jnp.cumsum(onehot, axis=0) - onehot  # [T, E] rank among round
        pos = (jnp.take_along_axis(rank, choice[:, None], 1)[:, 0]
               + taken[choice])  # [T]
        keep = (pos < capacity)
        gate = jnp.take_along_axis(remaining, choice[:, None], 1)[:, 0]
        gate = jnp.where(keep, gate, 0.0)
        slot = jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity,
                              dtype=jnp.float32)  # [T, C]
        combine = combine + (gate[:, None, None]
                             * onehot.astype(jnp.float32)[:, :, None]
                             * slot[:, None, :])
        taken = taken + jnp.sum(onehot * keep[:, None].astype(jnp.int32),
                                axis=0)
        chosen_fraction = chosen_fraction + jnp.mean(
            onehot.astype(jnp.float32), axis=0)
        kept_gate_sum = kept_gate_sum + gate
        remaining = remaining * (1.0 - onehot.astype(probs.dtype))
    if num_selected > 1:
        # Renormalize over the kept choices so gates sum to 1 per
        # token (dropped tokens keep 0 everywhere → pure residual
        # passthrough).
        combine = combine / jnp.maximum(kept_gate_sum, 1e-9)[:, None, None]
    # num_selected == 1: keep the RAW router probability as the scale
    # (Switch Transformer). Renormalizing would make the weight a
    # constant 1.0 — zero gradient into the router from the main loss,
    # and top-1 routing could never be learned.
    return combine, chosen_fraction / num_selected


def _fit_group_size(tokens: int, group_size: int) -> int:
    """Largest divisor of ``tokens`` ≤ ``group_size``."""
    group_size = min(group_size, tokens)
    for candidate in range(group_size, 0, -1):
        if tokens % candidate == 0:
            return candidate
    return tokens


class MoE(nn.Module):
    """Top-k routed expert FFN: [B, S, D] → [B, S, D].

    Expert weights carry the ``"expert"`` logical axis so the rule
    table shards them over the ``expert`` mesh axis; the dispatch
    einsums become all-to-alls under GSPMD.

    Routing happens within fixed-size token *groups* (GShard): the
    combine tensor is [groups, G, E, C] with C ∝ G/E, so dispatch
    memory is O(T·G·k) instead of the O(T²·k/E) a global dispatch
    would cost — the difference between toy shapes and batch·seq in
    the millions.
    """

    num_experts: int
    mlp_dim: int
    num_selected: int = 2
    capacity_factor: float = 1.25
    group_size: int = 512
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, s, d = x.shape
        tokens = b * s
        group = _fit_group_size(tokens, self.group_size)
        n_groups = tokens // group
        grouped = x.reshape(n_groups, group, d)

        router = nn.Dense(
            self.num_experts, use_bias=False, dtype=jnp.float32,
            kernel_init=nn.with_partitioning(
                nn.initializers.normal(0.02), ("embed", None)),
            name="router")
        probs = jax.nn.softmax(
            router(grouped.astype(jnp.float32)), axis=-1)  # [n, G, E]

        capacity = compute_capacity(group, self.num_experts,
                                    self.num_selected,
                                    self.capacity_factor)
        combine, chosen_fraction = jax.vmap(
            lambda p: top_k_dispatch(p, self.num_selected, capacity)
        )(probs)  # combine [n, G, E, C]; fraction [n, E]

        # Load-balance loss (Switch eq. 4): E · Σ_e fraction_e · mean
        # router prob_e; minimized at uniform routing.
        aux = self.num_experts * jnp.sum(
            jnp.mean(chosen_fraction, axis=0)
            * jnp.mean(probs, axis=(0, 1)))
        self.sow("losses", "moe_aux", aux)

        w_in = self.param(
            "w_in",
            nn.with_partitioning(nn.initializers.normal(0.02),
                                 ("expert", "embed", "mlp")),
            (self.num_experts, d, self.mlp_dim))
        w_out = self.param(
            "w_out",
            nn.with_partitioning(nn.initializers.normal(0.02),
                                 ("expert", "mlp", "embed")),
            (self.num_experts, self.mlp_dim, d))

        dispatch = (combine > 0).astype(self.dtype)  # [n, G, E, C]
        # [n, E, C, d] expert inputs → per-expert FFN (n and C are
        # batch-like dims for the expert matmuls).
        expert_in = jnp.einsum(
            "ngec,ngd->necd", dispatch, grouped.astype(self.dtype))
        h = jnp.einsum("necd,edf->necf", expert_in,
                       jnp.asarray(w_in, self.dtype))
        h = nn.gelu(h, approximate=True)
        expert_out = jnp.einsum("necf,efd->necd", h,
                                jnp.asarray(w_out, self.dtype))
        y = jnp.einsum("ngec,necd->ngd", combine.astype(self.dtype),
                       expert_out)
        return y.reshape(b, s, d)
