# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Fused flash attention as a Pallas TPU kernel.

The hot op of the BERT/Llama training path (the reference had no
attention at all — its engine was external tf_cnn_benchmarks CNNs, so
this is greenfield TPU work). One kernel fuses QKᵀ → online softmax →
PV so the (Lq × Lk) score matrix never round-trips to HBM; VMEM holds
one (block_q × block_k) tile at a time and fp32 running statistics.

Kernel shape notes (see /opt/skills/guides/pallas_guide.md):
- Grid = (batch·heads, q_blocks, kv_blocks); the innermost grid dim is
  sequential on TPU, so fp32 accumulators in VMEM scratch carry across
  the kv sweep for one q block.
- Blocks are (block_q, head_dim) / (block_k, head_dim) tiles — last
  dim stays the 128-lane axis (head_dim 64/128 in our models).
- Causal masking is arithmetic (global positions from program ids);
  fully-future kv blocks are skipped with ``pl.when``.
- Backward pass: recompute-based ``custom_vjp`` (the standard
  flash-attention trade — backward re-runs attention blockwise rather
  than storing Lq×Lk activations).

``flash_attention`` falls back to the XLA blockwise implementation
when shapes don't satisfy the kernel's divisibility constraints, so
callers can use it unconditionally.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubeflow_tpu.ops.attention import (
    NEG_INF,
    _repeat_kv,
    blockwise_attention,
)

# Tuned on v5e (H=16 D=64 causal bf16, dependent-chain timing):
# 2048/1024 beats 1024/1024 at every length measured — 8.1 vs 11.9 ms
# at B=8 L=2048, 11.6 vs 30.4 ms at B=2 L=8192 (2.6×), 43.3 vs
# 48.3 ms at B=1 L=32768. Larger q blocks amortize the kv sweep's
# running-statistics updates; 2048/2048 wins at short L but exhausts
# VMEM at L≥8192, and 4096 q blocks fail to compile.
DEFAULT_BLOCK_Q = 2048
DEFAULT_BLOCK_K = 1024


def _fit_block(length: int, block: int) -> int:
    """Largest power-of-two block ≤ min(block, length) dividing
    ``length`` — so a non-multiple length (L=3072 with the 2048
    default) degrades to a smaller kernel block instead of the XLA
    fallback. Always a power of two (arbitrary lengths like 1500 are
    not tile-aligned block shapes — Mosaic would reject them), and
    never degrades below 512 (blocks that small underutilize the MXU
    and lose to the XLA path — the original 256-block measurement);
    lengths no power-of-two ≥ 512 divides take the fallback via the
    divisibility guard in :func:`flash_attention`."""
    block = min(block, length)
    block = 1 << (block.bit_length() - 1)  # round down to a power of 2
    while block > 512 and length % block:
        block //= 2
    return block


def _flash_kernel(q_ref, k_ref, v_ref, *rest, scale: float, causal: bool,
                  block_q: int, block_k: int, has_mask: bool):
    if has_mask:
        mask_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        mask_ref = None
        o_ref, acc_ref, m_ref, l_ref = rest
    i = pl.program_id(1)  # q block
    j = pl.program_id(2)  # kv block
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def _compute():
        q = q_ref[0]  # (block_q, d)
        k = k_ref[0]  # (block_k, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if has_mask:
            # (1, block_k) 0/1 row of padded-key validity, broadcast
            # over the q rows.
            s = jnp.where(mask_ref[:] != 0, s, NEG_INF)

        m_prev = m_ref[:, :1]  # (block_q, 1)
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        m_safe = jnp.maximum(m_new, NEG_INF / 2)
        p = jnp.exp(s - m_safe)  # (block_q, block_k)
        correction = jnp.exp(m_prev - m_safe)  # (block_q, 1)
        l_new = l_prev * correction + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, d)
        acc_ref[:] = acc_ref[:] * correction + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # Skip kv blocks entirely in this q block's causal future.
        @pl.when(j * block_k <= (i + 1) * block_q - 1)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _finalize():
        norm = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / norm).astype(o_ref.dtype)


def _flash_bhld(q, k, v, mask, *, num_heads: int, scale: float,
                causal: bool, block_q: int, block_k: int, interpret: bool):
    """Kernel launch on [BH, L, D] tensors; ``mask`` is [B, Lk] or None."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    grid = (bh, lq // block_q, lk // block_k)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, has_mask=mask is not None)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
    ]
    inputs = (q, k, v)
    if mask is not None:
        # One (1, block_k) row per kv block, shared by every head of
        # the same batch element (grid dim 0 is batch-major b*h).
        in_specs.append(pl.BlockSpec(
            (1, block_k), lambda b, i, j: (b // num_heads, j)))
        inputs = inputs + (mask,)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)


def _to_bhld(x):
    b, l, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, l, d)


def _from_bhld(x, b, h):
    bh, l, d = x.shape
    return x.reshape(b, h, l, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    b, lq, h, d = q.shape
    out = _flash_bhld(
        _to_bhld(q), _to_bhld(k), _to_bhld(v), None, num_heads=h,
        scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret)
    return _from_bhld(out, b, h)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, residuals, g):
    # Recompute-based backward: differentiate the O(L·block)-memory
    # XLA blockwise reference. Numerically matches the kernel (same
    # online-softmax algebra in fp32).
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, block_size=block_k, causal=causal, scale=scale),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_masked(q, k, v, mask, causal, scale, block_q, block_k, interpret):
    b, lq, h, d = q.shape
    out = _flash_bhld(
        _to_bhld(q), _to_bhld(k), _to_bhld(v), mask, num_heads=h,
        scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret)
    return _from_bhld(out, b, h)


def _flash_masked_fwd(q, k, v, mask, causal, scale, block_q, block_k,
                      interpret):
    out = _flash_masked(q, k, v, mask, causal, scale, block_q, block_k,
                        interpret)
    return out, (q, k, v, mask)


def _flash_masked_bwd(causal, scale, block_q, block_k, interpret,
                      residuals, g):
    q, k, v, mask = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, block_size=block_k, causal=causal, scale=scale,
            kv_segment_valid=mask),
        q, k, v)
    return vjp(g) + (jnp.zeros_like(mask),)


_flash_masked.defvjp(_flash_masked_fwd, _flash_masked_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    kv_segment_valid: Optional[jax.Array] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused attention on [B, L, H, D]; GQA KV heads are expanded.

    ``kv_segment_valid`` is an optional [B, Lk] 0/1 mask for padded
    keys (threaded into the kernel as a per-block row). Falls back to
    :func:`blockwise_attention` when sequence lengths don't divide the
    block sizes (or head_dim < 8, below the fp32 sublane tile).
    """
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = d ** -0.5 if scale is None else scale
    if k.shape[2] != h:
        k = _repeat_kv(k, h // k.shape[2])
        v = _repeat_kv(v, h // v.shape[2])
    block_q = _fit_block(lq, block_q)
    block_k = _fit_block(lk, block_k)
    if lq % block_q or lk % block_k or d % 8:
        return blockwise_attention(q, k, v, block_size=min(512, lk),
                                   causal=causal, scale=scale,
                                   kv_segment_valid=kv_segment_valid)
    if interpret is None:
        if jax.default_backend() != "tpu":
            # Non-TPU: run the XLA blockwise path — the same online-
            # softmax algorithm, compiled. Interpret-mode Pallas is a
            # kernel-debugging tool (python-level grid loops), far too
            # slow as a routine CPU path; pass interpret=True to force
            # the kernel (kernel-correctness tests do).
            return blockwise_attention(q, k, v, block_size=min(512, lk),
                                       causal=causal, scale=scale,
                                       kv_segment_valid=kv_segment_valid)
        interpret = False
    if kv_segment_valid is None:
        return _flash(q, k, v, causal, scale, block_q, block_k, interpret)
    mask = kv_segment_valid.astype(jnp.float32)
    return _flash_masked(q, k, v, mask, causal, scale, block_q, block_k,
                         interpret)
