# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Batch normalization with distributed-parity ("ghost") statistics.

Why this exists: the ResNet-50 train step on one chip is NOT
MXU-bound — the XPlane trace (PERF.md) shows the BN statistics
reductions are >50% of step time, i.e. the step spends most of its
HBM bandwidth re-reading activations to compute per-channel
mean/var. The FLOPs are trivial; the READ of the full activation
tensor is the cost, and it is proportional to the number of rows the
statistics are computed over.

``stat_rows`` caps that: training statistics are computed over the
first ``stat_rows`` rows of the batch (0 = all rows, exactly flax's
``nn.BatchNorm``). This is ghost-batch-normalization-style
estimation (small-virtual-batch statistics, Hoffer et al. 2017): the
mean/var are estimated from a 32-row sample instead of all 256,
which is the same estimator quality a 32-per-device distributed run
gets. It is NOT literally per-replica BN — here ONE subset's stats
normalize every row, whereas 8 chips would each normalize their own
32 rows with their own stats — so treat it as a measured throughput/
statistics trade, not bitwise distributed parity. Three requirements
follow: the input pipeline must shuffle (a fixed leading subset of a
class-ordered batch would bias the stats — every pipeline in
training/data.py shuffles); the stat SAMPLE count per channel
(``stat_rows × H × W`` at each layer) must stay in the hundreds —
the convergence test measured 4-samples-per-channel stats diverging
while half-batch stats track exact BN (resnet50 at ``stat_rows=32``
has ≥1568 samples/channel everywhere); and convergence with
``stat_rows>0`` is covered by its own training test rather than
assumed (tests/test_batch_norm.py).

Normalization, scale/bias and the running-average update are
unchanged; only which rows feed the mean/var estimate differs. The
module's param/collection layout matches ``nn.BatchNorm`` exactly
(params: scale/bias; batch_stats: mean/var), so checkpoints and
exports are interchangeable — verified by equivalence test at
``stat_rows=0`` (tests/test_batch_norm.py).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import linen as nn


class GhostBatchNorm(nn.Module):
    """``nn.BatchNorm``-compatible BN with ``stat_rows`` row capping.

    Only the feature-last layout (reduction over all axes but -1) is
    supported — the NHWC convention every model in this tree uses.

    ``stat_rows`` is a SINGLE-CHIP lever: with the batch dim sharded
    over a data axis, ``x[:stat_rows]`` names rows resident on a
    device subset, so XLA inserts collectives to share them with
    every device and the HBM saving disappears (use ``stat_rows=0``
    on a mesh — there the stats reduce across devices as sync-BN,
    per-channel scalars over ICI, which is cheap). The benchmark
    applies it only on the single-chip layout (training/benchmark.py).
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    stat_rows: int = 0  # 0 → full batch (exact nn.BatchNorm)
    scale_init: Callable = nn.initializers.ones
    bias_init: Callable = nn.initializers.zeros

    @nn.compact
    def __call__(self, x):
        features = x.shape[-1]
        reduce_axes = tuple(range(x.ndim - 1))
        ra_mean = self.variable("batch_stats", "mean",
                                lambda *_: jnp.zeros(features, jnp.float32),
                                None)
        ra_var = self.variable("batch_stats", "var",
                               lambda *_: jnp.ones(features, jnp.float32),
                               None)
        scale = self.param("scale", self.scale_init, (features,))
        bias = self.param("bias", self.bias_init, (features,))

        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xs = x
            if 0 < self.stat_rows < x.shape[0]:
                # Stats over the leading rows only: the reduction —
                # and its HBM read — shrinks by batch/stat_rows.
                # lax.stop_gradient? No: grads flow through the stat
                # rows exactly as in per-replica BN on a real mesh.
                xs = x[: self.stat_rows]
            xf = xs.astype(jnp.float32)
            mean = jnp.mean(xf, reduce_axes)
            # Fast variance (E[x²] − E[x]²): one pass over the data,
            # matching flax's use_fast_variance=True default.
            var = jnp.maximum(
                jnp.mean(jnp.square(xf), reduce_axes) - jnp.square(mean),
                0.0)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var

        # Mirror flax's _normalize op-for-op (promotion to f32 via the
        # f32 mean/var, THEN mul-by-scale, THEN bias, cast to dtype
        # last) so the module is bitwise-identical to nn.BatchNorm at
        # stat_rows=0 — asserted for f32 AND bf16 in
        # tests/test_batch_norm.py.
        y = x - mean  # promotes to f32 (mean is f32), like flax
        mul = jax.lax.rsqrt(var + self.epsilon)
        mul = mul * scale
        y = y * mul
        y = y + bias
        return jnp.asarray(y, self.dtype)
