# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""LoRA: low-rank adapters for parameter-efficient fine-tuning.

The BASELINE stretch target is "Llama-2-7B fine-tune on a v5e" — full
fine-tuning of a 7B model cannot fit one 16 GB chip (params + grads +
adam moments ≈ 4× param bytes), but LoRA can: the base weights stay
frozen in bf16 (no gradients, no optimizer moments — XLA dead-code-
eliminates their backward matmuls), and only rank-r adapters train.

Design (TPU-first):

- Adapters live in a **separate flax collection ``"lora"``**, not in
  ``"params"``. ``jax.grad`` then differentiates *only* the adapter
  tree — the frozen 13 GB never gets a cotangent buffer, which is the
  difference between fitting and OOM. (The optax.masked alternative
  still materializes the full-size grad tree before masking.)
- ``y = x @ W + (x @ A) @ B · (α/r)`` — two skinny matmuls fused by
  XLA into the surrounding computation; the full-size delta ``A @ B``
  is never materialized during training.
- ``B`` initializes to zero, so step 0 is *exactly* the base model.
- ``A``/``B`` carry logical-axis metadata (``(in_axis, "lora")`` /
  ``("lora", out_axis)``) so the same TP/fsdp rule table that shards
  the base kernel shards the adapters (parallel/tensor_parallel.py);
  the rank axis replicates.
- :func:`merge_lora` folds trained adapters into the base weights for
  serving (one outer product per target matrix, done once at export —
  the merged model has zero inference overhead).

The reference (early Kubeflow) has no fine-tuning story at all; parity
anchor is the BASELINE.md stretch row only.
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


def _unbox(value: Any) -> jax.Array:
    """Inside module code, ``self.variable`` values are boxed
    (``nn.Partitioned``) during init and plain arrays during apply."""
    if isinstance(value, nn.meta.AxisMetadata):
        return nn.meta.unbox(value)
    return value


class LoRADense(nn.Module):
    """Bias-free Dense with an optional low-rank adapter branch.

    With ``rank == 0`` this is exactly the plain partitioned Dense the
    models build (same param name/path — checkpoints interchange).
    With ``rank > 0`` it adds ``lora_a`` [in, r] (normal init) and
    ``lora_b`` [r, out] (zeros) in the ``"lora"`` collection.
    """

    features: int
    axes: Tuple[str, str]
    dtype: Any = jnp.bfloat16
    rank: int = 0
    alpha: float = 16.0

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        kernel = self.param(
            "kernel",
            nn.with_partitioning(nn.initializers.normal(0.02), self.axes),
            (in_features, self.features),
        )
        y = jnp.dot(x, kernel.astype(self.dtype))
        if not self.rank:
            return y
        a = self.variable(
            "lora", "lora_a",
            lambda: nn.with_partitioning(
                nn.initializers.normal(0.02), (self.axes[0], "lora")
            )(self.make_rng("params"), (in_features, self.rank),
              jnp.float32),
        )
        b = self.variable(
            "lora", "lora_b",
            lambda: nn.with_partitioning(
                nn.initializers.zeros, ("lora", self.axes[1])
            )(self.make_rng("params"), (self.rank, self.features),
              jnp.float32),
        )
        scale = self.alpha / self.rank
        delta = jnp.dot(
            jnp.dot(x, _unbox(a.value).astype(self.dtype)),
            _unbox(b.value).astype(self.dtype),
        )
        return y + delta * jnp.asarray(scale, self.dtype)


def merge_lora(params: Any, lora: Any, alpha: float) -> Any:
    """Fold trained adapters into base weights: ``W += A @ B · (α/r)``.

    ``alpha`` is required and must be the ``lora_alpha`` the model was
    trained with (e.g. ``model.lora_alpha``) — a defaulted value here
    could silently mis-scale the export when training used a
    non-default α. ``lora`` mirrors the module tree of ``params`` with
    ``{"lora_a": A, "lora_b": B}`` leaves at each adapted module.
    Returns a new params tree (same structure/dtypes as ``params``) —
    the export path for serving a fine-tuned model with zero runtime
    overhead.
    """

    def walk(p: Any, l: Any) -> Any:
        if not isinstance(p, dict):
            return p
        if isinstance(l, dict) and "lora_a" in l:
            a = _unbox(l["lora_a"]).astype(jnp.float32)
            b = _unbox(l["lora_b"]).astype(jnp.float32)
            kernel = _unbox(p["kernel"])
            scale = alpha / a.shape[1]
            merged = kernel.astype(jnp.float32) + a @ b * scale
            out = dict(p)
            out["kernel"] = merged.astype(kernel.dtype)
            return out
        out = {}
        for key, sub in p.items():
            out[key] = walk(sub, l.get(key, {}) if isinstance(l, dict)
                            else {})
        return out

    return walk(params, lora)
