# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Attention primitives: dense reference + blockwise (online-softmax).

The reference framework has no attention anywhere (its only model path
is tf_cnn_benchmarks CNNs, ``kubeflow/tf-job/prototypes/
tf-cnn-benchmarks.jsonnet:36-43``); sequence models appear only as
BASELINE targets (BERT, Llama). These primitives are therefore
greenfield, designed TPU-first:

- All shapes static; masking is arithmetic (no boolean gather) so XLA
  tiles cleanly onto the MXU.
- Softmax statistics carried in float32 even for bf16 inputs.
- The blockwise form is the building block for ring attention
  (:mod:`kubeflow_tpu.parallel.ring_attention`): it consumes KV in
  chunks with online-softmax rescaling, which is exactly the per-ring-
  step update.

Convention: ``q, k, v`` are ``[batch, seq, heads, head_dim]``; KV may
have fewer heads than Q (grouped-query attention) as long as
``q_heads % kv_heads == 0``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """Expand KV heads for grouped-query attention: [B,L,Hkv,D] →
    [B,L,Hkv*n_rep,D]."""
    if n_rep == 1:
        return x
    b, l, h, d = x.shape
    return jnp.broadcast_to(
        x[:, :, :, None, :], (b, l, h, n_rep, d)
    ).reshape(b, l, h * n_rep, d)


def dense_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset: int | jax.Array = 0,
    kv_offset: int | jax.Array = 0,
    kv_segment_valid: Optional[jax.Array] = None,
) -> jax.Array:
    """Reference softmax attention (fp32 statistics).

    ``q_offset``/``kv_offset`` are the global positions of the first
    query/key — this makes the same function usable on sequence shards
    (ring attention's per-step block compute) and on full sequences
    (offsets 0). ``kv_segment_valid`` is an optional [B, Lk] 0/1 mask
    for padded keys, or [B, Lq, Lk] for a per-query mask (the decode
    engine's multi-token verify path, where each batch row's queries
    have their own causal frontier).
    """
    q_heads, kv_heads = q.shape[2], k.shape[2]
    if q_heads != kv_heads:
        k = _repeat_kv(k, q_heads // kv_heads)
        v = _repeat_kv(v, q_heads // kv_heads)
    head_dim = q.shape[-1]
    scale = head_dim ** -0.5 if scale is None else scale

    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = kv_offset + jnp.arange(k.shape[1])
        s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, NEG_INF)
    if kv_segment_valid is not None:
        # [B, Lk] masks padded keys for every query; [B, Lq, Lk] is
        # the per-query form (each query row carries its own key
        # validity — e.g. batch rows at different cache positions
        # with per-query causal frontiers).
        mask = kv_segment_valid.astype(bool)
        mask = (mask[:, None, :, :] if mask.ndim == 3
                else mask[:, None, None, :])
        s = jnp.where(mask, s, NEG_INF)
    # Guard fully-masked rows (e.g. ring steps entirely in the causal
    # future): keep the max finite so exp() never sees -inf - -inf.
    m = jnp.maximum(jnp.max(s, axis=-1, keepdims=True), NEG_INF / 2)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    o = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1, 3)
    return o.astype(q.dtype)


def attention_block_update(
    carry: Tuple[jax.Array, jax.Array, jax.Array],
    q: jax.Array,
    k_block: jax.Array,
    v_block: jax.Array,
    *,
    scale: float,
    q_offset: int | jax.Array,
    kv_offset: int | jax.Array,
    causal: bool,
    kv_segment_valid: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One online-softmax update of (o, m, l) with a new KV block.

    This is the flash-attention inner loop in functional form: the ring
    variant calls it once per ring step with the block that just
    arrived over ICI. ``o`` is the unnormalized fp32 accumulator
    [B,Lq,H,D]; ``m``/``l`` are fp32 running max / normalizer
    [B,H,Lq].
    """
    o, m, l = carry
    q_heads, kv_heads = q.shape[2], k_block.shape[2]
    if q_heads != kv_heads:
        k_block = _repeat_kv(k_block, q_heads // kv_heads)
        v_block = _repeat_kv(v_block, q_heads // kv_heads)

    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k_block, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = kv_offset + jnp.arange(k_block.shape[1])
        s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, NEG_INF)
    if kv_segment_valid is not None:
        s = jnp.where(
            kv_segment_valid[:, None, None, :].astype(bool), s, NEG_INF
        )

    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    m_safe = jnp.maximum(m_new, NEG_INF / 2)
    p = jnp.exp(s - m_safe[..., None])
    correction = jnp.exp(m - m_safe)  # == 1 where m was still -inf-ish
    l_new = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v_block.dtype), v_block,
        preferred_element_type=jnp.float32,
    )
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return o_new, m_new, l_new


def attention_init_carry(
    batch: int, q_len: int, heads: int, head_dim: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Zero-state (o, m, l) carry for :func:`attention_block_update`."""
    return (
        jnp.zeros((batch, q_len, heads, head_dim), jnp.float32),
        jnp.full((batch, heads, q_len), NEG_INF, jnp.float32),
        jnp.zeros((batch, heads, q_len), jnp.float32),
    )


def attention_finalize(
    o: jax.Array, l: jax.Array, dtype: jnp.dtype
) -> jax.Array:
    """Normalize the accumulator: o / l (fully-masked rows → 0)."""
    norm = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / norm).astype(dtype)


def _fit_block_size(length: int, block_size: int) -> int:
    """Largest divisor of ``length`` ≤ ``block_size`` — keeps the
    O(Lq · block) memory bound when lengths don't divide the requested
    block (degenerating to one full-size block would silently lose it,
    exactly for the long odd sequences that need it most)."""
    if length % block_size == 0:
        return block_size
    for candidate in range(block_size, 0, -1):
        if length % candidate == 0:
            return candidate
    return length


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    block_size: int = 512,
    causal: bool = False,
    scale: Optional[float] = None,
    kv_segment_valid: Optional[jax.Array] = None,
) -> jax.Array:
    """Memory-efficient attention: scan over KV blocks with online
    softmax. O(Lq · block) live memory instead of O(Lq · Lk); the
    single-device analogue of ring attention. ``kv_segment_valid`` is
    an optional [B, Lk] 0/1 mask for padded keys.
    """
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = d ** -0.5 if scale is None else scale
    block_size = min(block_size, lk)
    if lk % block_size:
        best = _fit_block_size(lk, block_size)
        if best >= min(128, block_size):
            block_size = best
        else:
            # Awkward lengths (primes, near-primes) have no usable
            # divisor; a tiny block would turn the scan into Lk
            # sequential single-key updates. Pad KV to a block
            # multiple instead — the validity mask makes padded keys
            # inert.
            pad = block_size - lk % block_size
            k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
            v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
            if kv_segment_valid is None:
                kv_segment_valid = jnp.ones((b, lk), jnp.int32)
            kv_segment_valid = jnp.pad(kv_segment_valid,
                                       ((0, 0), (0, pad)))
            lk += pad
    n_blocks = lk // block_size

    k_blocks = jnp.moveaxis(
        k.reshape(b, n_blocks, block_size, k.shape[2], d), 1, 0)
    v_blocks = jnp.moveaxis(
        v.reshape(b, n_blocks, block_size, v.shape[2], d), 1, 0)
    xs = (jnp.arange(n_blocks), k_blocks, v_blocks)
    if kv_segment_valid is not None:
        xs = xs + (jnp.moveaxis(
            kv_segment_valid.reshape(b, n_blocks, block_size), 1, 0),)

    def body(carry, inputs):
        idx, k_blk, v_blk = inputs[:3]
        mask_blk = inputs[3] if len(inputs) > 3 else None
        carry = attention_block_update(
            carry, q, k_blk, v_blk,
            scale=scale, q_offset=0, kv_offset=idx * block_size,
            causal=causal, kv_segment_valid=mask_blk,
        )
        return carry, None

    carry = attention_init_carry(b, lq, h, d)
    (o, _, l), _ = jax.lax.scan(body, carry, xs)
    return attention_finalize(o, l, q.dtype)
