# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Pallas experiment: fused BN-train forward (stats + normalize).

Status: **measured, does not beat XLA** — kept as the experiment the
perf write-up cites (PERF.md "Round 3: attacking the BN-stat
bottleneck"). The traffic argument, confirmed by measurement:

exact BN-train forward must (1) reduce x to per-channel mean/var and
(2) normalize x with those stats. Whatever the kernel structure, pass
2 cannot start before pass 1 finishes, and a ResNet activation
(hundreds of MB) cannot stay resident in 16 MB VMEM between the
passes — so the minimum HBM traffic is read-x, read-x, write-y, which
is exactly what XLA's `convert_reduce_fusion` + elementwise-fusion
schedule already does (with the normalize fused into neighboring
elementwise work for free). A hand kernel can only tie the traffic
while giving up XLA's cross-op fusion; the measured numbers
(scripts/bn_pallas_bench.py on the chip: 3-17x slower than the XLA
schedule across the four ResNet-50 BN shapes — table in PERF.md)
show it losing outright.

The kernel stays for two reasons: it is the measured evidence, and it
is the template for cases where a fused epilogue DOES pay (a producer
XLA cannot fuse stats into, e.g. a custom attention output).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _bn_fwd_kernel(x_ref, scale_ref, bias_ref, y_ref, stats_ref,
                   acc_ref, *, eps: float, m_total: int):
    """grid = (2, m_tiles): phase 0 accumulates per-channel sum/sumsq
    into VMEM scratch; phase 1 normalizes with the finished stats.
    Scratch persists across the sequential TPU grid loop."""
    phase = pl.program_id(0)
    m_idx = pl.program_id(1)

    @pl.when((phase == 0) & (m_idx == 0))
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(phase == 0)
    def _accumulate():
        x = x_ref[...].astype(jnp.float32)
        acc_ref[0, :] += jnp.sum(x, axis=0)
        acc_ref[1, :] += jnp.sum(x * x, axis=0)

    @pl.when((phase == 1) & (m_idx == 0))
    def _finalize_stats():
        n = jnp.float32(m_total)
        mean = acc_ref[0, :] / n
        var = jnp.maximum(acc_ref[1, :] / n - mean * mean, 0.0)
        stats_ref[0, :] = mean
        stats_ref[1, :] = var
        # Cache (mean, rsqrt) in the accumulator for the normalize.
        acc_ref[0, :] = mean
        acc_ref[1, :] = jax.lax.rsqrt(var + eps)

    @pl.when(phase == 1)
    def _normalize():
        x = x_ref[...].astype(jnp.float32)
        y = (x - acc_ref[0, :]) * acc_ref[1, :]
        y = y * scale_ref[...].astype(jnp.float32) \
            + bias_ref[...].astype(jnp.float32)
        y_ref[...] = y.astype(y_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("eps", "block_m", "interpret"))
def fused_bn_train_forward(x: jax.Array, scale: jax.Array,
                           bias: jax.Array, *, eps: float = 1e-5,
                           block_m: int = 512,
                           interpret: bool = False):
    """[M, C] x → (y, mean, var), stats over axis 0, one pallas_call.

    C must be a multiple of 128 (lane width); M a multiple of
    ``block_m``. Flatten NHWC inputs to (N·H·W, C) first.
    """
    m, c = x.shape
    if m % block_m:
        raise ValueError(f"M {m} % block_m {block_m}")
    if c % 128:
        raise ValueError(f"C {c} must be a multiple of 128")
    m_tiles = m // block_m
    y, stats = pl.pallas_call(
        functools.partial(_bn_fwd_kernel, eps=eps, m_total=m),
        grid=(2, m_tiles),
        in_specs=[
            pl.BlockSpec((block_m, c), lambda p, i: (i, 0)),
            pl.BlockSpec((c,), lambda p, i: (0,)),
            pl.BlockSpec((c,), lambda p, i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((block_m, c), lambda p, i: (i, 0)),
            pl.BlockSpec((2, c), lambda p, i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, c), x.dtype),
            jax.ShapeDtypeStruct((2, c), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((2, c), jnp.float32)],
        interpret=interpret,  # CPU tests; real lowering on TPU
    )(x, scale, bias)
    return y, stats[0], stats[1]


def reference_bn_train_forward(x, scale, bias, *, eps: float = 1e-5):
    """The XLA-scheduled equivalent (what the model actually runs)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=0)
    var = jnp.maximum(jnp.mean(xf * xf, axis=0) - mean * mean, 0.0)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return y.astype(x.dtype), mean, var
