# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

# jupyterhub_config.py fragment — runs INSIDE the hub pod, not in the
# kubeflow_tpu package. Rebuild of the reference's KubeFormSpawner
# (kubeflow/core/jupyterhub_spawner.py:7-113) with TPU chip resources
# in place of the free-text GPU extra_resource_limits field (:29,56-62).



class TPUFormSpawner(__import__("kubespawner").KubeSpawner):
    """Spawner form: image, CPU, memory, TPU chips."""

    def _options_form_default(self):
        return """
    <label for='image'>Image</label>
    <input name='image' placeholder='repo/image:tag'></input>
    <br/>
    <label for='cpu_guarantee'>CPU</label>
    <input name='cpu_guarantee' placeholder='200m, 1.0, 2.5, etc'></input>
    <br/>
    <label for='mem_guarantee'>Memory</label>
    <input name='mem_guarantee' placeholder='100Mi, 1.5Gi'></input>
    <br/>
    <label for='tpu_chips'>TPU chips (0, 1, 4, or 8)</label>
    <input name='tpu_chips' placeholder='0'></input>
    <br/>
    <label for='tpu_accelerator'>TPU accelerator type</label>
    <input name='tpu_accelerator' placeholder='tpu-v5-lite-podslice'></input>
    """

    def options_from_form(self, formdata):
        options = {}
        for field in ("image", "cpu_guarantee", "mem_guarantee",
                      "tpu_chips", "tpu_accelerator"):
            value = formdata.get(field, [""])[0].strip()
            if value:
                options[field] = value
        return options

    @property
    def singleuser_image_spec(self):
        return self.user_options.get("image", self.image)

    def get_env(self):
        env = super().get_env()
        chips = int(self.user_options.get("tpu_chips", "0") or "0")
        if chips:
            # Single-host notebook slice: the jax[tpu] kernel picks
            # these up; no jax.distributed needed for one host.
            env["TPU_CHIPS"] = str(chips)
        return env

    def start(self):
        chips = int(self.user_options.get("tpu_chips", "0") or "0")
        if chips:
            self.extra_resource_limits = {"google.com/tpu": str(chips)}
            self.node_selector = dict(self.node_selector or {})
            self.node_selector["cloud.google.com/gke-tpu-accelerator"] = (
                self.user_options.get("tpu_accelerator",
                                      "tpu-v5-lite-podslice")
            )
        if "cpu_guarantee" in self.user_options:
            self.cpu_guarantee = self.user_options["cpu_guarantee"]
        if "mem_guarantee" in self.user_options:
            self.mem_guarantee = self.user_options["mem_guarantee"]
        return super().start()


c.JupyterHub.spawner_class = TPUFormSpawner
c.JupyterHub.ip = "0.0.0.0"
c.JupyterHub.hub_ip = "0.0.0.0"
# Parity: hub restarts must not kill user notebooks; 10-minute image
# pulls allowed (reference jupyterhub_spawner.py:72-87).
c.JupyterHub.cleanup_servers = False
c.KubeSpawner.start_timeout = 60 * 10

# Per-user workspace PVC mounted at ~/work (parity :96-113).
import os
c.KubeSpawner.pvc_name_template = "claim-{username}{servername}"
c.KubeSpawner.storage_pvc_ensure = True
c.KubeSpawner.storage_capacity = os.environ.get("NOTEBOOK_PVC_SIZE", "10Gi")
c.KubeSpawner.volumes = [
    {
        "name": "volume-{username}{servername}",
        "persistentVolumeClaim": {"claimName": "claim-{username}{servername}"},
    }
]
c.KubeSpawner.volume_mounts = [
    {"mountPath": "/home/jovyan/work",
     "name": "volume-{username}{servername}"}
]
