"""Notebook-hub assets: the KubeSpawner config deployed into the hub
image (see kubeflow_tpu.manifests.jupyterhub) and image build files
under images/notebook/."""
