from kubeflow_tpu.training.train import (  # noqa: F401
    TrainStepFn,
    TrainState,
    create_train_state,
    make_train_step,
    state_sharding,
)
