# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""tpu-lm — LM pretraining/fine-tune entrypoint (BERT MLM, Llama causal).

The in-pod program for the BASELINE multi-host configs (BERT-base
pretraining step time; Llama fine-tune stretch): the tpu-lm
prototype's POD COMMAND. It initializes ``jax.distributed`` itself
from the operator-injected env (launcher.initialize_distributed) and
runs one SPMD program per host: build mesh (multi-slice dcn_data from
the MEGASCALE env) → shard state → stream per-host batches → ``fit``
with checkpoint/resume + preemption drain.

Mesh spec strings use the standard axis names
(:mod:`kubeflow_tpu.parallel.mesh`): ``--mesh data=-1,tensor=4``.
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path
from typing import Dict, Optional

from kubeflow_tpu.parallel.mesh import MeshSpec

OBJECTIVES = ("mlm", "causal")


def parse_mesh(spec: Optional[str]) -> Optional[MeshSpec]:
    """``"data=2,tensor=4"`` → MeshSpec(data=2, tensor=4)."""
    if not spec:
        return None
    sizes: Dict[str, int] = {}
    for part in spec.split(","):
        name, _, value = part.partition("=")
        if not value:
            raise ValueError(f"bad mesh entry {part!r} (want axis=N)")
        sizes[name.strip()] = int(value)
    return MeshSpec(**sizes)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpu-lm")
    p.add_argument("--model", default="bert-base")
    p.add_argument("--objective", choices=OBJECTIVES, default=None,
                   help="default: mlm for bert*, causal otherwise")
    p.add_argument("--global_batch", type=int, default=256)
    p.add_argument("--seq_len", type=int, default=128)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--log_every", type=int, default=10)
    p.add_argument("--learning_rate", type=float, default=1e-4)
    p.add_argument("--warmup_steps", type=int, default=10)
    p.add_argument("--mesh", default=None,
                   help="e.g. data=-1,tensor=4 (default: all-data)")
    p.add_argument("--data", default=None,
                   help="token shards (.npy / raw .bin): comma-"
                        "separated files, dirs, or globs; gs://-style "
                        "fsspec paths download into a local cache. "
                        "Default: reference-parity synthetic data. "
                        "mlm objectives get dynamic masking over the "
                        "shards.")
    p.add_argument("--bin_dtype", default="uint16",
                   help="dtype of raw .bin token dumps (headerless; "
                        ".npy shards self-describe)")
    p.add_argument("--checkpoint_dir", default=None)
    p.add_argument("--save_every", type=int, default=200)
    p.add_argument("--continuous_every", type=int, default=0,
                   help="continuous sharded checkpointing: per-host "
                        "async shard writes every N steps under "
                        "<checkpoint_dir>/continuous (manifest-last "
                        "commit; elastic resizes restore + reshard "
                        "from these). 0 = off")
    p.add_argument("--metrics_path", default=None)
    p.add_argument("--remat", action="store_true",
                   help="rematerialize blocks (llama only)")
    p.add_argument("--microbatches", type=int, default=4,
                   help="pipeline schedule microbatch count (only "
                        "with a pipeline mesh axis)")
    p.add_argument("--virtual_stages", type=int, default=1,
                   help=">1 selects the interleaved pipeline "
                        "schedule: v cyclic stage groups per device, "
                        "~v× smaller bubble (PERF.md)")
    p.add_argument("--seed", type=int, default=0)
    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.continuous_every > 0 and not args.checkpoint_dir:
        # Mirror the tpu-lm manifest builder: a continuous tier with
        # nowhere durable to land is the silent-data-loss trap —
        # an elastic resize would restart the run from step 0.
        parser.error("--continuous_every needs --checkpoint_dir")
    from kubeflow_tpu.training.launcher import initialize_distributed
    from kubeflow_tpu.utils.platform import sync_platform_from_env

    sync_platform_from_env()
    # Multi-host bootstrap from the operator-injected KFT_* env: this
    # CLI is the tpu-lm pod command, so the gang join happens here —
    # without it each host would see only local devices, read
    # process_count()==1, feed itself the FULL batch, and train an
    # independent model copy whose loss curves look plausible (the
    # silent-wrongness failure mode; test_multiprocess pretrain_cli
    # mode proves the real command joins the gang).
    initialize_distributed()

    import jax
    import optax

    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.parallel.mesh import build_mesh
    from kubeflow_tpu.training.checkpoint import CheckpointConfig
    from kubeflow_tpu.training.data import (
        DevicePrefetcher,
        mlm_mask_batches,
        resolve_shards,
        synthetic_causal_lm,
        synthetic_mlm,
        token_shard_batches,
    )
    from kubeflow_tpu.training.lm import create_lm_state, make_lm_train_step
    from kubeflow_tpu.training.loop import (
        DRAIN_EXIT_CODE,
        DrainInterrupt,
        LoopConfig,
        fit,
    )

    entry = get_model(args.model)
    objective = args.objective or (
        "mlm" if entry.name.startswith("bert") else "causal")
    kwargs = {}
    if args.remat:
        kwargs["remat"] = True
    model = entry.make(**kwargs)
    vocab = entry.num_classes_or_vocab

    mesh = build_mesh(parse_mesh(args.mesh))
    if args.data:
        # Real token shards (local or gs://-style — SURVEY §2.4's
        # storage row on the pretraining path, not just fine-tuning).
        paths = resolve_shards(args.data)
        gen = token_shard_batches(
            paths, args.global_batch, args.seq_len, seed=args.seed,
            bin_dtype=args.bin_dtype)

        def check_vocab(source, bound=vocab):
            # Out-of-range ids silently CLAMP in the embedding gather
            # (XLA semantics) — a wrong-vocab tokenizer dump or a
            # misdeclared bin_dtype would train to convergence on
            # garbage. Fail loudly instead.
            for batch in source:
                top = int(batch["input_ids"].max())
                if top >= bound:
                    raise ValueError(
                        f"shard token id {top} >= model vocab {bound} "
                        f"— wrong tokenizer or wrong --bin_dtype?")
                yield batch

        gen = check_vocab(gen)
        if objective == "mlm":
            gen = mlm_mask_batches(gen, seed=args.seed)
    elif objective == "mlm":
        gen = synthetic_mlm(args.global_batch, args.seq_len, vocab,
                            seed=args.seed)
    else:
        gen = synthetic_causal_lm(args.global_batch, args.seq_len, vocab,
                                  seed=args.seed)
    sample = next(gen)

    tx = optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(
            optax.schedules.warmup_cosine_decay_schedule(
                0.0, args.learning_rate, args.warmup_steps,
                max(args.steps, args.warmup_steps + 1)),
            weight_decay=0.01,
        ),
    )
    if mesh.shape.get("pipeline", 1) > 1:
        # Pipeline trainer preset (training/pipeline_lm.py): decoder
        # blocks staged over the pipeline axis, GPipe or interleaved
        # schedule. Dense causal decoders only.
        from kubeflow_tpu.training.pipeline_lm import (
            create_pipeline_lm_state,
            make_pipeline_lm_train_step,
        )

        from kubeflow_tpu.models.llama import Llama

        if objective != "causal" or not isinstance(model, Llama):
            # Guard here with a clean message: a non-decoder tree
            # would otherwise die deep inside partition_llama_params
            # with a bare KeyError.
            raise SystemExit(
                "a pipeline mesh axis needs a causal decoder (Llama) "
                f"model (got {entry.name!r}, objective={objective!r})")
        state, shardings = create_pipeline_lm_state(
            model, tx, jax.random.PRNGKey(args.seed), sample, mesh,
            n_virtual=args.virtual_stages)
        step_fn = make_pipeline_lm_train_step(
            mesh, shardings, model, n_microbatches=args.microbatches,
            n_virtual=args.virtual_stages)
    else:
        state, shardings = create_lm_state(
            model, tx, jax.random.PRNGKey(args.seed), sample, mesh)
        step_fn = make_lm_train_step(mesh, shardings, objective=objective)

    ckpt = None
    continuous = None
    if args.checkpoint_dir:
        ckpt = CheckpointConfig(directory=args.checkpoint_dir,
                                save_interval_steps=args.save_every)
        if args.continuous_every > 0:
            from kubeflow_tpu.training.checkpoint import (
                ContinuousCheckpointConfig,
            )

            continuous = ContinuousCheckpointConfig(
                directory=str(Path(args.checkpoint_dir) / "continuous"),
                save_interval_steps=args.continuous_every,
                num_hosts=jax.process_count(),
                host_id=jax.process_index(),
                mesh_shape={k: int(v) for k, v in mesh.shape.items()
                            if int(v) > 1})
    config = LoopConfig(total_steps=args.steps, log_every=args.log_every,
                        checkpoint=ckpt, continuous=continuous,
                        metrics_path=args.metrics_path)
    data = DevicePrefetcher(gen, mesh)
    try:
        state = fit(state, step_fn, data, config)
    except DrainInterrupt as drain:
        # Preemption (SIGTERM): the in-flight step finished and the
        # checkpoint is durable. The distinguishable exit code tells
        # the operator to restart the slice WITHOUT burning a
        # restart-budget slot; the restarted pod resumes at the drain
        # step.
        print(json.dumps({
            "drained": True,
            "step": drain.step,
            "checkpointed": drain.checkpointed,
        }))
        return DRAIN_EXIT_CODE
    finally:
        data.close()

    if jax.process_index() == 0:
        print(json.dumps({
            "model": entry.name,
            "objective": objective,
            "final_step": int(state.step),
            "mesh": {k: int(v) for k, v in mesh.shape.items()},
        }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
