"""tpu-cnn — the benchmark harness (tf_cnn_benchmarks replacement).

Reference parity: ``tf_cnn_benchmarks.py --model=resnet50
--batch_size=N --flush_stdout`` driven by ``launcher.py`` inside the
TFJob pods (``tf-controller-examples/tf-cnn/launcher.py``,
``kubeflow/tf-job/prototypes/tf-cnn-benchmarks.jsonnet:36-43``).
Synthetic data (the reference default), images/sec as the headline
metric — but measured on a jitted SPMD step over a TPU mesh rather
than a parameter-server session loop.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import optax

from kubeflow_tpu.models.registry import get_model
from kubeflow_tpu.parallel.mesh import MeshSpec, build_mesh
from kubeflow_tpu.training.train import (
    create_train_state,
    make_train_step,
    place_batch,
    place_state,
)


@dataclasses.dataclass
class BenchConfig:
    model: str = "resnet50"
    batch_size: int = 128  # global
    steps: int = 20
    warmup_steps: int = 3
    learning_rate: float = 0.1
    momentum: float = 0.9
    mesh: Optional[MeshSpec] = None  # None → all devices on the data axis
    image_size: Optional[int] = None  # override model default (for smoke runs)
    seed: int = 0


def synthetic_batch(config: BenchConfig, num_classes: int,
                    input_shape, rng: jax.Array) -> Dict[str, jax.Array]:
    """Random images/labels — parity with tf_cnn_benchmarks synthetic
    mode (the reference never wired real data into the benchmark,
    ``tf-controller-examples/tf-cnn/README.md:15-16``)."""
    img_rng, label_rng = jax.random.split(rng)
    images = jax.random.normal(
        img_rng, (config.batch_size, *input_shape), jnp.bfloat16
    )
    labels = jax.random.randint(
        label_rng, (config.batch_size,), 0, num_classes
    )
    return {"inputs": images, "labels": labels}


def run_benchmark(config: BenchConfig) -> Dict[str, float]:
    """Returns {images_per_sec, images_per_sec_per_chip, step_time_ms, ...}."""
    entry = get_model(config.model)
    model = entry.make()
    input_shape = entry.input_spec[0]
    if config.image_size is not None:
        input_shape = (config.image_size, config.image_size, input_shape[-1])

    mesh = build_mesh(config.mesh)
    n_chips = mesh.size

    tx = optax.sgd(config.learning_rate, momentum=config.momentum, nesterov=True)
    rng = jax.random.PRNGKey(config.seed)
    sample = jnp.zeros((1, *input_shape), jnp.bfloat16)
    state = create_train_state(model, tx, rng, sample)
    state = place_state(mesh, state)
    batch = place_batch(
        mesh, synthetic_batch(config, entry.num_classes_or_vocab, input_shape, rng)
    )

    step_fn = make_train_step(mesh)

    # Warmup (includes compile). Fence with a host value pull, not
    # block_until_ready: on remote-tunneled platforms (axon) the ready
    # bit of a dispatched chain can report early, and a timed loop
    # fenced that way measures dispatch, not compute.
    compile_start = time.perf_counter()
    for _ in range(max(config.warmup_steps, 1)):
        state, metrics = step_fn(state, batch)
    float(metrics["loss"])
    compile_s = time.perf_counter() - compile_start

    start = time.perf_counter()
    for _ in range(config.steps):
        state, metrics = step_fn(state, batch)
    final_loss = float(metrics["loss"])
    elapsed = time.perf_counter() - start

    images_per_sec = config.batch_size * config.steps / elapsed
    return {
        "model": config.model,
        "global_batch_size": config.batch_size,
        "n_chips": n_chips,
        "steps": config.steps,
        "images_per_sec": images_per_sec,
        "images_per_sec_per_chip": images_per_sec / n_chips,
        "step_time_ms": elapsed / config.steps * 1e3,
        "compile_plus_warmup_s": compile_s,
        "final_loss": final_loss,
    }


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog="tpu-cnn")
    parser.add_argument("--model", default="resnet50")
    parser.add_argument("--batch_size", type=int, default=128)
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--image_size", type=int, default=None)
    args = parser.parse_args(argv)
    result = run_benchmark(
        BenchConfig(model=args.model, batch_size=args.batch_size,
                    steps=args.steps, image_size=args.image_size)
    )
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
