# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""tpu-cnn — the benchmark harness (tf_cnn_benchmarks replacement).

Reference parity: ``tf_cnn_benchmarks.py --model=resnet50
--batch_size=N --flush_stdout`` driven by ``launcher.py`` inside the
TFJob pods (``tf-controller-examples/tf-cnn/launcher.py``,
``kubeflow/tf-job/prototypes/tf-cnn-benchmarks.jsonnet:36-43``).
Synthetic data (the reference default), images/sec as the headline
metric — but measured on a jitted SPMD step over a TPU mesh rather
than a parameter-server session loop.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import optax

from kubeflow_tpu.models.registry import get_model
from kubeflow_tpu.parallel.mesh import MeshSpec, build_mesh
from kubeflow_tpu.training.train import (
    create_train_state,
    make_train_step,
    place_batch,
    place_state,
)


@dataclasses.dataclass
class BenchConfig:
    model: str = "resnet50"
    batch_size: int = 128  # global
    steps: int = 20
    warmup_steps: int = 3
    learning_rate: Optional[float] = None  # None → per-model default
    momentum: float = 0.9
    mesh: Optional[MeshSpec] = None  # None → all devices on the data axis
    image_size: Optional[int] = None  # override model default (for smoke runs)
    seed: int = 0
    model_kwargs: Optional[Dict] = None  # e.g. {"bn_stat_rows": 64}
    profile_dir: Optional[str] = None  # capture timed steps as XPlane


def synthetic_batch(config: BenchConfig, num_classes: int,
                    input_shape, rng: jax.Array) -> Dict[str, jax.Array]:
    """Random images/labels — parity with tf_cnn_benchmarks synthetic
    mode (the reference never wired real data into the benchmark,
    ``tf-controller-examples/tf-cnn/README.md:15-16``)."""
    img_rng, label_rng = jax.random.split(rng)
    images = jax.random.normal(
        img_rng, (config.batch_size, *input_shape), jnp.bfloat16
    )
    labels = jax.random.randint(
        label_rng, (config.batch_size,), 0, num_classes
    )
    return {"inputs": images, "labels": labels}


PEAK_BF16_FLOPS = {
    # device_kind → peak bf16 FLOP/s (MFU denominator).
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
}


def peak_flops_per_chip() -> float:
    kind = jax.devices()[0].device_kind
    for prefix, peak in PEAK_BF16_FLOPS.items():
        if kind.startswith(prefix):
            return peak
    return 197e12  # assume v5e-class if unknown


def _run_timed_steps(step_fn, state, batch, warmup_steps: int, steps: int,
                     batch_iter=None, profile_dir: Optional[str] = None):
    """AOT-compile the exact step once, run warmup + the timed loop on
    that executable, and read its XLA FLOP count.

    Fencing is a host value pull (``float(loss)``), not
    ``block_until_ready``: on remote-tunneled platforms the ready bit
    of a dispatched chain can report early, and a loop fenced that way
    measures dispatch, not compute.

    ``batch_iter`` (optional) supplies a fresh same-shape batch per
    timed step — the real-data path (token shards through
    ``DevicePrefetcher``); without it the fixed ``batch`` repeats
    (synthetic mode, the reference's default).

    Returns (elapsed_s, compile_s, final_loss, flops_per_device).
    ``flops_per_device`` is ONE device's share for an SPMD-partitioned
    computation (XLA cost_analysis semantics); None if the backend
    can't report it.
    """
    compile_start = time.perf_counter()
    compiled = step_fn.lower(state, batch).compile()
    flops = None
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0]
        flops = float(analysis["flops"])
    except Exception:  # cost analysis is backend-dependent
        pass
    for _ in range(max(warmup_steps, 1)):
        state, metrics = compiled(state, batch)
    float(metrics["loss"])
    compile_s = time.perf_counter() - compile_start

    # Optional XPlane capture of exactly the timed steps (compile and
    # warmup stay out of the trace) — the dashboard's trace tab and
    # docs/profiling.md consume what lands here.
    if profile_dir:
        jax.profiler.start_trace(profile_dir)
    start = time.perf_counter()
    try:
        for _ in range(steps):
            if batch_iter is not None:
                batch = next(batch_iter)
            state, metrics = compiled(state, batch)
        final_loss = float(metrics["loss"])  # fence inside the trace
        elapsed = time.perf_counter() - start
    finally:
        if profile_dir:
            jax.profiler.stop_trace()
    return elapsed, compile_s, final_loss, flops


def _attach_mfu(result: Dict[str, float], flops_per_device: Optional[float],
                step_time_s: float, n_chips: int) -> None:
    """MFU is only meaningful against a TPU peak; skip on other
    backends (the CPU smoke path must not publish a fake MFU)."""
    if flops_per_device is None:
        return
    result["flops_per_step"] = flops_per_device * n_chips  # global
    if jax.devices()[0].platform != "tpu":
        return
    # Per-device share over one chip's peak: n_chips cancels.
    result["mfu_pct"] = round(
        flops_per_device / step_time_s / peak_flops_per_chip() * 100, 2)


def run_benchmark(config: BenchConfig) -> Dict[str, float]:
    """Returns {images_per_sec, images_per_sec_per_chip, step_time_ms, ...}."""
    entry = get_model(config.model)
    model = entry.make(**(config.model_kwargs or {}))
    input_shape = entry.input_spec[0]
    if config.image_size is not None:
        input_shape = (config.image_size, config.image_size, input_shape[-1])

    mesh = build_mesh(config.mesh)
    n_chips = mesh.size

    # Per-model lr overrides live on the registry entry (the no-norm
    # classics NaN at the BN-era 0.1 — models/classic_cnn.py).
    lr = (config.learning_rate if config.learning_rate is not None
          else (entry.bench_lr if entry.bench_lr is not None else 0.1))
    tx = optax.sgd(lr, momentum=config.momentum, nesterov=True)
    rng = jax.random.PRNGKey(config.seed)
    sample = jnp.zeros((1, *input_shape), jnp.bfloat16)
    # Jit the init: on remote-tunneled backends eager init dispatches
    # hundreds of tiny ops individually (minutes); compiled it is one.
    state = jax.jit(
        lambda r: create_train_state(model, tx, r, sample))(rng)
    state = place_state(mesh, state)
    batch = place_batch(
        mesh, synthetic_batch(config, entry.num_classes_or_vocab, input_shape, rng)
    )

    step_fn = make_train_step(mesh)
    elapsed, compile_s, final_loss, flops = _run_timed_steps(
        step_fn, state, batch, config.warmup_steps, config.steps,
        profile_dir=config.profile_dir)

    images_per_sec = config.batch_size * config.steps / elapsed
    result = {
        "model": config.model,
        "global_batch_size": config.batch_size,
        "n_chips": n_chips,
        "steps": config.steps,
        "images_per_sec": images_per_sec,
        "images_per_sec_per_chip": images_per_sec / n_chips,
        "step_time_ms": elapsed / config.steps * 1e3,
        "compile_plus_warmup_s": compile_s,
        "final_loss": final_loss,
    }
    _attach_mfu(result, flops, elapsed / config.steps, n_chips)
    return result


@dataclasses.dataclass
class LMBenchConfig:
    model: str = "bert-base"
    batch_size: int = 32
    seq_len: int = 512
    steps: int = 10
    warmup_steps: int = 2
    learning_rate: float = 1e-4
    objective: str = "mlm"
    seed: int = 0
    profile_dir: Optional[str] = None  # capture timed steps as XPlane


def run_lm_benchmark(config: LMBenchConfig) -> Dict[str, float]:
    """BERT/Llama pretraining step benchmark (BASELINE.md LM target).

    Single-process: the whole mesh is local (one chip on the bench
    runner, the 8-device CPU mesh in tests). Reports step time, tokens/
    sec, and MFU from XLA's FLOP count.
    """
    from kubeflow_tpu.training.lm import (
        create_lm_state,
        make_lm_train_step,
        place_lm_batch,
    )

    entry = get_model(config.model)
    model = entry.make()
    vocab = entry.num_classes_or_vocab
    mesh = build_mesh(None)
    n_chips = mesh.size
    rng = jax.random.PRNGKey(config.seed)
    ids_rng, label_rng, weight_rng, init_rng = jax.random.split(rng, 4)
    b, l = config.batch_size, config.seq_len
    batch = {"input_ids": jax.random.randint(ids_rng, (b, l), 0, vocab)}
    if config.objective == "mlm":
        batch["mlm_labels"] = jax.random.randint(label_rng, (b, l), 0, vocab)
        batch["mlm_weights"] = (
            jax.random.uniform(weight_rng, (b, l)) < 0.15).astype(jnp.float32)

    tx = optax.adamw(config.learning_rate)
    state, shardings = create_lm_state(model, tx, init_rng, batch, mesh=mesh)
    step_fn = make_lm_train_step(mesh, shardings,
                                 objective=config.objective)
    batch = place_lm_batch(mesh, batch)

    elapsed, compile_s, final_loss, flops = _run_timed_steps(
        step_fn, state, batch, config.warmup_steps, config.steps,
        profile_dir=config.profile_dir)
    step_time_s = elapsed / config.steps

    result = {
        "model": config.model,
        "global_batch_size": b,
        "seq_len": l,
        "n_chips": n_chips,
        "steps": config.steps,
        "step_time_ms": step_time_s * 1e3,
        "tokens_per_sec": b * l / step_time_s,
        "compile_plus_warmup_s": compile_s,
        "final_loss": final_loss,
    }
    _attach_mfu(result, flops, step_time_s, n_chips)
    return result


def _shard_batch_iter(data_paths, mesh, batch_size, seq_len, seed):
    """Token shards → per-host batches → device-placed iterator (the
    real-data path; ``training/data.py``)."""
    from kubeflow_tpu.training.data import (
        DevicePrefetcher,
        token_shard_batches,
    )

    stream = token_shard_batches(
        list(data_paths), batch_size, seq_len, seed=seed)
    return DevicePrefetcher(stream, mesh, prefetch=2)


@dataclasses.dataclass
class LoRABenchConfig:
    model: str = "llama2-7b"
    lora_rank: int = 16
    batch_size: int = 1
    seq_len: int = 1024
    steps: int = 5
    warmup_steps: int = 1
    learning_rate: float = 1e-4
    seed: int = 0
    data_paths: Optional[tuple] = None  # token shards; None → synthetic
    profile_dir: Optional[str] = None  # capture timed steps as XPlane


def run_lora_benchmark(config: LoRABenchConfig) -> Dict[str, float]:
    """LoRA fine-tune step benchmark (BASELINE.md stretch row:
    "Llama-2-7B fine-tune … v5e").

    What makes 7B fit one 16 GB chip: the base weights are frozen in
    bf16 (no grad/moment buffers — training/finetune.py), blocks
    rematerialize on the backward pass, and adapters (~0.1% of params)
    are the only train state. Reports step time, tokens/sec, MFU, and
    the trainable-parameter fraction.
    """
    from kubeflow_tpu.training.finetune import (
        create_lora_state,
        make_lora_train_step,
    )
    from kubeflow_tpu.training.lm import place_lm_batch

    entry = get_model(config.model)
    model = entry.make(lora_rank=config.lora_rank, remat=True)
    vocab = entry.num_classes_or_vocab
    mesh = build_mesh(None)
    n_chips = mesh.size
    rng = jax.random.PRNGKey(config.seed)
    ids_rng, init_rng = jax.random.split(rng)
    b, l = config.batch_size, config.seq_len
    batch = {"input_ids": jax.random.randint(ids_rng, (b, l), 0, vocab)}

    tx = optax.adamw(config.learning_rate)
    state, shardings = create_lora_state(
        model, tx, init_rng, batch, mesh=mesh, base_dtype=jnp.bfloat16)
    step_fn = make_lora_train_step(mesh, shardings)
    batch_iter = None
    try:
        if config.data_paths:
            batch_iter = _shard_batch_iter(
                config.data_paths, mesh, b, l, config.seed)
            batch = next(batch_iter)
        else:
            batch = place_lm_batch(mesh, batch)

        elapsed, compile_s, final_loss, flops = _run_timed_steps(
            step_fn, state, batch, config.warmup_steps, config.steps,
            batch_iter=batch_iter, profile_dir=config.profile_dir)
    finally:
        # An OOM in lowering or a shard-read error mid-loop must not
        # leak the prefetch thread and its device-resident batches.
        if batch_iter is not None:
            batch_iter.close()
    step_time_s = elapsed / config.steps

    n_base = sum(x.size for x in jax.tree.leaves(state.base_params))
    n_lora = sum(x.size for x in jax.tree.leaves(state.lora))
    result = {
        "model": config.model,
        "lora_rank": config.lora_rank,
        "global_batch_size": b,
        "seq_len": l,
        "n_chips": n_chips,
        "steps": config.steps,
        "step_time_ms": step_time_s * 1e3,
        "tokens_per_sec": b * l / step_time_s,
        "compile_plus_warmup_s": compile_s,
        "final_loss": final_loss,
        "base_params": n_base,
        "trainable_params": n_lora,
        "trainable_pct": round(n_lora / max(n_base, 1) * 100, 4),
    }
    _attach_mfu(result, flops, step_time_s, n_chips)
    return result


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog="tpu-cnn")
    parser.add_argument("--model", default="resnet50")
    parser.add_argument("--batch_size", type=int, default=None,
                        help="default: 128 (vision) / 32 (language)")
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--image_size", type=int, default=None)
    parser.add_argument("--seq_len", type=int, default=512)
    parser.add_argument("--lora_rank", type=int, default=0,
                        help=">0: LoRA fine-tune benchmark "
                             "(language models only)")
    parser.add_argument("--data", default=None,
                        help="token shards (.npy / raw .bin) for the "
                             "fine-tune path: comma-separated files, "
                             "dirs, or globs; gs://-style fsspec paths "
                             "download into a local cache. Default is "
                             "the reference-parity synthetic mode")
    parser.add_argument("--profile_dir", default=None,
                        help="capture the timed steps as an XPlane "
                             "trace under this dir (TensorBoard/XProf-"
                             "readable; surfaced by the dashboard's "
                             "trace tab — docs/profiling.md)")
    parser.add_argument("--bn_stat_rows", type=int, default=0,
                        help="ghost-BN statistics row cap for vision "
                             "models (0 = exact BN; single-chip "
                             "lever, see PERF.md)")
    parser.add_argument("--learning_rate", type=float, default=None,
                        help="vision sgd lr (default: 0.1, or 0.01 "
                             "for the no-BN classics vgg16/alexnet)")
    args = parser.parse_args(argv)
    from kubeflow_tpu.training.launcher import initialize_distributed
    from kubeflow_tpu.utils.platform import sync_platform_from_env

    # Honor JAX_PLATFORMS from the spawning process (a CPU-smoke
    # tpu-cnn job must not dispatch to a tunnel-registered TPU).
    sync_platform_from_env()
    # Multi-host bootstrap from the operator-injected KFT_* env. The
    # trainer CLI is the POD COMMAND of tpu-cnn jobs (the prototype
    # sets it directly — not via the launcher wrapper, whose
    # jax.distributed init would die with its own process anyway), so
    # the gang join must happen HERE: without it every host builds a
    # local-devices mesh and silently trains its own model copy.
    initialize_distributed()
    entry = get_model(args.model)
    if args.bn_stat_rows and entry.family != "vision":
        # Silently ignoring the flag would report an exact-BN number
        # as a ghost-BN one; models without BN fail loudly below.
        parser.error(
            f"--bn_stat_rows applies to vision models; {args.model!r} "
            f"is {entry.family}")
    if args.bn_stat_rows < 0:
        # GhostBatchNorm's `0 < stat_rows` guard would silently fall
        # back to exact BN — the same misreport, negative edition.
        parser.error(f"--bn_stat_rows must be >= 0; got "
                     f"{args.bn_stat_rows}")
    if args.learning_rate is not None and entry.family != "vision":
        # Only the vision config consumes it; silently measuring the
        # LM benchmarks at their hardcoded adamw lr while reporting
        # the flag's value is the same misreport class.
        parser.error(
            f"--learning_rate applies to vision models; {args.model!r} "
            f"is {entry.family}")
    if args.lora_rank > 0 and entry.family != "language":
        # Never fall through to the wrong benchmark: a tpu-finetune
        # job with a vision model must fail loudly, not run (and
        # report success for) a pretraining benchmark.
        parser.error(
            f"--lora_rank requires a language model; {args.model!r} is "
            f"{entry.family}")
    data_paths = None
    if args.data:
        if args.lora_rank <= 0:
            # Only the fine-tune path consumes shards today; silently
            # timing synthetic batches while the operator believes
            # real data was measured is the worst failure mode.
            parser.error("--data requires --lora_rank > 0")
        from kubeflow_tpu.training.data import resolve_shards

        try:
            data_paths = tuple(resolve_shards(args.data))
        except ValueError as e:
            parser.error(str(e))
    if entry.family == "language" and args.lora_rank > 0:
        result = run_lora_benchmark(
            LoRABenchConfig(model=args.model, lora_rank=args.lora_rank,
                            batch_size=args.batch_size or 1,
                            steps=args.steps, seq_len=args.seq_len,
                            data_paths=data_paths,
                            profile_dir=args.profile_dir))
    elif entry.family == "language":
        result = run_lm_benchmark(
            LMBenchConfig(model=args.model,
                          batch_size=args.batch_size or 32,
                          steps=args.steps, seq_len=args.seq_len,
                          profile_dir=args.profile_dir))
    else:
        result = run_benchmark(
            BenchConfig(model=args.model,
                        batch_size=args.batch_size or 128,
                        steps=args.steps, image_size=args.image_size,
                        profile_dir=args.profile_dir,
                        learning_rate=args.learning_rate,
                        model_kwargs=({"bn_stat_rows": args.bn_stat_rows}
                                      if args.bn_stat_rows else None))
        )
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
