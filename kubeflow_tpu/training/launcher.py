# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Pod entrypoint: distributed bootstrap + user-program supervision.

Replaces two reference pieces:

- ``tf-controller-examples/tf-cnn/launcher.py``: parsed the operator's
  injected ``TF_CONFIG`` into PS-architecture flags (``:64-77``),
  streamed the child's stdout to logs (``:29-54``), and slept forever
  after success so the operator wouldn't restart the pod (``:86-90``).
- ``grpc_tensorflow_server.py`` (referenced at
  ``kubeflow/core/tf-job.libsonnet:99``): the stock PS/worker server
  for replicas without a user binary.

TPU-native: the operator injects the ``jax.distributed`` bootstrap env
instead of TF_CONFIG —

  KFT_COORDINATOR_ADDRESS  host:port of process 0
  KFT_NUM_PROCESSES        gang size
  KFT_PROCESS_ID           this process's index
  KFT_REPLICA_TYPE/_INDEX  replica identity (chief detection)

``launch()`` initializes jax.distributed (the gRPC coordinator inside
jax replaces the stock PS server entirely), then either runs the user
command as a supervised subprocess or falls through to the benchmark
(the "stock server" equivalent: every replica runs the same SPMD
program).

Supervised-subprocess caveat: a jax.distributed runtime dies with its
process — the launcher's init does NOT transfer to a child command.
The in-tree trainer CLIs (pretrain, benchmark) therefore call
``initialize_distributed()`` themselves from the same env (which DOES
travel to the child), and prototypes set them as the pod command
directly; launcher-wrapping is for log supervision + the stock
benchmark fallthrough, not for providing the child's gang join.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import time
from typing import List, Optional, Sequence

logger = logging.getLogger(__name__)

ENV_COORD = "KFT_COORDINATOR_ADDRESS"
ENV_NPROC = "KFT_NUM_PROCESSES"
ENV_PID = "KFT_PROCESS_ID"
ENV_REPLICA_TYPE = "KFT_REPLICA_TYPE"
ENV_REPLICA_INDEX = "KFT_REPLICA_INDEX"
ENV_SLEEP = "KFT_SLEEP_ON_SUCCESS"

# Exit code for a preemption drain: the pod was told to terminate
# (SIGTERM — spot reclaim, maintenance, node drain), finished its
# in-flight step, wrote a checkpoint, and exited. Distinguishable from
# success (0) and from a crash (1, 134, 139, ...) so the operator can
# restart the slice WITHOUT burning a restart-budget slot — preemption
# is the platform's doing, not the job's. 77 is outside the shell/
# signal ranges (126+) and unused by Python/abseil conventions.
DRAIN_EXIT_CODE = 77


def distributed_config(env=os.environ) -> Optional[dict]:
    """The operator-injected gang description, or None (single host).

    Multi-slice (numSlices > 1) jobs describe ONE flat gang here —
    ``num_processes`` counts every worker across every slice, and
    ``process_id`` is the slice-major global index — while the
    MEGASCALE_* vars (read by :func:`slice_config` and by
    ``parallel.mesh.build_mesh`` for the ``dcn_data`` axis) carry the
    slice structure. jax.distributed wants the flat view; the mesh
    wants the hierarchy."""
    if ENV_COORD not in env:
        return None
    return {
        "coordinator_address": env[ENV_COORD],
        "num_processes": int(env.get(ENV_NPROC, "1")),
        "process_id": int(env.get(ENV_PID, "0")),
    }


def slice_config(env=os.environ) -> Optional[dict]:
    """The operator-injected multi-slice (megascale) description, or
    None for single-slice jobs (which carry no MEGASCALE_* vars)."""
    from kubeflow_tpu.parallel.mesh import (
        ENV_MEGASCALE_COORD,
        ENV_MEGASCALE_SLICE_ID,
        slice_count_from_env,
    )

    num_slices = slice_count_from_env(env)
    if num_slices <= 1:
        return None
    return {
        "num_slices": num_slices,
        "slice_id": int(env.get(ENV_MEGASCALE_SLICE_ID, "0")),
        "coordinator_address": env.get(ENV_MEGASCALE_COORD),
    }


def _distributed_initialized(jax) -> bool:
    """Whether jax.distributed.initialize already ran in this
    process. ``jax.distributed.is_initialized`` only exists from
    jax 0.4.39; older versions expose the same fact as the private
    global state's client handle."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    from jax._src import distributed

    state = getattr(distributed, "global_state", None)
    return getattr(state, "client", None) is not None


def initialize_distributed(env=os.environ) -> bool:
    """jax.distributed.initialize from env; True if multi-process.

    Idempotent within a process: the launcher's no-argv fallthrough
    initializes and then runs the benchmark CLI in-process, whose own
    call must be a no-op (a second initialize raises)."""
    config = distributed_config(env)
    if config is None:
        logger.info("single-process run (no %s)", ENV_COORD)
        return False
    if config["num_processes"] <= 1:
        logger.info("single-process run (%s=1)", ENV_NPROC)
        return False
    import jax

    if _distributed_initialized(jax):
        logger.info("jax.distributed already initialized; skipping")
        return True

    slices = slice_config(env)
    if slices:
        logger.info(
            "multi-slice gang: slice %d of %d (megascale coordinator "
            "%s); mesh dcn_data axis comes from the env",
            slices["slice_id"], slices["num_slices"],
            slices["coordinator_address"])
    if (env.get("JAX_PLATFORMS") or "").strip().lower() == "cpu":
        # CPU gangs (operator `simulateTpu` mode, hermetic multi-
        # process tests) need an explicit cross-host collectives
        # transport — without it this jaxlib answers every multi-
        # process computation with "not implemented on the CPU
        # backend". Must happen BEFORE any backend touch; newer jax
        # versions default to gloo and ignore the re-set.
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:  # noqa: BLE001 — flag renamed/absent
            logger.info("jax_cpu_collectives_implementation not "
                        "settable; relying on the version default")
    logger.info("jax.distributed.initialize(%s, num_processes=%d, "
                "process_id=%d)", config["coordinator_address"],
                config["num_processes"], config["process_id"])
    jax.distributed.initialize(
        coordinator_address=config["coordinator_address"],
        num_processes=config["num_processes"],
        process_id=config["process_id"],
    )
    return True


def run_and_stream(command: Sequence[str]) -> int:
    """Run the user program, streaming its output into our logs
    (parity: reference launcher.py:29-54)."""
    logger.info("running: %s", " ".join(command))
    process = subprocess.Popen(
        list(command), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, errors="replace")
    assert process.stdout is not None
    for line in process.stdout:
        logger.info("%s", line.rstrip("\n"))
    process.wait()
    logger.info("command exited with %d", process.returncode)
    return process.returncode


def launch(argv: Optional[List[str]] = None, env=os.environ) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(levelname)s|%(asctime)s|%(pathname)s|%(lineno)d| %(message)s",
        datefmt="%Y-%m-%dT%H:%M:%S",
    )
    argv = list(sys.argv[1:] if argv is None else argv)
    from kubeflow_tpu.utils.platform import sync_platform_from_env

    sync_platform_from_env()
    if argv:
        # The CHILD owns the gang join (the env travels to it; the
        # in-tree trainer CLIs call initialize_distributed on boot).
        # A parent-side init here would collide with the child's join
        # — same process_id, same coordinator bind — hanging the gang
        # (r5 review finding).
        rc = run_and_stream(argv)
    else:
        # No user binary → run the stock SPMD benchmark (the TPU
        # analogue of the stock grpc_tensorflow_server) in-process:
        # init here; the CLI's own call no-ops (idempotence guard).
        initialize_distributed(env)
        from kubeflow_tpu.training.benchmark import main as bench_main

        rc = bench_main([])
    if rc == 0 and env.get(ENV_SLEEP, "").lower() in ("1", "true", "yes"):
        # Parity escape hatch with the reference's sleep-forever-on-
        # success (launcher.py:86-90) for operators that would restart
        # completed pods. The kubeflow_tpu operator tracks completion
        # via terminationPolicy, so this is off by default.
        logger.info("success; sleeping forever (%s set)", ENV_SLEEP)
        while True:
            time.sleep(3600)
    return rc


if __name__ == "__main__":
    raise SystemExit(launch())
