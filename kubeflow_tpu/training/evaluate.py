# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Held-out evaluation: loss / perplexity / accuracy over a stream.

The trainers (training/lm.py, training/finetune.py) report *training*
metrics; this is the eval side — a no-grad jitted step accumulating
weighted sums so the reported numbers are exact over the stream, not
means-of-means across ragged batches. Works with the same batch dicts
the trainers consume (mlm or causal) and with either a plain params
tree or a params+lora pair (evaluating a fine-tune without merging).

The reference's only eval artifact was a notebook accuracy print
(user_guide.md MNIST flow); this is the library-grade equivalent for
the LM families.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import optax

from kubeflow_tpu.training.lm import Batch, _model_args, lm_targets


@functools.partial(jax.jit, static_argnames=("apply_fn", "objective"))
def _eval_sums(apply_fn, variables, batch, objective: str):
    """Returns (sum weighted CE, sum weights, sum weighted correct).

    Target/weight selection comes from :func:`lm_targets` — the same
    rules the training losses use, so train and eval can never
    disagree about batch conventions (incl. pre-shifted ``targets``).
    """
    logits = apply_fn(variables, *_model_args(batch))
    logits, targets, weights = lm_targets(logits, batch, objective)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    correct = (jnp.argmax(logits, -1) == targets).astype(jnp.float32)
    return ((ce * weights).sum(), weights.sum(), (correct * weights).sum())


def _accumulate(batches: Iterator[Batch], step, max_batches):
    """Sum a per-batch tuple of device scalars over the stream.

    Accumulates as device values: a float() per batch would fence
    every step and serialize the eval loop; the caller pulls host
    values once at the end.
    """
    totals = None
    n = 0
    for batch in batches:
        sums = step(batch)
        totals = sums if totals is None else tuple(
            a + b for a, b in zip(totals, sums))
        n += 1
        if max_batches is not None and n >= max_batches:
            break
    return totals, n


@functools.partial(jax.jit, static_argnames=("apply_fn",))
def _vision_eval_sums(apply_fn, variables, batch):
    logits = apply_fn(variables, batch["inputs"], train=False)
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["labels"])
    correct = (jnp.argmax(logits, -1) == batch["labels"]).astype(
        jnp.float32)
    n = jnp.asarray(batch["labels"].shape[0], jnp.float32)
    return ce.sum(), n, correct.sum()


def evaluate_vision(
    apply_fn: Any,
    variables: Dict[str, Any],
    batches: Iterator[Batch],
    *,
    max_batches: Optional[int] = None,
) -> Dict[str, float]:
    """Exact top-1 accuracy + mean CE over an image stream (the eval
    side of training/train.py; eval-mode BN uses the running
    statistics in ``variables["batch_stats"]``). Batches are the
    trainer's {"inputs", "labels"} dicts — e.g. from
    :func:`~kubeflow_tpu.training.data.image_shard_batches`."""
    totals, n_batches = _accumulate(
        batches,
        lambda b: _vision_eval_sums(apply_fn, variables, b),
        max_batches)
    if n_batches == 0:
        raise ValueError("evaluation stream produced no examples")
    total_ce, total_n, total_correct = (float(t) for t in totals)
    return {
        "loss": total_ce / total_n,
        "accuracy": total_correct / total_n,
        "examples": total_n,
        "batches": float(n_batches),
    }


def evaluate_lm(
    apply_fn: Any,
    variables: Dict[str, Any],
    batches: Iterator[Batch],
    *,
    objective: str = "causal",
    max_batches: Optional[int] = None,
) -> Dict[str, float]:
    """Exact aggregate metrics over ``batches`` (or the first
    ``max_batches`` of them). ``variables`` is the dict the model
    applies with — ``{"params": p}`` or ``{"params": p, "lora": l}``
    for an unmerged fine-tune."""
    totals, n = _accumulate(
        batches,
        lambda b: _eval_sums(apply_fn, variables, b, objective),
        max_batches)
    if n == 0:
        raise ValueError("evaluation stream produced no weighted tokens")
    total_ce, total_w, total_correct = (float(t) for t in totals)
    if total_w == 0:
        raise ValueError("evaluation stream produced no weighted tokens")
    loss = total_ce / total_w
    return {
        "loss": loss,
        "perplexity": math.exp(min(loss, 80.0)),  # overflow guard
        "accuracy": total_correct / total_w,
        "tokens": total_w,
        "batches": float(n),
    }
