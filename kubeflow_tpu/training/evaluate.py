"""Held-out evaluation: loss / perplexity / accuracy over a stream.

The trainers (training/lm.py, training/finetune.py) report *training*
metrics; this is the eval side — a no-grad jitted step accumulating
weighted sums so the reported numbers are exact over the stream, not
means-of-means across ragged batches. Works with the same batch dicts
the trainers consume (mlm or causal) and with either a plain params
tree or a params+lora pair (evaluating a fine-tune without merging).

The reference's only eval artifact was a notebook accuracy print
(user_guide.md MNIST flow); this is the library-grade equivalent for
the LM families.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import optax

from kubeflow_tpu.training.lm import Batch, _model_args, lm_targets


@functools.partial(jax.jit, static_argnames=("apply_fn", "objective"))
def _eval_sums(apply_fn, variables, batch, objective: str):
    """Returns (sum weighted CE, sum weights, sum weighted correct).

    Target/weight selection comes from :func:`lm_targets` — the same
    rules the training losses use, so train and eval can never
    disagree about batch conventions (incl. pre-shifted ``targets``).
    """
    logits = apply_fn(variables, *_model_args(batch))
    logits, targets, weights = lm_targets(logits, batch, objective)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    correct = (jnp.argmax(logits, -1) == targets).astype(jnp.float32)
    return ((ce * weights).sum(), weights.sum(), (correct * weights).sum())


def evaluate_lm(
    apply_fn: Any,
    variables: Dict[str, Any],
    batches: Iterator[Batch],
    *,
    objective: str = "causal",
    max_batches: Optional[int] = None,
) -> Dict[str, float]:
    """Exact aggregate metrics over ``batches`` (or the first
    ``max_batches`` of them). ``variables`` is the dict the model
    applies with — ``{"params": p}`` or ``{"params": p, "lora": l}``
    for an unmerged fine-tune."""
    # Accumulate as device scalars: a float() per batch would fence
    # every step and serialize the eval loop; one pull at the end
    # lets dispatch pipeline ahead of the device.
    total_ce = total_w = total_correct = None
    n = 0
    for batch in batches:
        ce, w, correct = _eval_sums(apply_fn, variables, batch, objective)
        if total_ce is None:
            total_ce, total_w, total_correct = ce, w, correct
        else:
            total_ce, total_w, total_correct = (
                total_ce + ce, total_w + w, total_correct + correct)
        n += 1
        if max_batches is not None and n >= max_batches:
            break
    if n == 0:
        raise ValueError("evaluation stream produced no weighted tokens")
    total_ce = float(total_ce)
    total_w = float(total_w)
    total_correct = float(total_correct)
    if total_w == 0:
        raise ValueError("evaluation stream produced no weighted tokens")
    loss = total_ce / total_w
    return {
        "loss": loss,
        "perplexity": math.exp(min(loss, 80.0)),  # overflow guard
        "accuracy": total_correct / total_w,
        "tokens": total_w,
        "batches": float(n),
    }
