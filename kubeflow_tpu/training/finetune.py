# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Parameter-efficient fine-tuning: frozen base + trainable LoRA.

The BASELINE.md stretch row ("Llama-2-7B fine-tune … v5e") needs a
trainer where the base model contributes **no gradient buffers and no
optimizer moments** — that is what makes 7B fit a 16 GB chip:

    full fine-tune:  params + grads + 2×adam moments ≈ 4× param bytes
    LoRA fine-tune:  params (frozen, bf16) + ~0.1% adapter state

Mechanics: the adapters live in the flax ``"lora"`` collection
(ops/lora.py), so ``jax.value_and_grad`` here differentiates *only*
the adapter tree — XLA dead-code-eliminates every ``dW`` matmul of the
frozen kernels on the backward pass (the structural guarantee; the
optax.masked alternative would still materialize full-size grads).

Sharding follows the same logical-axis rule table as pretraining
(parallel/tensor_parallel.py): adapters annotate ``(in_axis, "lora")``
/ ``("lora", out_axis)``, so under a (data, fsdp, tensor) mesh the
skinny A/B factors shard alongside their frozen kernels while the
rank axis replicates.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel.tensor_parallel import rules_for
from kubeflow_tpu.training.lm import (
    LOSSES,
    Batch,
    _model_args,
    accumulated_value_and_grad,
    jit_train_step,
    lm_forward_with_aux,
    sharded_collection_init,
    sharded_opt_init,
)


class LoRAState(struct.PyTreeNode):
    """Train state where only ``lora`` (and its moments) update."""

    step: jax.Array
    base_params: Any  # frozen
    lora: Any  # trainable adapters
    opt_state: optax.OptState  # moments over ``lora`` only
    apply_fn: Any = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)


def create_lora_state(
    model: Any,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    sample_batch: Batch,
    mesh: Optional[Mesh] = None,
    rules: Optional[Mapping[str, Any]] = None,
    base_dtype: Any = None,
) -> Tuple[LoRAState, Optional[LoRAState]]:
    """Build (state, state_shardings) for a ``lora_rank > 0`` model.

    ``base_dtype=jnp.bfloat16`` stores the frozen weights in bf16 —
    halves the resident footprint vs flax's f32 param default, and is
    lossless for training since the base never receives updates. The
    cast happens inside the init jit, so per-tensor f32 temporaries
    are freed as each param is produced (no 2× peak).
    """

    from kubeflow_tpu.utils.trees import cast_floating

    def cast_base(split):
        params, lora = split
        if base_dtype is not None:
            params = cast_floating(params, base_dtype)
        return params, lora

    if mesh is None:
        def init_split(rng):
            variables = model.init(rng, *_model_args(sample_batch))
            return cast_base((nn.meta.unbox(variables["params"]),
                              nn.meta.unbox(variables["lora"])))

        params, lora = jax.jit(init_split)(rng)
        state = LoRAState(
            step=jnp.zeros((), jnp.int32),
            base_params=params, lora=lora, opt_state=tx.init(lora),
            apply_fn=model.apply, tx=tx)
        return state, None

    rules = rules_for(mesh, rules)
    (params, lora), (params_sh, lora_sh) = sharded_collection_init(
        model, rng, sample_batch, mesh, rules,
        split_fn=lambda v: (v["params"], v["lora"]),
        transform_fn=cast_base)
    opt_state, opt_sh = sharded_opt_init(tx, lora, lora_sh, mesh)
    replicated = NamedSharding(mesh, P())

    state = LoRAState(
        step=jnp.zeros((), jnp.int32),
        base_params=params, lora=lora, opt_state=opt_state,
        apply_fn=model.apply, tx=tx)
    shardings = LoRAState(
        step=replicated,
        base_params=params_sh, lora=lora_sh, opt_state=opt_sh,
        apply_fn=model.apply, tx=tx)
    return state, shardings


def make_lora_train_step(
    mesh: Optional[Mesh],
    shardings: Optional[LoRAState],
    *,
    objective: str = "causal",
    donate: bool = True,
    aux_loss_weight: float = 0.01,
    grad_accum: int = 1,
):
    """Jitted SPMD step: grads and updates over ``state.lora`` only.

    Auxiliary losses sown into the ``"losses"`` collection (the MoE
    load-balance loss, ops/moe.py) are collected and weighted exactly
    as in the pretraining step — a LoRA fine-tune of an MoE model must
    keep routing-balance pressure even though the router is frozen.
    ``grad_accum`` > 1 runs sequential microbatches
    (lm.accumulated_value_and_grad) — with the frozen base already
    memory-cheap, this is the lever for long-sequence fine-tunes.
    """
    loss_fn = LOSSES[objective]

    def step(state: LoRAState, batch: Batch):
        def compute(lora, mb):
            return lm_forward_with_aux(
                state.apply_fn,
                {"params": state.base_params, "lora": lora},
                mb, loss_fn, aux_loss_weight)

        (loss, acc, aux), grads = accumulated_value_and_grad(
            compute, state.lora, batch, grad_accum, objective)
        updates, new_opt = state.tx.update(grads, state.opt_state,
                                           state.lora)
        new_lora = optax.apply_updates(state.lora, updates)
        metrics = {
            "loss": loss,
            "accuracy": acc,
            "aux_loss": aux,
            "grad_norm": optax.global_norm(grads),
        }
        return (
            state.replace(step=state.step + 1, lora=new_lora,
                          opt_state=new_opt),
            metrics,
        )

    return jit_train_step(step, mesh, shardings, donate)
