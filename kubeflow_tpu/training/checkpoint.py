# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Checkpoint/resume — the recovery unit for whole-slice restarts.

The reference had *no* training checkpointing (SURVEY §5: tf-cnn ran
synthetic data, model saved in-container only) because its PS replicas
restarted independently. A TPU slice fails as a unit — the operator's
gang kernel answers any worker loss with RESTART_SLICE
(``native/kft_runtime.cc`` ``kft_gang_decide``) — so restart-from-
checkpoint is load-bearing, not optional: every replica comes back,
restores the latest step, and training resumes.

Built on Orbax:
- Sharded-aware: arrays restore directly into their NamedShardings
  (each host reads only its shards — no replicated gather).
- Async save: the device→host copy blocks the step loop; the disk
  write does not.
- ``keep`` + atomic finalization: a killed pod never leaves a corrupt
  latest checkpoint (Orbax commits via rename).
"""

from __future__ import annotations

import dataclasses
import logging
from pathlib import Path
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class CheckpointConfig:
    directory: str
    save_interval_steps: int = 1000
    keep: int = 3
    async_save: bool = True


class Checkpointer:
    """Save/restore a TrainState/LMState-shaped pytree.

    Only array leaves are checkpointed (``apply_fn``/``tx`` are static
    fields rebuilt by the caller); restore takes the freshly-built
    state as the abstract target so shapes, dtypes, and shardings all
    come from the live program, never from disk.
    """

    def __init__(self, config: CheckpointConfig):
        self.config = config
        path = Path(config.directory).resolve()
        path.mkdir(parents=True, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            save_interval_steps=config.save_interval_steps,
            max_to_keep=config.keep,
            enable_async_checkpointing=config.async_save,
        )
        self._manager = ocp.CheckpointManager(path, options=options)

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Save if the interval policy says so (or ``force``)."""
        if step in self._manager.all_steps():
            return False
        saved = self._manager.save(
            step,
            args=ocp.args.StandardSave(jax.tree.map(lambda x: x, state)),
            force=force,
        )
        if saved:
            logger.info("checkpoint saved at step %d", step)
        return saved

    def restore(self, state: Any, step: Optional[int] = None) -> Any:
        """Restore into the sharding/structure of ``state``.

        Returns ``state`` untouched if no checkpoint exists (fresh
        start) — the launcher calls this unconditionally on boot, which
        is exactly the whole-slice recovery path: first boot restores
        nothing, a gang restart restores the latest step.
        """
        step = self.latest_step() if step is None else step
        if step is None:
            logger.info("no checkpoint in %s; fresh start",
                        self.config.directory)
            return state
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state)
        restored = self._manager.restore(
            step, args=ocp.args.StandardRestore(abstract)
        )
        logger.info("restored checkpoint step %d from %s", step,
                    self.config.directory)
        return restored

    def restore_raw(self, step: Optional[int] = None) -> Any:
        """Restore the checkpoint's own structure (plain arrays) with
        no live-state target — the export path's entry point
        (serving/export_cli.py), where only a subtree (e.g. the LoRA
        adapters) is wanted and the saver's optimizer state need not
        be reconstructible. Returns None if no checkpoint exists."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        return self._manager.restore(step)

    def wait(self) -> None:
        """Block until pending async saves are durable (call before
        declaring job success)."""
        self._manager.wait_until_finished()

    def close(self) -> None:
        self._manager.close()
