# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Checkpoint/resume — the recovery unit for whole-slice restarts
AND the continuous sharded checkpoints elastic resizes restore from.

The reference had *no* training checkpointing (SURVEY §5: tf-cnn ran
synthetic data, model saved in-container only) because its PS replicas
restarted independently. A TPU slice fails as a unit — the operator's
gang kernel answers any worker loss with RESTART_SLICE
(``native/kft_runtime.cc`` ``kft_gang_decide``) — so restart-from-
checkpoint is load-bearing, not optional: every replica comes back,
restores the latest step, and training resumes.

Two tiers:

- :class:`Checkpointer` (Orbax) — the monolithic periodic tier.
  Sharded-aware (arrays restore directly into their NamedShardings),
  async save, ``keep`` + atomic finalization (Orbax commits via
  rename). ``restore`` additionally SKIPS a corrupt/truncated latest
  step (falls back to the previous one with a warning) — recovery
  must never die on the artifact of the crash it is recovering from.

- :class:`ShardedCheckpointer` (r16) — continuous per-host shard
  writes of the FULL train state (params + optimizer moments + step)
  every N steps, generalizing the r14 ``serving/sharding.py``
  per-shard msgpack format to training state. Each host writes its
  contiguous slice of every shardable leaf to its own file (temp +
  fsync + atomic rename), and the manifest — which records the
  dp/fsdp mesh shape and the per-leaf split plan — commits LAST,
  only after every host's shard is durable: a writer killed
  mid-shard-write can never yield a restorable-but-wrong state
  (manifest absent ⇒ step invisible). Restore reassembles the full
  leaves on host and places them onto the LIVE state's shardings via
  ``jax.device_put`` — so restoring a 4-host checkpoint into a
  3-host (or 2-host) dp/fsdp mesh re-slices the optimizer state onto
  the surviving topology. That is the elastic-gang recovery path:
  seconds of replay from the last continuous step, not minutes of
  full-checkpoint reload.

Wait discipline: this module's background writer runs under the
operator-grade lint rules (scripts/lint.py
check_operator_wait_discipline) — monotonic clocks only, every wait
bounded.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
import shutil
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class CheckpointConfig:
    directory: str
    save_interval_steps: int = 1000
    keep: int = 3
    async_save: bool = True


class Checkpointer:
    """Save/restore a TrainState/LMState-shaped pytree.

    Only array leaves are checkpointed (``apply_fn``/``tx`` are static
    fields rebuilt by the caller); restore takes the freshly-built
    state as the abstract target so shapes, dtypes, and shardings all
    come from the live program, never from disk.
    """

    def __init__(self, config: CheckpointConfig):
        self.config = config
        path = Path(config.directory).resolve()
        path.mkdir(parents=True, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            save_interval_steps=config.save_interval_steps,
            max_to_keep=config.keep,
            enable_async_checkpointing=config.async_save,
        )
        self._manager = ocp.CheckpointManager(path, options=options)

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Save if the interval policy says so (or ``force``)."""
        if step in self._manager.all_steps():
            return False
        saved = self._manager.save(
            step,
            args=ocp.args.StandardSave(jax.tree.map(lambda x: x, state)),
            force=force,
        )
        if saved:
            logger.info("checkpoint saved at step %d", step)
        return saved

    def restore(self, state: Any, step: Optional[int] = None) -> Any:
        """Restore into the sharding/structure of ``state``.

        Returns ``state`` untouched if no checkpoint exists (fresh
        start) — the launcher calls this unconditionally on boot, which
        is exactly the whole-slice recovery path: first boot restores
        nothing, a gang restart restores the latest step.

        Corrupt-step fallback (r16 hardening): a truncated/garbled
        step — the typical artifact of the very crash this restore is
        recovering from — is SKIPPED with a warning and the previous
        step restores instead of the whole recovery raising. An
        explicitly-requested ``step`` still raises (the caller asked
        for that step, not "the freshest usable one").
        """
        if step is not None:
            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct,
                                    state)
            restored = self._manager.restore(
                step, args=ocp.args.StandardRestore(abstract))
            logger.info("restored checkpoint step %d from %s", step,
                        self.config.directory)
            return restored
        steps = sorted(self._manager.all_steps())
        if not steps:
            logger.info("no checkpoint in %s; fresh start",
                        self.config.directory)
            return state
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state)
        for candidate in reversed(steps):
            try:
                restored = self._manager.restore(
                    candidate, args=ocp.args.StandardRestore(abstract))
            except Exception:  # noqa: BLE001 — any torn artifact
                logger.warning(
                    "checkpoint step %d in %s is corrupt/unreadable; "
                    "falling back to the previous step", candidate,
                    self.config.directory, exc_info=True)
                continue
            logger.info("restored checkpoint step %d from %s",
                        candidate, self.config.directory)
            return restored
        logger.warning("every checkpoint step in %s is unreadable; "
                       "fresh start", self.config.directory)
        return state

    def restore_raw(self, step: Optional[int] = None) -> Any:
        """Restore the checkpoint's own structure (plain arrays) with
        no live-state target — the export path's entry point
        (serving/export_cli.py), where only a subtree (e.g. the LoRA
        adapters) is wanted and the saver's optimizer state need not
        be reconstructible. Returns None if no checkpoint exists."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        return self._manager.restore(step)

    def wait(self) -> None:
        """Block until pending async saves are durable (call before
        declaring job success)."""
        self._manager.wait_until_finished()

    def close(self) -> None:
        self._manager.close()


# -- continuous sharded checkpointing (r16) -------------------------------

MANIFEST_FORMAT = 1
MANIFEST_FILE = "manifest.json"
STEP_DIR_FMT = "step-{step:08d}"
_STEP_DIR_RE = re.compile(r"^step-(\d{8})$")
SHARD_FILE_FMT = "state.shard-{i:05d}-of-{n:05d}.msgpack"


def atomic_write_bytes(path: Path, data: bytes) -> None:
    """temp file + fsync + atomic rename: after os.replace returns,
    the path holds either the OLD content or the complete NEW bytes —
    never a truncation. The temp name carries the pid so concurrent
    hosts on a shared mount can't collide mid-write."""
    tmp = path.with_name(f".{path.name}.tmp-{os.getpid()}")
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _path_key(path: Tuple[Any, ...]) -> str:
    """One flat ``"/"``-joined key per tree path (DictKey /
    GetAttrKey / SequenceKey all reduce to their payload), matching
    the serving/sharding.py flat-key idiom."""
    parts = []
    for entry in path:
        for attr in ("key", "name", "idx"):
            if hasattr(entry, attr):
                parts.append(str(getattr(entry, attr)))
                break
        else:
            parts.append(str(entry))
    return "/".join(parts)


def flatten_state(state: Any) -> Tuple[Dict[str, Any], Any]:
    """(flat key → leaf, treedef) for a train-state pytree."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    out: Dict[str, Any] = {}
    for path, leaf in flat:
        key = _path_key(path)
        if key in out:
            raise ValueError(f"duplicate flat key {key!r} in state")
        out[key] = leaf
    return out, treedef


@dataclasses.dataclass
class ContinuousCheckpointConfig:
    """Knobs for the continuous sharded tier (docs/user_guide.md).

    ``num_hosts``/``host_id`` come from the gang env
    (``jax.process_count()``/``process_index()``) in production; tests
    emulate an N-host gang with N checkpointer instances over one
    directory. ``mesh_shape`` is recorded in the manifest for the
    restore-time reshard bookkeeping (dp/fsdp factorization)."""

    directory: str
    save_interval_steps: int = 10
    keep: int = 3
    num_hosts: int = 1
    host_id: int = 0
    async_save: bool = True
    commit_timeout_seconds: float = 30.0
    min_shard_size: int = 1024
    mesh_shape: Optional[Dict[str, int]] = None


class ShardedCheckpointer:
    """Continuous per-host shard writes of the full train state.

    Write protocol (crash-safe by construction):

    1. every host snapshots device→host (the only step-loop stall)
       and hands the write to its background thread (``async_save``);
    2. each host writes ITS contiguous slice of every shardable leaf
       to ``state.shard-<i>-of-<n>.msgpack`` via temp+fsync+rename
       (replicated/indivisible leaves live whole in shard 0);
    3. host 0 commits ``manifest.json`` LAST, only once every shard
       file of the step exists — a step without a manifest does not
       exist to ``restore``, so a writer killed mid-shard can never
       yield a torn restore.

    Restore reassembles the full leaves (concat along each leaf's
    recorded dim) and places them onto the LIVE state's shardings —
    restoring into a smaller/larger dp/fsdp mesh re-slices params and
    optimizer moments onto the new topology (the elastic-resize
    path). ``restore`` walks committed steps newest-first and skips
    unreadable ones."""

    def __init__(self, config: ContinuousCheckpointConfig):
        if config.num_hosts < 1:
            raise ValueError("num_hosts must be >= 1")
        if not 0 <= config.host_id < config.num_hosts:
            raise ValueError(
                f"host_id {config.host_id} outside "
                f"[0, {config.num_hosts})")
        self.config = config
        self.root = Path(config.directory).resolve()
        self.root.mkdir(parents=True, exist_ok=True)
        self._stop = threading.Event()
        # Depth-1 work slot, newest-wins: only the FRESHEST committed
        # step matters for restore, so a writer that falls behind a
        # slow mount (or a commit barrier waiting out a lagging peer)
        # coalesces snapshots instead of queueing full train-state
        # copies without bound.
        self._slot: Optional[Tuple[int, Dict[str, np.ndarray],
                                   Dict[str, Dict[str, int]]]] = None
        self._slot_lock = threading.Lock()
        self._writing = False
        self._idle = threading.Event()
        self._idle.set()
        self._dropped = 0
        self._last_saved: Optional[int] = None
        self._writer: Optional[threading.Thread] = None
        if config.async_save:
            self._writer = threading.Thread(
                target=self._writer_loop,
                name=f"ckpt-writer-{config.host_id}", daemon=True)
            self._writer.start()

    # -- layout -----------------------------------------------------------

    def _step_dir(self, step: int) -> Path:
        return self.root / STEP_DIR_FMT.format(step=step)

    def _shard_file(self, step: int, host: int) -> Path:
        return self._step_dir(step) / SHARD_FILE_FMT.format(
            i=host, n=self.config.num_hosts)

    def all_steps(self) -> List[int]:
        """COMMITTED steps (manifest present), ascending."""
        steps = []
        for child in self.root.iterdir() if self.root.is_dir() else ():
            match = _STEP_DIR_RE.match(child.name)
            if match and (child / MANIFEST_FILE).is_file():
                steps.append(int(match.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- save -------------------------------------------------------------

    def _plan(self, flat: Dict[str, Any]) -> Dict[str, Dict[str, int]]:
        """Per-leaf split decision: the first dim divisible by
        num_hosts on a large-enough leaf; everything else replicates
        into shard 0. Deterministic from shapes alone, so every host
        computes the identical plan with no collective."""
        n = self.config.num_hosts
        plan: Dict[str, Dict[str, int]] = {}
        if n == 1:
            return plan
        for key, leaf in flat.items():
            shape = getattr(leaf, "shape", ())
            size = int(np.prod(shape)) if shape else 1
            if size < self.config.min_shard_size:
                continue
            for dim, width in enumerate(shape):
                if width % n == 0 and width >= n:
                    plan[key] = {"dim": dim}
                    break
        return plan

    @staticmethod
    def _host_view(leaf: Any) -> np.ndarray:
        """The GLOBAL value of a leaf on this host. Fully-addressable
        arrays (single-process, or replicated) are a plain
        device→host copy; a multi-process sharded array is
        all-gathered first — save() is called at the SAME step on
        every host (the fit loop's cadence is deterministic), so the
        collective lines up. Reading only each host's addressable
        slice (no gather) is the scale optimization this format
        already supports; the gather keeps the plan independent of
        the device placement."""
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            from jax.experimental import multihost_utils

            return np.asarray(
                multihost_utils.process_allgather(leaf, tiled=True))
        return np.asarray(jax.device_get(leaf))

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Snapshot + hand this host's shard write to the writer;
        True if a save was scheduled. The device→host snapshot
        happens here (the step loop pays only that); the disk write
        overlaps compute on the writer thread. Newest-wins: a
        snapshot handed over while the writer is still busy REPLACES
        any not-yet-written one (only the freshest step matters for
        restore — an unbounded backlog of full-state copies must
        never build up behind a slow mount)."""
        interval = max(1, int(self.config.save_interval_steps))
        if not force and step % interval != 0:
            return False
        if self._last_saved == step:
            return False
        flat, _ = flatten_state(state)
        host_flat: Dict[str, np.ndarray] = {}
        plan = self._plan(flat)
        host = self.config.host_id
        n = self.config.num_hosts
        for key, leaf in flat.items():
            value = self._host_view(leaf)
            entry = plan.get(key)
            if entry is None:
                if host == 0:
                    host_flat[key] = value
                continue
            dim = entry["dim"]
            width = value.shape[dim] // n
            sl = [slice(None)] * value.ndim
            sl[dim] = slice(host * width, (host + 1) * width)
            host_flat[key] = np.ascontiguousarray(value[tuple(sl)])
        self._last_saved = step
        item = (step, host_flat, plan)
        if self._writer is None:
            self._write_one(item)
        else:
            with self._slot_lock:
                if self._slot is not None:
                    self._dropped += 1
                    logger.warning(
                        "continuous checkpoint writer behind; "
                        "dropping unwritten step %d for step %d",
                        self._slot[0], step)
                self._slot = item
                self._idle.clear()
        return True

    def _writer_loop(self) -> None:
        while not self._stop.is_set():
            with self._slot_lock:
                item = self._slot
                self._slot = None
                self._writing = item is not None
            if item is None:
                self._idle.set()
                self._stop.wait(0.05)
                continue
            try:
                self._write_one(item)
            except Exception:  # noqa: BLE001 — a failed continuous
                # save must never kill training; the next interval
                # retries and the periodic tier still covers recovery.
                logger.exception("continuous checkpoint write failed")
            finally:
                with self._slot_lock:
                    self._writing = False
                    if self._slot is None:
                        self._idle.set()

    def _write_one(self, item: Tuple[int, Dict[str, np.ndarray],
                                     Dict[str, Dict[str, int]]]) -> None:
        from flax import serialization

        step, host_flat, plan = item
        step_dir = self._step_dir(step)
        step_dir.mkdir(parents=True, exist_ok=True)
        atomic_write_bytes(
            self._shard_file(step, self.config.host_id),
            serialization.msgpack_serialize(host_flat))
        if self.config.host_id == 0:
            self._commit(step, plan)

    def _commit(self, step: int,
                plan: Dict[str, Dict[str, int]]) -> None:
        """Manifest-last commit, gated on EVERY host's shard being
        durable (filesystem barrier on the shared mount, bounded by
        ``commit_timeout_seconds`` — peers that never show leave the
        step uncommitted, which restore simply never sees)."""
        n = self.config.num_hosts
        deadline = time.monotonic() + self.config.commit_timeout_seconds
        while True:
            missing = [h for h in range(n)
                       if not self._shard_file(step, h).is_file()]
            if not missing:
                break
            if time.monotonic() >= deadline or self._stop.is_set():
                logger.warning(
                    "continuous checkpoint step %d: shards %s never "
                    "arrived; step left uncommitted", step, missing)
                return
            self._stop.wait(0.05)
        manifest = {
            "format": MANIFEST_FORMAT,
            "step": step,
            "num_hosts": n,
            "mesh": dict(self.config.mesh_shape or {}),
            "plan": plan,
            "shards": [SHARD_FILE_FMT.format(i=i, n=n)
                       for i in range(n)],
        }
        atomic_write_bytes(
            self._step_dir(step) / MANIFEST_FILE,
            json.dumps(manifest, indent=1, sort_keys=True)
            .encode("utf-8"))
        self._prune()

    def _prune(self) -> None:
        steps = self.all_steps()
        for old in steps[:-max(1, int(self.config.keep))]:
            step_dir = self._step_dir(old)
            try:
                # Manifest first: a reader racing the prune sees an
                # uncommitted (invisible) step, never a half-deleted
                # "valid" one.
                (step_dir / MANIFEST_FILE).unlink(missing_ok=True)
                shutil.rmtree(step_dir, ignore_errors=True)
            except OSError:
                logger.warning("could not prune %s", step_dir,
                               exc_info=True)
        # Orphaned UNCOMMITTED steps older than the newest committed
        # one can never complete (some host's newest-wins writer
        # skipped them): sweep their shards too, or they accumulate
        # forever on the shared mount.
        if steps:
            newest = steps[-1]
            for child in self.root.iterdir():
                match = _STEP_DIR_RE.match(child.name)
                if (match and int(match.group(1)) < newest
                        and not (child / MANIFEST_FILE).is_file()):
                    shutil.rmtree(child, ignore_errors=True)

    # -- restore ----------------------------------------------------------

    def _read_step(self, step: int) -> Dict[str, np.ndarray]:
        from flax import serialization

        step_dir = self._step_dir(step)
        manifest = json.loads(
            (step_dir / MANIFEST_FILE).read_text())
        if int(manifest.get("format", 0)) != MANIFEST_FORMAT:
            raise ValueError(
                f"unsupported continuous-checkpoint format "
                f"{manifest.get('format')!r}")
        shards = [serialization.msgpack_restore(
            (step_dir / fname).read_bytes())
            for fname in manifest["shards"]]
        if len(shards) != int(manifest["num_hosts"]):
            raise ValueError("manifest shard count mismatch")
        plan: Dict[str, Dict[str, int]] = manifest["plan"]
        flat: Dict[str, np.ndarray] = {}
        for key, value in shards[0].items():
            entry = plan.get(key)
            if entry is None:
                flat[key] = np.asarray(value)
                continue
            dim = int(entry["dim"])
            pieces = [np.asarray(shard[key]) for shard in shards]
            flat[key] = np.concatenate(pieces, axis=dim)
        for i, shard in enumerate(shards[1:], start=1):
            extra = set(shard) - set(flat)
            if extra:
                raise ValueError(
                    f"shard {i} carries unplanned leaves "
                    f"{sorted(extra)}")
        return flat

    def restore(self, state: Any, step: Optional[int] = None) -> Any:
        """Restore the freshest COMMITTED step into ``state``'s
        structure and shardings — each leaf is placed with
        ``jax.device_put(value, live_leaf.sharding)``, which IS the
        mesh reshard: a checkpoint written by a 4-host dp/fsdp gang
        restores onto whatever mesh the surviving hosts built.
        Unreadable steps are skipped with a warning (an explicit
        ``step`` raises instead); no usable step returns ``state``
        untouched (fresh start)."""
        if step is not None:
            flat = self._read_step(step)
            return self._fill(state, flat, step)
        for candidate in reversed(self.all_steps()):
            try:
                flat = self._read_step(candidate)
            except Exception:  # noqa: BLE001 — torn/corrupt artifact
                logger.warning(
                    "continuous checkpoint step %d unreadable; "
                    "trying the previous one", candidate,
                    exc_info=True)
                continue
            return self._fill(state, flat, candidate)
        logger.info("no continuous checkpoint in %s", self.root)
        return state

    def _fill(self, state: Any, flat: Dict[str, np.ndarray],
              step: int) -> Any:
        live, treedef = jax.tree_util.tree_flatten_with_path(state)
        leaves = []
        seen = set()
        for path, leaf in live:
            key = _path_key(path)
            if key not in flat:
                raise ValueError(
                    f"continuous checkpoint step {step} lacks leaf "
                    f"{key!r} — state structure changed?")
            seen.add(key)
            value = flat[key]
            expect = getattr(leaf, "shape", None)
            if expect is not None and tuple(value.shape) != tuple(expect):
                raise ValueError(
                    f"leaf {key!r}: checkpoint shape "
                    f"{tuple(value.shape)} != live {tuple(expect)}")
            sharding = getattr(leaf, "sharding", None)
            if isinstance(leaf, jax.Array) and sharding is not None:
                leaves.append(jax.device_put(value, sharding))
            else:
                leaves.append(value)
        extra = set(flat) - seen
        if extra:
            raise ValueError(
                f"continuous checkpoint step {step} carries unknown "
                f"leaves {sorted(extra)[:5]}")
        logger.info("restored continuous checkpoint step %d from %s "
                    "(resharded onto the live mesh)", step, self.root)
        return jax.tree_util.tree_unflatten(treedef, leaves)

    # -- lifecycle --------------------------------------------------------

    def wait(self, timeout: Optional[float] = 60.0) -> bool:
        """Block until the handed-over write is durable; False on
        timeout."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        while True:
            with self._slot_lock:
                if self._slot is None and not self._writing:
                    return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            self._idle.wait(timeout=0.05)

    def close(self) -> None:
        self.wait(timeout=self.config.commit_timeout_seconds)
        self._stop.set()
        if self._writer is not None:
            self._writer.join(timeout=5.0)
