# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Pipeline-parallel LM training: real decoder blocks on the
``pipeline`` mesh axis.

This is the trainer-layer integration of :func:`spmd_pipeline`
(SURVEY §2.5: pipeline parallelism "as sharding presets in the new
trainer layer", not an orphan primitive): a Llama model's decoder
blocks are partitioned into contiguous stage groups, each stage's
layer params are stacked and sharded over the pipeline axis, and the
GPipe schedule streams microbatches stage→stage over ``ppermute``
while the ``data`` axis shards microbatch rows (pp × dp composition).

Embedding, final norm and lm head run outside the pipeline (they are
a tiny fraction of FLOPs and live replicated); the stage function
``lax.scan``s the per-stage layers so every stage runs literally the
same block code the unpipelined :class:`~kubeflow_tpu.models.llama.
Llama` runs — which is what makes the numerical-equality test
against the unpipelined model possible (tests/test_pipeline_lm.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.models.llama import Llama, LlamaBlock, RMSNorm, _dense
from kubeflow_tpu.parallel.pipeline import (
    interleave_stage_params,
    spmd_pipeline,
    spmd_pipeline_interleaved,
    stack_stage_params,
)
from kubeflow_tpu.training.lm import LOSSES, Batch

PIPELINE_AXIS = "pipeline"


class PipelineLMState(struct.PyTreeNode):
    """Step + staged params + optimizer state."""

    step: jax.Array
    params: Dict[str, Any]  # {tok_embed, stages, final_norm, lm_head}
    opt_state: optax.OptState
    tx: optax.GradientTransformation = struct.field(pytree_node=False)


def partition_llama_params(params: Dict[str, Any],
                           n_stages: int) -> Dict[str, Any]:
    """Regroup a flat Llama param tree into the staged layout.

    ``layer_i`` subtrees are stacked into contiguous stage groups:
    leaves of ``stages`` get shape [n_stages, layers_per_stage, ...].
    """
    layer_keys = sorted(
        (k for k in params if k.startswith("layer_")),
        key=lambda k: int(k.split("_")[1]))
    n_layers = len(layer_keys)
    if n_layers == 0:
        raise ValueError("param tree has no layer_<i> subtrees")
    if n_layers % n_stages:
        raise ValueError(
            f"{n_layers} layers not divisible into {n_stages} stages")
    per = n_layers // n_stages
    stage_trees = []
    for s in range(n_stages):
        group = [params[layer_keys[s * per + j]] for j in range(per)]
        stage_trees.append(jax.tree.map(lambda *xs: jnp.stack(xs), *group))
    return {
        "tok_embed": params["tok_embed"],
        "stages": stack_stage_params(stage_trees),
        "final_norm": params["final_norm"],
        "lm_head": params["lm_head"],
    }


def _block_for(model: Llama) -> LlamaBlock:
    if model.num_experts or model.cache_size or model.lora_rank:
        raise ValueError(
            "pipeline trainer supports dense training blocks only "
            "(no MoE/cache/LoRA) — compose ep or LoRA with dp/fsdp/tp "
            "presets instead")
    # prevent_cse=False: the block applies inside the per-stage
    # lax.scan, where checkpointing doesn't need (and shouldn't pay
    # for) the CSE-blocking barriers the default inserts.
    block_cls = (nn.remat(LlamaBlock, prevent_cse=False)
                 if model.remat else LlamaBlock)
    return block_cls(
        model.num_heads, model.num_kv_heads,
        model.d_model // model.num_heads, model.mlp_dim,
        model.rope_theta, model.dtype, model.attention_fn)


def staged_llama_forward(
    model: Llama,
    params: Dict[str, Any],
    input_ids: jax.Array,
    *,
    mesh: Mesh,
    n_microbatches: int,
    batch_axis: Optional[str] = "data",
    n_virtual: int = 1,
) -> jax.Array:
    """Forward pass equal to ``model.apply`` on the unstaged params
    (same block code, same math), with the block stack pipelined.
    ``n_virtual > 1`` selects the interleaved (circular) schedule:
    ``n_virtual`` cyclic stage groups per device, shrinking the GPipe
    bubble by ~``n_virtual``× at fixed microbatch count."""
    x = jnp.take(params["tok_embed"]["embedding"], input_ids,
                 axis=0).astype(model.dtype)
    block = _block_for(model)

    def stage_fn(stage_params, h):
        mb, length = h.shape[0], h.shape[1]
        pos = jnp.broadcast_to(jnp.arange(length)[None, :], (mb, length))

        def body(carry, layer_params):
            return block.apply({"params": layer_params}, carry, pos), None

        h, _ = jax.lax.scan(body, h, stage_params)
        return h

    if n_virtual > 1:
        x = spmd_pipeline_interleaved(
            stage_fn, params["stages"], x, mesh=mesh,
            n_microbatches=n_microbatches, n_virtual=n_virtual,
            batch_axis=batch_axis)
    else:
        x = spmd_pipeline(stage_fn, params["stages"], x, mesh=mesh,
                          n_microbatches=n_microbatches,
                          batch_axis=batch_axis)
    x = RMSNorm(dtype=model.dtype).apply(
        {"params": params["final_norm"]}, x)
    return _dense(model.vocab_size, ("embed", "vocab"),
                  jnp.float32).apply(
        {"params": params["lm_head"]}, x.astype(jnp.float32))


def pipeline_state_shardings(mesh: Mesh, state: PipelineLMState,
                             n_virtual: int = 1) -> PipelineLMState:
    """stages over the pipeline axis; embed/norm/head + moments of
    each follow their param's sharding; scalars replicated. With
    ``n_virtual > 1`` stage leaves are [v, n_devices, ...] and the
    DEVICE dim (1) is the sharded one (cyclic stage placement)."""
    replicated = NamedSharding(mesh, P())
    stage_sh = NamedSharding(
        mesh, P(PIPELINE_AXIS) if n_virtual == 1 else P(None, PIPELINE_AXIS))

    def shard_params(tree):
        return {
            "tok_embed": jax.tree.map(lambda _: replicated,
                                      tree["tok_embed"]),
            "stages": jax.tree.map(lambda _: stage_sh, tree["stages"]),
            "final_norm": jax.tree.map(lambda _: replicated,
                                       tree["final_norm"]),
            "lm_head": jax.tree.map(lambda _: replicated,
                                    tree["lm_head"]),
        }

    params_sh = shard_params(state.params)

    def opt_sharding(leaf_tree):
        # Optimizer state mirrors the param tree wherever its subtree
        # structure matches (adam mu/nu do); scalars replicate.
        def match(entry):
            if (isinstance(entry, dict)
                    and set(entry) == set(state.params)):
                return shard_params(entry)
            return jax.tree.map(lambda _: replicated, entry)

        return jax.tree.map(
            match, leaf_tree,
            is_leaf=lambda e: (isinstance(e, dict)
                               and set(e) == set(state.params)))

    return PipelineLMState(
        step=replicated,
        params=params_sh,
        opt_state=opt_sharding(state.opt_state),
        tx=state.tx,
    )


def create_pipeline_lm_state(
    model: Llama,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    sample_batch: Batch,
    mesh: Mesh,
    n_stages: Optional[int] = None,
    n_virtual: int = 1,
) -> Tuple[PipelineLMState, PipelineLMState]:
    """Init a staged state + its sharding tree.

    ``n_stages`` defaults to the mesh's pipeline-axis size.
    ``n_virtual > 1`` partitions the blocks into
    ``n_stages * n_virtual`` stages placed cyclically (device d holds
    stages {q*n + d}) for the interleaved schedule.
    """
    n_stages = n_stages or mesh.shape[PIPELINE_AXIS]
    if n_stages != mesh.shape[PIPELINE_AXIS]:
        raise ValueError(
            f"n_stages {n_stages} != mesh pipeline axis "
            f"{mesh.shape[PIPELINE_AXIS]}")
    variables = jax.jit(model.init)(rng, sample_batch["input_ids"])
    params = partition_llama_params(
        nn.meta.unbox(variables["params"]), n_stages * n_virtual)
    if n_virtual > 1:
        params["stages"] = interleave_stage_params(
            params["stages"], n_stages)
    state = PipelineLMState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        tx=tx,
    )
    shardings = pipeline_state_shardings(mesh, state, n_virtual)
    state = jax.device_put(state, shardings)
    return state, shardings


def make_pipeline_lm_train_step(
    mesh: Mesh,
    shardings: PipelineLMState,
    model: Llama,
    *,
    n_microbatches: int = 4,
    objective: str = "causal",
    donate: bool = True,
    n_virtual: int = 1,
):
    """The ``pipeline=N`` trainer preset: jitted (state, batch) →
    (state, metrics) with the block stack on the pipeline axis and
    batch rows on the data axis. ``n_virtual > 1`` = interleaved
    schedule (state must come from ``create_pipeline_lm_state`` with
    the same ``n_virtual``)."""
    loss_fn = LOSSES[objective]

    def step(state: PipelineLMState, batch: Batch):
        def compute(params):
            logits = staged_llama_forward(
                model, params, batch["input_ids"], mesh=mesh,
                n_microbatches=n_microbatches, n_virtual=n_virtual)
            loss, acc = loss_fn(logits, batch)
            return loss, acc

        (loss, acc), grads = jax.value_and_grad(
            compute, has_aux=True)(state.params)
        updates, new_opt = state.tx.update(grads, state.opt_state,
                                           state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss,
            "accuracy": acc,
            "grad_norm": optax.global_norm(grads),
        }
        return (
            state.replace(step=state.step + 1, params=new_params,
                          opt_state=new_opt),
            metrics,
        )

    batch_sh = NamedSharding(mesh, P(("dcn_data", "data", "fsdp")))
    return jax.jit(
        step,
        in_shardings=(shardings, batch_sh),
        out_shardings=(shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,) if donate else (),
    )
