"""The training loop: resume → step → log → checkpoint → profile.

This is the in-pod driver the operator's whole-slice recovery model
assumes (SURVEY §5 "failure detection"): on every boot it restores the
latest checkpoint unconditionally — first boot is a fresh start, a
gang restart resumes at the saved step — so the operator can answer
any slice fault with "kill and recreate the gang" and lose at most
``save_interval_steps`` of work. The reference had nothing here: its
launcher streamed tf_cnn_benchmarks output and slept forever on
success (``tf-controller-examples/tf-cnn/launcher.py:29-54,86-90``).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax

from kubeflow_tpu.training.checkpoint import CheckpointConfig, Checkpointer
from kubeflow_tpu.utils.metrics import MetricsLogger

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class LoopConfig:
    total_steps: int
    log_every: int = 10
    checkpoint: Optional[CheckpointConfig] = None
    metrics_path: Optional[str] = None
    # JAX profiler capture [start, stop) in *resumed* step numbers;
    # traces land under profile_dir (XPlane — TensorBoard-compatible).
    profile_start: Optional[int] = None
    profile_stop: Optional[int] = None
    profile_dir: str = "/tmp/kft-profile"


def fit(
    state: Any,
    step_fn: Callable[[Any, Any], Tuple[Any, Dict[str, jax.Array]]],
    batches: Iterator[Any],
    config: LoopConfig,
    *,
    metrics_logger: Optional[MetricsLogger] = None,
    hooks: Optional[list] = None,
) -> Any:
    """Run up to ``config.total_steps`` (counting resumed steps).

    ``hooks``: callables ``(step, state, metrics) -> None`` invoked at
    every log interval (dashboards, early-stop probes, tests).
    """
    ckpt = Checkpointer(config.checkpoint) if config.checkpoint else None
    owns_logger = metrics_logger is None
    metrics_logger = metrics_logger or MetricsLogger(config.metrics_path)

    if ckpt:
        state = ckpt.restore(state)
    start_step = int(state.step)
    if start_step >= config.total_steps:
        logger.info("checkpoint already at step %d >= total %d; done",
                    start_step, config.total_steps)
        return state

    profiling = False
    window_start = time.perf_counter()
    window_steps = 0
    metrics: Dict[str, jax.Array] = {}
    try:
        for step in range(start_step, config.total_steps):
            if config.profile_start is not None and step == config.profile_start:
                jax.profiler.start_trace(config.profile_dir)
                profiling = True
            batch = next(batches)
            state, metrics = step_fn(state, batch)
            window_steps += 1

            next_step = step + 1
            if profiling and next_step == (config.profile_stop
                                           or config.profile_start + 3):
                float(metrics["loss"])  # fence: value pull, not ready-bit
                jax.profiler.stop_trace()
                profiling = False
                logger.info("profiler trace written to %s", config.profile_dir)
            if next_step % config.log_every == 0 or next_step == config.total_steps:
                # The float() pulls fence the window (value pull, not
                # ready-bit — see benchmark.py on remote platforms).
                host_metrics = {k: float(v) for k, v in metrics.items()}
                elapsed = time.perf_counter() - window_start
                host_metrics["steps_per_sec"] = window_steps / max(elapsed, 1e-9)
                metrics_logger.log(next_step, host_metrics)
                logger.info("step %d: %s", next_step, host_metrics)
                for hook in hooks or ():
                    hook(next_step, state, host_metrics)
                window_start = time.perf_counter()
                window_steps = 0
            if ckpt:
                ckpt.save(next_step, state)
        if ckpt:
            ckpt.save(int(state.step), state, force=True)
            ckpt.wait()
    finally:
        if profiling:
            jax.profiler.stop_trace()
        if ckpt:
            ckpt.close()
        if owns_logger:
            metrics_logger.close()
    return state
