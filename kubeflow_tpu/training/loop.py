# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""The training loop: resume → step → log → checkpoint → profile → drain.

This is the in-pod driver the operator's whole-slice recovery model
assumes (SURVEY §5 "failure detection"): on every boot it restores the
latest checkpoint unconditionally — first boot is a fresh start, a
gang restart resumes at the saved step — so the operator can answer
any slice fault with "kill and recreate the gang" and lose at most
``save_interval_steps`` of work. The reference had nothing here: its
launcher streamed tf_cnn_benchmarks output and slept forever on
success (``tf-controller-examples/tf-cnn/launcher.py:29-54,86-90``).

Preemption drain: TPU spot reclaims and node maintenance deliver
SIGTERM with a grace period — *the* TPU-cloud failure mode. ``fit``
catches it, finishes the in-flight step, force-saves a checkpoint, and
raises :class:`DrainInterrupt`; entrypoints exit with
``DRAIN_EXIT_CODE`` so the operator restarts the slice without burning
a restart-budget slot and the job resumes from the drain step — losing
zero work instead of everything since the last periodic save.
"""

from __future__ import annotations

import dataclasses
import logging
import signal
import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax

from kubeflow_tpu.obs import metrics as obs_metrics
from kubeflow_tpu.training.checkpoint import (
    CheckpointConfig,
    Checkpointer,
    ContinuousCheckpointConfig,
    ShardedCheckpointer,
)
from kubeflow_tpu.training.launcher import DRAIN_EXIT_CODE  # noqa: F401
from kubeflow_tpu.utils.metrics import MetricsLogger

logger = logging.getLogger(__name__)

# Scrapeable training signals alongside the JSONL MetricsLogger: the
# same step-time/throughput numbers the log line carries, but live on
# /metrics (trainers that embed a serving surface, or a sidecar
# running obs.exposition.start_exposition_server). Observed once per
# log window — the step itself stays untimed (JAX dispatch is async;
# per-step wall clocks would fence the device).
_T_STEP_SECONDS = obs_metrics.Histogram(
    "kft_training_step_seconds",
    "Mean per-step wall time over each log window")
_T_STEPS_PER_SEC = obs_metrics.Gauge(
    "kft_training_steps_per_sec",
    "Training throughput over the last log window")
_T_STEPS = obs_metrics.Counter(
    "kft_training_steps_total", "Optimizer steps completed")


class DrainInterrupt(Exception):
    """Raised by ``fit`` after a drain signal: the in-flight step
    finished and (if checkpointing) a checkpoint is durable at
    ``.step``. Entrypoints translate this to ``DRAIN_EXIT_CODE``."""

    def __init__(self, step: int, checkpointed: bool):
        super().__init__(
            f"drained at step {step} "
            f"({'checkpoint saved' if checkpointed else 'no checkpoint'})")
        self.step = step
        self.checkpointed = checkpointed


@dataclasses.dataclass
class LoopConfig:
    total_steps: int
    log_every: int = 10
    checkpoint: Optional[CheckpointConfig] = None
    # Continuous sharded tier (r16): per-host async shard writes every
    # N steps with a manifest-last commit — the checkpoint an elastic
    # resize restores (and reshards) from. Rides ALONGSIDE the
    # periodic Orbax tier; on boot the loop restores whichever tier
    # holds the freshest step.
    continuous: Optional[ContinuousCheckpointConfig] = None
    metrics_path: Optional[str] = None
    # JAX profiler capture [start, stop) in *resumed* step numbers;
    # traces land under profile_dir (XPlane — TensorBoard-compatible).
    profile_start: Optional[int] = None
    profile_stop: Optional[int] = None
    profile_dir: str = "/tmp/kft-profile"
    # Preemption drain: on any of these signals, finish the in-flight
    # step, force-save a checkpoint, raise DrainInterrupt. Installed
    # only when fit runs on the main thread (signal API constraint);
    # () disables.
    drain_signals: Tuple[int, ...] = (signal.SIGTERM,)
    # Multi-host gangs must AGREE on the drain step: the Orbax save is
    # itself a collective, so a host draining unilaterally while its
    # peers sit in the train-step psum deadlocks the gang until the
    # kubelet SIGKILLs it (which then reads as a crash, burning
    # budget). Every N steps the hosts all-gather their local drain
    # flags and drain together iff any host saw the signal. Trade-off:
    # up to N extra steps run inside the grace period — keep
    # N * step_time well under terminationGracePeriodSeconds.
    drain_sync_steps: int = 5


def fit(
    state: Any,
    step_fn: Callable[[Any, Any], Tuple[Any, Dict[str, jax.Array]]],
    batches: Iterator[Any],
    config: LoopConfig,
    *,
    metrics_logger: Optional[MetricsLogger] = None,
    hooks: Optional[list] = None,
) -> Any:
    """Run up to ``config.total_steps`` (counting resumed steps).

    ``hooks``: callables ``(step, state, metrics) -> None`` invoked at
    every log interval (dashboards, early-stop probes, tests).
    """
    ckpt = Checkpointer(config.checkpoint) if config.checkpoint else None
    cont = (ShardedCheckpointer(config.continuous)
            if config.continuous else None)
    owns_logger = metrics_logger is None
    metrics_logger = metrics_logger or MetricsLogger(config.metrics_path)

    # Restore the FRESHEST tier: the continuous shards typically lead
    # the periodic Orbax step (they save every few steps), so an
    # elastic resize / crash replays seconds, not a full interval.
    # Restoring through the live ``state`` reshards onto whatever
    # mesh this (possibly smaller) gang built.
    if ckpt or cont:
        orbax_step = ckpt.latest_step() if ckpt else None
        cont_step = cont.latest_step() if cont else None
        if cont_step is not None and (orbax_step is None
                                      or cont_step >= orbax_step):
            state = cont.restore(state)
        elif ckpt:
            state = ckpt.restore(state)
    start_step = int(state.step)
    if start_step >= config.total_steps:
        logger.info("checkpoint already at step %d >= total %d; done",
                    start_step, config.total_steps)
        return state

    # Preemption drain: the handler only flips a flag — the loop body
    # observes it between steps, so the in-flight step always
    # completes and the saved state is a real step boundary. Signals
    # can only be installed from the main thread; elsewhere (tests
    # driving fit from a worker thread) drain is simply unavailable.
    drain_requested = threading.Event()
    prev_handlers = {}
    if (config.drain_signals
            and threading.current_thread() is threading.main_thread()):
        def _on_drain(signum, frame):
            del frame
            logger.info("drain signal %d: finishing in-flight step, "
                        "then checkpoint + exit", signum)
            drain_requested.set()

        for sig in config.drain_signals:
            prev_handlers[sig] = signal.signal(sig, _on_drain)

    multi_host = jax.process_count() > 1
    profiling = False
    window_start = time.perf_counter()
    window_steps = 0
    metrics: Dict[str, jax.Array] = {}
    try:
        for step in range(start_step, config.total_steps):
            if multi_host:
                # Collective drain agreement: every host evaluates
                # this at the SAME iterations (same start_step, same
                # stride), so the allgather below lines up. A host
                # that saw no signal still participates and learns a
                # peer was preempted.
                drain_now = False
                if (step - start_step) % max(config.drain_sync_steps,
                                             1) == 0:
                    from jax.experimental import multihost_utils

                    flags = multihost_utils.process_allgather(
                        drain_requested.is_set())
                    drain_now = bool(flags.any())
            else:
                drain_now = drain_requested.is_set()
            if drain_now:
                drained_step = int(state.step)
                if cont:
                    cont.save(drained_step, state, force=True)
                    cont.wait()
                if ckpt:
                    # Safe collectively: every host reached this exact
                    # step with the same drain verdict.
                    ckpt.save(drained_step, state, force=True)
                    ckpt.wait()
                logger.info("drained at step %d (checkpoint %s)",
                            drained_step,
                            "saved" if ckpt or cont
                            else "not configured")
                raise DrainInterrupt(drained_step,
                                     ckpt is not None or cont is not None)
            if config.profile_start is not None and step == config.profile_start:
                jax.profiler.start_trace(config.profile_dir)
                profiling = True
            batch = next(batches)
            state, metrics = step_fn(state, batch)
            window_steps += 1

            next_step = step + 1
            if profiling and next_step == (config.profile_stop
                                           or config.profile_start + 3):
                float(metrics["loss"])  # fence: value pull, not ready-bit
                jax.profiler.stop_trace()
                profiling = False
                logger.info("profiler trace written to %s", config.profile_dir)
            if next_step % config.log_every == 0 or next_step == config.total_steps:
                # The float() pulls fence the window (value pull, not
                # ready-bit — see benchmark.py on remote platforms).
                host_metrics = {k: float(v) for k, v in metrics.items()}
                elapsed = time.perf_counter() - window_start
                host_metrics["steps_per_sec"] = window_steps / max(elapsed, 1e-9)
                _T_STEP_SECONDS.observe(elapsed / max(window_steps, 1))
                _T_STEPS_PER_SEC.set(host_metrics["steps_per_sec"])
                _T_STEPS.inc(window_steps)
                metrics_logger.log(next_step, host_metrics)
                logger.info("step %d: %s", next_step, host_metrics)
                for hook in hooks or ():
                    hook(next_step, state, host_metrics)
                window_start = time.perf_counter()
                window_steps = 0
            if ckpt:
                ckpt.save(next_step, state)
            if cont:
                # Per-host async shard write: the step loop pays only
                # the device→host snapshot; the disk write overlaps
                # the next steps' compute.
                cont.save(next_step, state)
        if cont:
            cont.save(int(state.step), state, force=True)
            cont.wait()
        if ckpt:
            ckpt.save(int(state.step), state, force=True)
            ckpt.wait()
    finally:
        for sig, handler in prev_handlers.items():
            # getsignal/signal return None when the prior handler was
            # installed at C level — unrepresentable in Python, so the
            # closest restore is SIG_DFL. Leaving _on_drain installed
            # instead would bind future signals to THIS completed
            # run's Event: a later SIGTERM sets an orphaned flag and
            # the process silently ignores its own termination.
            signal.signal(sig, signal.SIG_DFL if handler is None
                          else handler)
        if profiling:
            jax.profiler.stop_trace()
        if cont:
            cont.close()
        if ckpt:
            ckpt.close()
        if owns_logger:
            metrics_logger.close()
    return state
