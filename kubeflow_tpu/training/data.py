# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Input pipeline: per-host sharded iterators + device prefetch.

The reference delegated input entirely to tf_cnn_benchmarks (synthetic
mode, ``tf-controller-examples/tf-cnn/README.md:15-16``). TPU-native
input is a host concern with a hard rule: the host must stay ahead of
the device. Design:

- **Per-host sharding**: in a multi-host gang each process yields only
  its ``1/num_processes`` slice of the global batch (keyed by
  ``jax.process_index()``), matching the batch's
  (dcn_data, data, fsdp) sharding so ``device_put`` is a local copy,
  never a cross-host shuffle.
- **Prefetch**: a background thread keeps ``prefetch`` batches already
  transferred (device_put is async under the hood), so the step loop
  never waits on host→HBM PCIe latency.
- **Synthetic generators** for the benchmark tier: deterministic,
  seeded, zero-I/O (imagenet-shaped images, MLM token batches).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Iterator, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from kubeflow_tpu.parallel.mesh import batch_sharding

Batch = Dict[str, np.ndarray]


def host_shard_range(global_batch: int,
                     process_index: Optional[int] = None,
                     process_count: Optional[int] = None) -> range:
    """This host's row range of the global batch."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    if global_batch % pc:
        raise ValueError(f"global batch {global_batch} % hosts {pc} != 0")
    per = global_batch // pc
    return range(pi * per, (pi + 1) * per)


def synthetic_images(
    global_batch: int,
    image_shape: Sequence[int] = (224, 224, 3),
    num_classes: int = 1000,
    seed: int = 0,
    dtype: str = "bfloat16",
) -> Iterator[Batch]:
    """Seeded synthetic image classification batches (benchmark tier).

    Each epoch-step uses a fresh fold of the seed so augmentation-
    sensitive tests see varied data, while any two hosts generate
    disjoint rows of the same global batch.
    """
    import jax.numpy as jnp

    rows = host_shard_range(global_batch)
    local = len(rows)
    step = 0
    while True:
        rng = np.random.RandomState((seed * 1_000_003 + step) % (2 ** 31))
        # Generate the global batch deterministically, take our rows —
        # cheap for synthetic data and keeps host-count invariance.
        images = rng.standard_normal(
            (global_batch, *image_shape)).astype(np.float32)
        labels = rng.randint(0, num_classes, (global_batch,))
        yield {
            "inputs": jnp.asarray(images[rows.start:rows.stop], dtype),
            "labels": labels[rows.start:rows.stop].astype(np.int32),
        }
        step += 1


def _apply_mlm_mask(ids: np.ndarray, rng: np.random.RandomState,
                    mask_rate: float, mask_token: int) -> Batch:
    """THE mlm batch construction (one implementation for synthetic
    and real-shard streams): mask positions at ``mask_rate``, inputs
    carry ``mask_token`` there, labels = original tokens, weights =
    the mask."""
    mask = rng.random_sample(ids.shape) < mask_rate
    return {
        "input_ids": np.where(mask, mask_token, ids).astype(np.int32),
        "type_ids": np.zeros_like(ids, dtype=np.int32),
        "valid": np.ones_like(ids, dtype=np.int32),
        "mlm_labels": ids.astype(np.int32),
        "mlm_weights": mask.astype(np.int32),
    }


def synthetic_mlm(
    global_batch: int,
    seq_len: int = 128,
    vocab_size: int = 30522,
    mask_rate: float = 0.15,
    mask_token: int = 103,
    seed: int = 0,
) -> Iterator[Batch]:
    """Synthetic BERT pretraining batches with dynamic masking.

    The mask is drawn over the GLOBAL batch before per-host row
    sharding, so the stream is host-count-invariant (the 2-process
    gang equality test depends on this)."""
    rows = host_shard_range(global_batch)
    step = 0
    while True:
        rng = np.random.RandomState((seed * 2_000_003 + step) % (2 ** 31))
        ids = rng.randint(5, vocab_size, (global_batch, seq_len))
        batch = _apply_mlm_mask(ids, rng, mask_rate, mask_token)
        yield {k: v[rows.start:rows.stop] for k, v in batch.items()}
        step += 1


def synthetic_causal_lm(
    global_batch: int,
    seq_len: int = 2048,
    vocab_size: int = 32000,
    seed: int = 0,
) -> Iterator[Batch]:
    """Synthetic decoder pretraining/fine-tune batches."""
    rows = host_shard_range(global_batch)
    step = 0
    while True:
        rng = np.random.RandomState((seed * 3_000_017 + step) % (2 ** 31))
        ids = rng.randint(0, vocab_size, (global_batch, seq_len))
        yield {"input_ids": ids[rows.start:rows.stop].astype(np.int32)}
        step += 1


def mlm_mask_batches(
    source: Iterator[Batch],
    *,
    mask_rate: float = 0.15,
    mask_token: int = 103,
    seed: int = 0,
) -> Iterator[Batch]:
    """Dynamic BERT masking over a causal token stream.

    Wraps any ``{"input_ids"}`` iterator (``token_shard_batches`` for
    real shards) into mlm batches: inputs masked at ``mask_rate``,
    labels = the original tokens, weights = the mask. The mask is
    re-drawn every batch (dynamic masking — each epoch sees different
    masks of the same text), seeded for reproducibility. Masking
    happens after per-host sharding, on each host's own rows, so the
    per-step seed folds in ``jax.process_index()`` — without it every
    host would draw the IDENTICAL mask pattern over its own rows and
    masked positions would be correlated across the gang.
    """
    pi = jax.process_index()
    for step, batch in enumerate(source):
        ids = np.asarray(batch["input_ids"])
        rng = np.random.RandomState(
            (seed * 5_000_011 + step * 1_000_003 + pi) % (2 ** 31))
        yield _apply_mlm_mask(ids, rng, mask_rate, mask_token)


def resolve_shards(spec, cache_root: Optional[str] = None) -> list:
    """Data-path spec → local shard files, fetching remote entries.

    ``spec`` is a comma-separated string (or sequence) of files,
    directories, or glob patterns. ``gs://``-style entries resolve
    through fsspec and are downloaded into a local content cache with
    the same atomicity discipline as the serving model cache
    (serving/remote.py: temp dir + rename, skip-if-cached) — SURVEY
    §2.4's storage row: training data on the TPU-VM path lives in
    object stores, not on local disk.

    Per-host note: every host materializes the full shard list; the
    batch iterators shard *rows* per host (``host_shard_range``), so
    the duplicate download costs bandwidth, never correctness. The
    reference's equivalent was TF reading gs:// paths natively.
    """
    import glob as _glob
    import os

    entries = ([e.strip() for e in spec.split(",") if e.strip()]
               if isinstance(spec, str) else [str(e) for e in spec])
    if not entries:
        raise ValueError("empty data spec")
    out: list = []
    for entry in entries:
        from kubeflow_tpu.serving.remote import is_remote

        if is_remote(entry):
            out.extend(_materialize_remote_shards(entry, cache_root))
        elif os.path.isdir(entry):
            files = sorted(
                os.path.join(entry, f) for f in os.listdir(entry)
                if f.endswith((".npy", ".bin")))
            if not files:
                raise ValueError(f"{entry}: no .npy/.bin shards inside")
            out.extend(files)
        elif _glob.has_magic(entry):
            files = sorted(_glob.glob(entry))
            if not files:
                raise ValueError(f"{entry!r} matched no shards")
            out.extend(files)
        elif os.path.exists(entry):
            out.append(entry)
        else:
            raise ValueError(f"data shard {entry!r} does not exist")
    return out


def _materialize_remote_shards(entry: str,
                               cache_root: Optional[str] = None) -> list:
    """One remote spec entry → cached local files (cache keying +
    atomic fetch shared with the serving model cache,
    serving/remote.py)."""
    import os
    import tempfile

    import fsspec
    import glob as _glob

    from kubeflow_tpu.serving.remote import atomic_get_file, cache_dir_for

    fs, root = fsspec.core.url_to_fs(entry)
    # Listings caches serve stale results forever without this
    # (same gotcha as serving/remote.py's scanner).
    fs.invalidate_cache()
    if _glob.has_magic(root):
        files = sorted(f for f in fs.glob(root) if not fs.isdir(f))
    elif fs.isdir(root):
        files = sorted(
            f for f in fs.ls(root, detail=False)
            if str(f).endswith((".npy", ".bin")) and not fs.isdir(f))
    elif fs.exists(root):
        files = [root]
    else:
        files = []
    if not files:
        raise ValueError(f"remote data spec {entry!r} matched no shards")
    cache_root = cache_root or os.environ.get(
        "KFT_DATA_CACHE",
        os.path.join(tempfile.gettempdir(), "kft-data-cache"))
    proto = (fs.protocol if isinstance(fs.protocol, str)
             else fs.protocol[0])
    out = []
    for remote_file in files:
        # Cache key = the FILE's remote parent dir (not the spec entry):
        # same-named shards from different remote dirs — other buckets,
        # other runs, recursive-glob matches — must never collide.
        parent = f"{proto}://{os.path.dirname(str(remote_file))}"
        local_dir = cache_dir_for(parent, cache_root)
        local_dir.mkdir(parents=True, exist_ok=True)
        dest = str(local_dir / os.path.basename(str(remote_file)))
        atomic_get_file(fs, remote_file, dest)
        out.append(dest)
    return out


def _epoch_batch_indices(n_items, global_batch, seed, epochs, rows,
                         seed_stride):
    """Shared epoch loop for the shard iterators: seeded permutation
    of item order each epoch, this host's row window of each global
    batch, partial trailing batches dropped."""
    per_epoch = n_items // global_batch
    epoch = 0
    while epochs is None or epoch < epochs:
        rng = np.random.RandomState(
            (seed * seed_stride + epoch) % (2 ** 31))
        order = rng.permutation(n_items)
        for b in range(per_epoch):
            yield order[b * global_batch + rows.start:
                        b * global_batch + rows.stop]
        epoch += 1


def _locate(offsets, i: int):
    """Flat index → (shard, local offset) via the cumulative sizes."""
    s = int(np.searchsorted(offsets, i, side="right") - 1)
    return s, int(i - offsets[s])


def image_shard_batches(
    image_paths: Sequence[str],
    label_paths: Sequence[str],
    global_batch: int,
    *,
    seed: int = 0,
    epochs: Optional[int] = None,
    dtype: str = "bfloat16",
    scale: float = 1.0 / 255.0,
) -> Iterator[Batch]:
    """Vision batches {"inputs", "labels"} from paired .npy shards.

    The real-data path for the vision trainer (training/train.py),
    mirroring :func:`token_shard_batches`' mechanics: mmapped shards
    (uint8 images [N, H, W, C] + integer labels [N]), static shapes
    (trailing partial batches dropped), per-host row sharding, seeded
    epoch shuffle of example order, and eager validation — shard
    mismatches raise HERE, not from inside the prefetch thread.

    ``scale`` maps stored uint8 to model range at batch-build time
    (the cast itself runs on host; the device sees ``dtype``).
    """
    if len(image_paths) != len(label_paths) or not image_paths:
        raise ValueError(
            f"need equal non-empty shard lists; got "
            f"{len(image_paths)} image vs {len(label_paths)} label")
    images, labels = [], []
    for ip, lp in zip(image_paths, label_paths):
        img = np.load(ip, mmap_mode="r")
        lab = np.load(lp, mmap_mode="r")
        if img.ndim != 4:
            raise ValueError(f"{ip}: expected [N,H,W,C], got {img.shape}")
        if img.dtype != np.uint8:
            # The scale default assumes uint8 storage; float shards
            # would silently double-normalize — refuse eagerly.
            raise ValueError(
                f"{ip}: image shards must be uint8 (got {img.dtype}); "
                f"store raw pixels and let `scale` normalize")
        if not np.issubdtype(lab.dtype, np.integer):
            raise ValueError(
                f"{lp}: labels must be integers (got {lab.dtype})")
        if lab.shape != (img.shape[0],):
            raise ValueError(
                f"{lp}: {lab.shape} labels for {img.shape[0]} images")
        if images and img.shape[1:] != images[0].shape[1:]:
            raise ValueError(
                f"{ip}: shape {img.shape[1:]} != {images[0].shape[1:]}")
        images.append(img)
        labels.append(lab)
    sizes = [i.shape[0] for i in images]
    total = sum(sizes)
    if total < global_batch:
        raise ValueError(
            f"{total} examples < global batch {global_batch}")
    import jax.numpy as jnp

    rows = host_shard_range(global_batch)
    offsets = np.cumsum([0] + sizes)
    np_dtype = (jnp.bfloat16 if dtype == "bfloat16"
                else np.dtype(dtype))
    return _image_shard_iter(images, labels, offsets, total,
                             global_batch, seed, epochs, np_dtype,
                             scale, rows)


def _image_shard_iter(images, labels, offsets, total, global_batch,
                      seed, epochs, np_dtype, scale, rows
                      ) -> Iterator[Batch]:
    def read(i: int):
        s, local = _locate(offsets, i)
        return images[s][local], labels[s][local]

    for mine in _epoch_batch_indices(total, global_batch, seed, epochs,
                                     rows, seed_stride=9_999_991):
        pairs = [read(int(i)) for i in mine]
        batch = (np.stack([p[0] for p in pairs]).astype(np.float32)
                 * scale).astype(np_dtype)
        yield {"inputs": batch,
               "labels": np.stack([p[1] for p in pairs]).astype(
                   np.int32)}


def token_shard_batches(
    paths: Sequence[str],
    global_batch: int,
    seq_len: int,
    *,
    seed: int = 0,
    epochs: Optional[int] = None,
    dtype: str = "int32",
    bin_dtype: str = "uint16",
) -> Iterator[Batch]:
    """Causal-LM batches from binary token shards on disk.

    The real-data path for fine-tuning (``training/finetune.py``) and
    pretraining: each shard is a flat token array — ``.npy`` (dtype
    self-describing) or raw ``.bin`` interpreted as ``bin_dtype``
    (default uint16, the common tokenizer-dump layout; pass
    ``bin_dtype="int32"`` for 32-bit dumps — raw files carry no dtype
    header, so it must be stated). TPU-first mechanics:

    - **mmap, not read**: shards map read-only; the OS page cache
      feeds the prefetch thread and nothing is resident twice.
    - **Static shapes**: the stream is chunked into fixed
      ``[batch, seq_len]`` blocks; the tail that doesn't fill a batch
      is dropped (never a ragged final batch that would retrace jit).
    - **Per-host sharding**: as with the synthetic generators, each
      process materializes only its ``1/num_processes`` rows.
    - **Seeded shuffle** of chunk order each epoch (shuffling fixed
      chunks, not documents — the standard packed-LM recipe).

    Validation (missing shards, too-small stream) happens eagerly at
    call time — not at first ``next()`` from inside a prefetch thread
    mid-training.
    """
    if not paths:
        raise ValueError("token_shard_batches needs at least one shard")
    arrays = []
    for path in paths:
        if str(path).endswith(".npy"):
            arr = np.load(path, mmap_mode="r")
        else:
            arr = np.memmap(path, dtype=np.dtype(bin_dtype), mode="r")
        arrays.append(arr.reshape(-1))
    total = sum(a.shape[0] for a in arrays)
    n_chunks = total // seq_len
    if n_chunks < global_batch:
        raise ValueError(
            f"{total} tokens / seq_len {seq_len} = {n_chunks} chunks "
            f"< global batch {global_batch}")

    # Flat index space over all shards: chunk i covers tokens
    # [i*seq_len, (i+1)*seq_len) of the concatenated stream.
    offsets = np.cumsum([0] + [a.shape[0] for a in arrays])
    # Divisibility check runs HERE, not in the generator body: a
    # generator defers its body to first next(), which in training
    # happens inside the DevicePrefetcher thread — exactly the
    # deferred failure this function promises not to have.
    rows = host_shard_range(global_batch)
    return _token_shard_iter(arrays, offsets, n_chunks, global_batch,
                             seq_len, seed, epochs, dtype, rows)


def _token_shard_iter(arrays, offsets, n_chunks, global_batch, seq_len,
                      seed, epochs, dtype, rows) -> Iterator[Batch]:

    def read_chunk(i: int) -> np.ndarray:
        start = i * seq_len
        s, local = _locate(offsets, start)
        out = np.empty((seq_len,), np.int64)
        filled = 0
        while filled < seq_len:
            take = min(seq_len - filled,
                       arrays[s].shape[0] - local)
            out[filled:filled + take] = arrays[s][local:local + take]
            filled += take
            s += 1
            local = 0
        return out

    for mine in _epoch_batch_indices(n_chunks, global_batch, seed,
                                     epochs, rows,
                                     seed_stride=7_000_003):
        batch = np.stack([read_chunk(int(i)) for i in mine])
        yield {"input_ids": batch.astype(dtype)}


class DevicePrefetcher:
    """Background thread that device_puts upcoming batches.

    ``__next__`` returns batches already resident (or in flight) on
    device with the mesh's batch sharding. ``close()`` stops the
    thread; also stops cleanly when the source iterator ends.
    """

    _DONE = object()

    def __init__(self, source: Iterator[Batch], mesh: Optional[Mesh],
                 prefetch: int = 2,
                 place: Optional[Callable[[Batch], Any]] = None):
        if place is not None:
            self._place = place
        elif mesh is not None:
            sharding = batch_sharding(mesh)
            if jax.process_count() > 1:
                # Each host holds only ITS rows of the global batch
                # (host_shard_range); device_put with the global
                # sharding would demand global-shaped arrays and fail
                # on divisibility (found by the real-CLI gang test).
                # make_array assembles the global array from the
                # per-process shards without any cross-host copy.
                self._place = lambda b: jax.tree.map(
                    lambda v: jax.make_array_from_process_local_data(
                        sharding, np.asarray(v)), b)
            else:
                self._place = lambda b: jax.device_put(b, sharding)
        else:
            self._place = jax.device_put
        self._source = source
        self._q: "queue.Queue" = queue.Queue(maxsize=max(prefetch, 1))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        try:
            for batch in self._source:
                if self._stop.is_set():
                    return
                self._q.put(self._place(batch))
        except BaseException as e:  # surface in the consumer
            self._q.put(e)
            return
        self._q.put(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._DONE:
            raise StopIteration
        if isinstance(item, BaseException):
            raise item
        return item

    def close(self):
        self._stop.set()
        # Unblock the producer if it's waiting on a full queue.
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
