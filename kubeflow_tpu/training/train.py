# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""SPMD training core: state, sharded train step.

Replaces the reference's training path — tf_cnn_benchmarks' session
loop with ``--variable_update=parameter_server`` (reference
``kubeflow/tf-job/prototypes/tf-cnn-benchmarks.jsonnet:41``): here one
jitted SPMD step runs on every chip; gradients are averaged by XLA
all-reduce over ICI instead of parameter-server pulls, and parameter
shards (fsdp axis) are all-gathered on demand. No PS replicas exist.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import core as flax_core
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel.mesh import (
    batch_sharding,
    fsdp_params_sharding,
    mirror_param_shardings,
)

Batch = Dict[str, jax.Array]
TrainStepFn = Callable[[Any, Batch], Tuple[Any, Dict[str, jax.Array]]]


class TrainState(struct.PyTreeNode):
    """Step counter + params + optimizer + (optional) BN statistics."""

    step: jax.Array
    params: flax_core.FrozenDict
    opt_state: optax.OptState
    batch_stats: Optional[flax_core.FrozenDict]
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)


def create_train_state(
    model: Any,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    sample_input: jax.Array,
) -> TrainState:
    variables = model.init(rng, sample_input, train=False)
    params = variables["params"]
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        batch_stats=variables.get("batch_stats"),
        apply_fn=model.apply,
        tx=tx,
    )


def state_sharding(mesh: Mesh, state: TrainState) -> TrainState:
    """Sharding tree matching a TrainState: fsdp-shard params and
    optimizer moments, replicate scalars and BN stats.

    min_weight_size is raised to 2^18 for conv nets: fsdp-sharding the
    small late-stage 1×1 conv kernels saves <1 MB/device but their
    kernel-grad computation (batch-sharded dy → channel-sharded grad,
    with spatial collapsed to 1×1) hits a GSPMD resharding cliff —
    "Involuntary full rematerialization", measured on the dcn×dp×fsdp
    dryrun layout. Replicating them removes the transition entirely;
    the large kernels that actually dominate memory stay sharded.
    """
    params_sh = fsdp_params_sharding(mesh, state.params,
                                     min_weight_size=2 ** 18)
    replicated = NamedSharding(mesh, P())

    return TrainState(
        step=replicated,
        params=params_sh,
        opt_state=mirror_param_shardings(state.opt_state, params_sh,
                                         replicated),
        batch_stats=None
        if state.batch_stats is None
        else jax.tree.map(lambda _: replicated, state.batch_stats),
        apply_fn=state.apply_fn,
        tx=state.tx,
    )


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def make_train_step(
    mesh: Optional[Mesh] = None,
    *,
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array] = softmax_cross_entropy,
    donate: bool = True,
) -> TrainStepFn:
    """Build the jitted SPMD train step.

    With a mesh, inputs arrive batch-sharded over (data, fsdp) and the
    state sharded per :func:`state_sharding`; XLA inserts the gradient
    all-reduce. Without a mesh (single chip) it's a plain jit.
    """

    def step(state: TrainState, batch: Batch):
        def compute_loss(params):
            variables = {"params": params}
            if state.batch_stats is not None:
                variables["batch_stats"] = state.batch_stats
                logits, updates = state.apply_fn(
                    variables, batch["inputs"], train=True,
                    mutable=["batch_stats"],
                )
                new_stats = updates["batch_stats"]
            else:
                logits = state.apply_fn(variables, batch["inputs"], train=True)
                new_stats = None
            return loss_fn(logits, batch["labels"]), (logits, new_stats)

        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            compute_loss, has_aux=True
        )(state.params)
        updates, new_opt_state = state.tx.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss,
            "accuracy": jnp.mean(
                (jnp.argmax(logits, -1) == batch["labels"]).astype(jnp.float32)
            ),
            "grad_norm": optax.global_norm(grads),
        }
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            batch_stats=new_stats if new_stats is not None else state.batch_stats,
        )
        return new_state, metrics

    if mesh is None:
        return jax.jit(step, donate_argnums=(0,) if donate else ())

    def jit_with_shardings(state_abstract: TrainState) -> TrainStepFn:
        sh = state_sharding(mesh, state_abstract)
        replicated = NamedSharding(mesh, P())
        return jax.jit(
            step,
            in_shardings=(sh, batch_sharding(mesh)),
            out_shardings=(sh, replicated),
            donate_argnums=(0,) if donate else (),
        )

    # The caller may not have a concrete state yet when building the
    # step; defer sharding resolution to first call, keyed by the
    # state's tree structure + leaf shapes so a differently-shaped
    # state (another model) gets fresh shardings.
    _cache: Dict[Any, TrainStepFn] = {}

    def _resolve(state: TrainState):
        leaves, treedef = jax.tree.flatten(state)
        key = (treedef, tuple(getattr(l, "shape", ()) for l in leaves))
        if key not in _cache:
            _cache[key] = jit_with_shardings(state)
        return _cache[key]

    def dispatch(state: TrainState, batch: Batch):
        return _resolve(state)(state, batch)

    # AOT surface: lets the benchmark compile the exact step once and
    # reuse the executable for both timing and FLOP counting.
    dispatch.lower = lambda state, batch: _resolve(state).lower(state, batch)

    return dispatch


def place_state(mesh: Mesh, state: TrainState) -> TrainState:
    """Device-put a host-built state onto the mesh with its shardings."""
    return jax.device_put(state, state_sharding(mesh, state))


def place_batch(mesh: Mesh, batch: Batch) -> Batch:
    return jax.device_put(batch, batch_sharding(mesh))
