# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Language-model training: sharded state + MLM / causal-LM steps.

Companion to :mod:`kubeflow_tpu.training.train` (the vision path) for
models that carry logical-axis metadata (``nn.with_partitioning`` —
bert.py, llama.py). Params/optimizer are sharded by the TP rule table
(:mod:`kubeflow_tpu.parallel.tensor_parallel`), batches over
``(data, fsdp)``, and one jitted SPMD step runs on every chip with XLA
inserting the TP all-reduces and gradient all-reduce — the replacement
for the reference's parameter-server update loop (SURVEY §2.5).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Mapping, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel.mesh import batch_sharding, mirror_param_shardings
from kubeflow_tpu.parallel.tensor_parallel import (
    logical_to_sharding,
    rules_for,
)

Batch = Dict[str, jax.Array]


class LMState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: optax.OptState
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)


def _init_variables(model: Any, rng: jax.Array, sample: Batch) -> Any:
    return model.init(rng, *_model_args(sample))


def _model_args(batch: Batch) -> Tuple[jax.Array, ...]:
    """Map a batch dict to positional model inputs.

    BERT batches carry ``type_ids``/``valid``; causal-LM batches just
    ``input_ids``.
    """
    if "type_ids" in batch or "valid" in batch:
        return (
            batch["input_ids"],
            batch.get("type_ids"),
            batch.get("valid"),
        )
    return (batch["input_ids"],)


def sharded_collection_init(
    model: Any,
    rng: jax.Array,
    sample_batch: Batch,
    mesh: Mesh,
    rules: Mapping[str, Any],
    split_fn: Callable[[Any], Any],
    transform_fn: Optional[Callable[[Any], Any]] = None,
) -> Tuple[Any, Any]:
    """Initialize ``split_fn(variables)`` *directly into its shards*.

    The shared recipe behind both trainers (pretraining here, LoRA in
    training/finetune.py): eval_shape the init to get logical axis
    metadata, map it through the TP rule table, then jit the real init
    with ``out_shardings`` so a 7B model never materializes replicated
    on one host. ``split_fn`` picks which collections to keep;
    ``transform_fn`` (optional) post-processes the unboxed values
    inside the init jit — e.g. a bf16 cast, which then frees each f32
    temporary per tensor instead of doubling peak memory. It must
    preserve tree structure. Returns (values, shardings) with matching
    structure.
    """
    boxed = jax.eval_shape(
        lambda r: _init_variables(model, r, sample_batch), rng)
    logical = split_fn(nn.get_partition_spec(boxed))
    shardings = logical_to_sharding(mesh, logical, rules)

    def init(rng):
        variables = _init_variables(model, rng, sample_batch)
        values = nn.meta.unbox(split_fn(variables))
        return transform_fn(values) if transform_fn else values

    values = jax.jit(init, out_shardings=shardings)(rng)
    return values, shardings


def sharded_opt_init(
    tx: optax.GradientTransformation,
    params: Any,
    params_sh: Any,
    mesh: Mesh,
) -> Tuple[optax.OptState, Any]:
    """Optimizer moments mirror the param tree; shard by tree path."""
    replicated = NamedSharding(mesh, P())
    opt_sh = mirror_param_shardings(
        jax.eval_shape(tx.init, params), params_sh, replicated)
    return jax.jit(tx.init, out_shardings=opt_sh)(params), opt_sh


def create_lm_state(
    model: Any,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    sample_batch: Batch,
    mesh: Optional[Mesh] = None,
    rules: Optional[Mapping[str, Any]] = None,
) -> Tuple[LMState, Optional[LMState]]:
    """Build (state, state_shardings). Without a mesh, shardings=None.

    With a mesh, params are *initialized directly into their shards*
    (jit with out_shardings) so a 7B model never materializes
    replicated on one host.
    """

    def init_params(rng):
        variables = _init_variables(model, rng, sample_batch)
        return nn.meta.unbox(variables["params"])

    if mesh is None:
        params = init_params(rng)
        return (
            LMState(
                step=jnp.zeros((), jnp.int32),
                params=params,
                opt_state=tx.init(params),
                apply_fn=model.apply,
                tx=tx,
            ),
            None,
        )

    rules = rules_for(mesh, rules)
    params, params_sh = sharded_collection_init(
        model, rng, sample_batch, mesh, rules, lambda v: v["params"])
    opt_state, opt_sh = sharded_opt_init(tx, params, params_sh, mesh)
    replicated = NamedSharding(mesh, P())

    state = LMState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=opt_state,
        apply_fn=model.apply,
        tx=tx,
    )
    shardings = LMState(
        step=replicated,
        params=params_sh,
        opt_state=opt_sh,
        apply_fn=model.apply,
        tx=tx,
    )
    return state, shardings


def lm_targets(logits: jax.Array, batch: Batch, objective: str
               ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The single source of truth for batch conventions: map (logits,
    batch) to aligned ``(logits_used, targets, weights)``.

    mlm: labels in ``mlm_labels``, weights in ``mlm_weights``.
    causal: ``targets`` (pre-shifted) if present, else input_ids
    shifted left; ``loss_weights`` (optional) masks padding and is
    sliced to match when the shift is implicit. Both the training
    losses below and training/evaluate.py build on this — eval must
    never re-derive (and drift from) these rules.
    """
    if objective == "mlm":
        return (logits, batch["mlm_labels"],
                batch["mlm_weights"].astype(jnp.float32))
    if "targets" in batch:
        targets, logits_used = batch["targets"], logits
    else:
        targets = batch["input_ids"][:, 1:]
        logits_used = logits[:, :-1]
    weights = batch.get("loss_weights")
    if weights is None:
        weights = jnp.ones(targets.shape, jnp.float32)
    elif "targets" not in batch:
        weights = weights[:, 1:]
    return logits_used, targets, weights.astype(jnp.float32)


def lm_token_weight(batch: Batch, objective: str) -> jax.Array:
    """Total token weight of a batch under the same conventions as
    :func:`lm_targets` (no logits needed) — the normalizer gradient
    accumulation must use so unevenly-weighted microbatches (mlm
    masks, padded causal rows) still average to the exact full-batch
    gradient."""
    if objective == "mlm":
        return batch["mlm_weights"].astype(jnp.float32).sum()
    weights = batch.get("loss_weights")
    if "targets" in batch:
        shape = batch["targets"].shape
        if weights is None:
            return jnp.asarray(float(shape[0] * shape[1]), jnp.float32)
        return weights.astype(jnp.float32).sum()
    b, l = batch["input_ids"].shape
    if weights is None:
        return jnp.asarray(float(b * (l - 1)), jnp.float32)
    return weights[:, 1:].astype(jnp.float32).sum()


def _weighted_loss(logits: jax.Array, batch: Batch, objective: str
                   ) -> Tuple[jax.Array, jax.Array]:
    logits, targets, weights = lm_targets(logits, batch, objective)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    denom = jnp.maximum(weights.sum(), 1.0)
    loss = (ce * weights).sum() / denom
    acc = ((jnp.argmax(logits, -1) == targets) * weights).sum() / denom
    return loss, acc


def mlm_loss(logits: jax.Array, batch: Batch) -> Tuple[jax.Array, jax.Array]:
    """Masked-LM loss: cross entropy at positions where
    ``mlm_weights`` is 1 (labels in ``mlm_labels``)."""
    return _weighted_loss(logits, batch, "mlm")


def causal_lm_loss(logits: jax.Array, batch: Batch
                   ) -> Tuple[jax.Array, jax.Array]:
    """Next-token loss. ``targets`` defaults to input_ids shifted left;
    ``loss_weights`` (optional) masks padding."""
    return _weighted_loss(logits, batch, "causal")


LOSSES = {"mlm": mlm_loss, "causal": causal_lm_loss}


def lm_forward_with_aux(apply_fn, variables, batch, loss_fn,
                        aux_loss_weight):
    """Shared forward for both trainers (pretraining here, LoRA in
    training/finetune.py): apply with the ``"losses"`` collection
    mutable so sown auxiliary losses (the MoE load-balance loss,
    ops/moe.py) are collected and weighted identically everywhere.
    Returns (total_loss, (loss, accuracy, aux))."""
    logits, mutated = apply_fn(variables, *_model_args(batch),
                               mutable=["losses"])
    loss, acc = loss_fn(logits, batch)
    aux = sum(
        jnp.sum(leaf)
        for leaf in jax.tree.leaves(mutated.get("losses", {}))
    )
    aux = jnp.asarray(aux, loss.dtype)
    return loss + aux_loss_weight * aux, (loss, acc, aux)


def accumulated_value_and_grad(compute, params, batch: Batch, n: int,
                               objective: str = "causal"):
    """value_and_grad over ``n`` sequential microbatches.

    ``compute(params, microbatch) -> (total_loss, (loss, acc, aux))``
    where ``total_loss = loss + aux_term``. The batch's leading dim
    splits into ``n`` equal microbatches run under ``lax.scan`` —
    live activation memory drops ~n× while the optimizer sees the
    **exact full-batch gradient**: each microbatch's CE contribution
    is re-weighted by its share of the batch's token weight
    (``lm_token_weight``), so mlm masks and padded causal rows — whose
    per-microbatch weight sums differ — don't bias the average, and a
    zero-weight microbatch contributes nothing. The aux term (a mean-
    style regularizer, e.g. the MoE load-balance loss) averages
    equally over microbatches.
    """
    if n <= 1:
        (_, aux), grads = jax.value_and_grad(
            lambda p: compute(p, batch), has_aux=True)(params)
        return aux, grads

    def split(x):
        if x.shape[0] % n:
            raise ValueError(
                f"batch dim {x.shape[0]} not divisible by "
                f"grad_accum={n}")
        return x.reshape(n, x.shape[0] // n, *x.shape[1:])

    micro = jax.tree.map(split, batch)
    total_w = jnp.maximum(lm_token_weight(batch, objective), 1.0)

    def body(carry, mb):
        g_acc, l_acc, a_acc, x_acc = carry
        frac = lm_token_weight(mb, objective) / total_w

        def scaled(p):
            total, (loss, acc, aux) = compute(p, mb)
            aux_term = total - loss  # aux_loss_weight · aux, by construction
            return loss * frac + aux_term / n, (loss, acc, aux)

        (_, (loss, acc, aux)), g = jax.value_and_grad(
            scaled, has_aux=True)(params)
        g_acc = jax.tree.map(jnp.add, g_acc, g)
        return (g_acc, l_acc + loss * frac, a_acc + acc * frac,
                x_acc + aux / n), None

    zeros = jax.tree.map(jnp.zeros_like, params)
    zero = jnp.zeros((), jnp.float32)
    (grads, loss, acc, aux), _ = jax.lax.scan(
        body, (zeros, zero, zero, zero), micro)
    return (loss, acc, aux), grads


def jit_train_step(step, mesh, shardings, donate):
    """Jit a (state, batch) → (state, metrics) step with the standard
    SPMD placement: state by its sharding tree, batch over
    (data, fsdp), metrics replicated."""
    if mesh is None:
        return jax.jit(step, donate_argnums=(0,) if donate else ())
    batch_sh = batch_sharding(mesh)
    return jax.jit(
        step,
        in_shardings=(shardings, batch_sh),
        out_shardings=(shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,) if donate else (),
    )


def make_lm_train_step(
    mesh: Optional[Mesh],
    shardings: Optional[LMState],
    *,
    objective: str = "causal",
    donate: bool = True,
    aux_loss_weight: float = 0.01,
    grad_accum: int = 1,
):
    """Jitted SPMD train step for an LMState.

    ``objective``: "mlm" (BERT pretraining) or "causal" (Llama).
    Auxiliary losses sown into the ``"losses"`` collection (the MoE
    load-balance loss, ops/moe.py) are collected every step and added
    with ``aux_loss_weight``; models that sow nothing contribute zero.
    ``grad_accum`` > 1 splits each batch into that many sequential
    microbatches (see :func:`accumulated_value_and_grad`).
    """
    loss_fn = LOSSES[objective]

    def step(state: LMState, batch: Batch):
        def compute(params, mb):
            return lm_forward_with_aux(
                state.apply_fn, {"params": params}, mb, loss_fn,
                aux_loss_weight)

        (loss, acc, aux), grads = accumulated_value_and_grad(
            compute, state.params, batch, grad_accum, objective)
        updates, new_opt = state.tx.update(grads, state.opt_state,
                                           state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss,
            "accuracy": acc,
            "aux_loss": aux,
            "grad_norm": optax.global_norm(grads),
        }
        return (
            state.replace(step=state.step + 1, params=new_params,
                          opt_state=new_opt),
            metrics,
        )

    return jit_train_step(step, mesh, shardings, donate)


def place_lm_batch(mesh: Mesh, batch: Batch) -> Batch:
    return jax.device_put(batch, batch_sharding(mesh))
