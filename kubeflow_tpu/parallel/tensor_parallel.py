# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Tensor parallelism via logical-axis sharding rules (GSPMD).

Megatron-style TP the XLA way: models annotate parameters with
*logical* axis names (flax ``nn.with_partitioning`` /
``nn.with_logical_partitioning``), and a rule table maps logical names
to mesh axes. ``pjit`` + GSPMD then insert the all-reduces a
hand-written Megatron layer would issue explicitly — column-parallel
matmul (activations gathered) followed by row-parallel (partial sums
all-reduced) falls out of the sharding propagation.

The reference has nothing comparable (SURVEY §2.5: TP/PP/EP/SP all
absent); this is the greenfield layer the BASELINE BERT/Llama configs
need.

Standard logical axis vocabulary (used by models/bert.py, models/llama.py):

- ``batch``   — batch dim                → (dcn_data, data, fsdp)
- ``seq``     — sequence dim             → seq (activations only)
- ``embed``   — residual-stream features → fsdp (ZeRO-3 shard)
- ``mlp``     — FFN hidden dim           → tensor
- ``heads``   — attention head dim       → tensor
- ``kv``      — per-head feature dim     → None
- ``vocab``   — embedding/logits vocab   → tensor
- ``expert``  — MoE expert dim           → expert
- ``stage``   — pipeline stage dim       → pipeline
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxes = Union[None, str, Tuple[str, ...]]

DEFAULT_RULES: Dict[str, MeshAxes] = {
    "batch": ("dcn_data", "data", "fsdp"),
    "seq": "seq",
    "embed": "fsdp",
    "mlp": "tensor",
    "heads": "tensor",
    "kv": None,
    "vocab": "tensor",
    "expert": "expert",
    "stage": "pipeline",
}


def rules_for(mesh: Mesh,
              overrides: Optional[Mapping[str, MeshAxes]] = None
              ) -> Dict[str, MeshAxes]:
    """DEFAULT_RULES pruned to axes the mesh actually has (size > 1) —
    a rule pointing at a size-1 axis is harmless but noisy in debug
    output — with optional per-model overrides."""
    rules = dict(DEFAULT_RULES)
    if overrides:
        rules.update(overrides)

    def live(axes: MeshAxes) -> MeshAxes:
        if axes is None:
            return None
        if isinstance(axes, str):
            return axes if mesh.shape.get(axes, 1) > 1 else None
        kept = tuple(a for a in axes if mesh.shape.get(a, 1) > 1)
        return kept or None

    return {k: live(v) for k, v in rules.items()}


def logical_to_sharding(
    mesh: Mesh,
    logical_axes: Any,
    rules: Optional[Mapping[str, MeshAxes]] = None,
) -> Any:
    """Map a pytree of logical-axis tuples (flax ``get_partition_spec``
    output style: leaves are ``PartitionSpec('embed', 'mlp')`` or
    tuples of names) to NamedShardings."""
    rules = dict(rules if rules is not None else rules_for(mesh))

    def convert(leaf: Any) -> NamedSharding:
        if leaf is None:
            return NamedSharding(mesh, P())
        names = tuple(leaf)
        # A mesh axis may appear at most once per spec: if two logical
        # names map to the same axis (e.g. d_model→d_model kernels),
        # the first occurrence wins and the rest replicate.
        used: set = set()
        dims = []
        for n in names:
            axes = rules.get(n) if n else None
            members = (axes,) if isinstance(axes, str) else tuple(axes or ())
            kept = tuple(a for a in members if a not in used)
            used.update(kept)
            if not kept:
                dims.append(None)
            else:
                dims.append(kept[0] if len(kept) == 1 else kept)
        return NamedSharding(mesh, P(*dims))

    def is_axes_leaf(x: Any) -> bool:
        # An axis spec is None, a PartitionSpec, or a tuple of axis
        # names — NOT any tuple (collections like flax `sow` wrap
        # values in tuples, which must flatten as containers).
        if x is None or isinstance(x, P):
            return True
        return isinstance(x, tuple) and all(
            e is None or isinstance(e, str) for e in x)

    return jax.tree.map(convert, logical_axes, is_leaf=is_axes_leaf)


def variables_sharding(
    mesh: Mesh,
    abstract_variables: Any,
    rules: Optional[Mapping[str, MeshAxes]] = None,
) -> Any:
    """Sharding tree for a flax variable dict whose params carry
    ``nn.Partitioned`` metadata (``nn.get_partition_spec`` under the
    hood); unannotated leaves replicate."""
    import flax.linen as nn

    logical = nn.get_partition_spec(abstract_variables)
    return logical_to_sharding(mesh, logical, rules)
