# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""SPMD pipeline parallelism (GPipe-style) over the ``pipeline`` axis.

Greenfield vs the reference (its only parallelism was the async
parameter-server topology, SURVEY §2.5): stages live on the
``pipeline`` mesh axis, activations hop stage→stage with
``lax.ppermute`` (one ICI neighbor hop), and microbatches stream
through the classic GPipe schedule — ``n_micro + n_stages - 1`` ticks,
every device running the same jitted program (SPMD: no per-stage
programs, no host-side scheduler — the schedule is arithmetic on the
stage index inside one ``shard_map``).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

StageFn = Callable[[Any, jax.Array], jax.Array]


def _pipeline_inner(
    stage_fn: StageFn,
    params: Any,
    microbatches: jax.Array,
    axis_name: str,
) -> jax.Array:
    """Runs INSIDE shard_map. ``params``: this stage's params (leading
    stage dim of size 1 already squeezed by the in_spec reshape).
    ``microbatches``: [n_micro, mb, ...] (replicated across stages)."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    perm = [(i, i + 1) for i in range(n - 1)]

    def tick(t, carry):
        state, outputs = carry
        # Stage 0 ingests microbatch t (clipped index is safe: the
        # result is only *used* while t < n_micro).
        feed = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False
        )
        state = jnp.where(idx == 0, feed, state)
        out = stage_fn(params, state)
        # Last stage completed microbatch t-(n-1) this tick.
        done_idx = jnp.clip(t - (n - 1), 0, n_micro - 1)
        write = (idx == n - 1) & (t >= n - 1)
        outputs = jnp.where(
            write,
            jax.lax.dynamic_update_index_in_dim(outputs, out, done_idx, 0),
            outputs,
        )
        # Hand activations to the next stage (stage 0 receives zeros,
        # immediately overwritten by the next feed).
        state = jax.lax.ppermute(out, axis_name, perm)
        return state, outputs

    state = jnp.zeros_like(microbatches[0])
    outputs = jnp.zeros_like(microbatches)
    _, outputs = jax.lax.fori_loop(
        0, n_micro + n - 1, tick, (state, outputs)
    )
    # Broadcast the last stage's outputs to every stage so the result
    # leaves shard_map replicated.
    outputs = jnp.where(idx == n - 1, outputs, 0)
    return jax.lax.psum(outputs, axis_name)


def spmd_pipeline(
    stage_fn: StageFn,
    stacked_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    n_microbatches: int,
    axis_name: str = "pipeline",
    batch_axis: str = None,
) -> jax.Array:
    """Run ``x`` through ``n_stages`` copies of ``stage_fn``.

    ``stacked_params``: pytree whose leaves have a leading
    ``n_stages`` dimension (stage i's slice feeds stage i) — sharded
    over the pipeline axis so each device holds only its stage.
    ``x``: [batch, ...]; batch must divide by ``n_microbatches``.
    ``batch_axis``: optional mesh axis (e.g. ``"data"``) the
    microbatch rows are sharded over — pp×dp composition: each
    data-coordinate pipelines its own rows instead of redundantly
    recomputing the full batch.
    Output has the same shape as ``x`` run through all stages in order.
    """
    n_stages = mesh.shape[axis_name]
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(f"batch {batch} % microbatches {n_microbatches}")
    mb = batch // n_microbatches
    if batch_axis is not None and mb % mesh.shape[batch_axis]:
        raise ValueError(
            f"microbatch rows {mb} % {batch_axis} axis "
            f"{mesh.shape[batch_axis]}")
    microbatches = x.reshape((n_microbatches, mb) + x.shape[1:])

    param_spec = jax.tree.map(lambda _: P(axis_name), stacked_params)
    mb_spec = P(None, batch_axis) if batch_axis else P()

    def inner(params, mbs):
        params = jax.tree.map(lambda p: p[0], params)  # squeeze stage dim
        return _pipeline_inner(stage_fn, params, mbs, axis_name)

    out = jax.shard_map(
        inner,
        mesh=mesh,
        in_specs=(param_spec, mb_spec),
        out_specs=mb_spec,
        check_vma=False,
    )(stacked_params, microbatches)
    del n_stages
    return out.reshape((batch,) + out.shape[2:])


def _interleaved_inner(
    stage_fn: StageFn,
    params: Any,
    microbatches: jax.Array,
    axis_name: str,
    n_virtual: int,
) -> jax.Array:
    """Runs INSIDE shard_map. ``params``: this device's virtual-stage
    params, leaves [n_virtual, ...] (device dim already squeezed).
    ``microbatches``: [n_micro, mb, ...] (replicated across stages).

    Circular (interleaved / "looping") schedule: total stage count
    S = n_virtual * n_devices, stage ``s`` living on device
    ``s % n`` as virtual stage ``s // n``. Microbatch ``m`` enters
    stage 0 at tick ``(m // n) * n * v + (m % n)`` and then advances
    one stage per tick without stalling; activations hop device→
    device on a circular ``ppermute`` (the wrap n-1→0 carries a
    microbatch to its next virtual stage). The schedule arithmetic
    below decodes, for every (tick, device), which microbatch and
    virtual stage that slot holds — each slot is unique, so the whole
    schedule is index math inside one SPMD loop, exactly like the
    GPipe variant.
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    v = n_virtual
    n_micro = microbatches.shape[0]
    perm = [(i, (i + 1) % n) for i in range(n)]
    # Last microbatch enters stage 0 at this tick, then needs S ticks.
    total_ticks = ((n_micro - 1) // n) * n * v + ((n_micro - 1) % n) + n * v

    def tick(t, carry):
        state, outputs = carry
        # Decode this (tick, device) slot. K = floor((t - idx) / n) is
        # the device's slot counter; it splits into (group, virtual
        # stage), and the microbatch residue r completes the id.
        r = jnp.mod(t - idx, n)
        big_k = (t - idx - r) // n
        group = big_k // v
        virt = jnp.mod(big_k, v)
        m = group * n + r
        active = (big_k >= 0) & (m < n_micro)
        m_safe = jnp.clip(m, 0, n_micro - 1)
        feed = jax.lax.dynamic_index_in_dim(
            microbatches, m_safe, 0, keepdims=False)
        ingest = active & (virt == 0) & (idx == 0)
        state = jnp.where(ingest, feed, state)
        # virt = mod(·, v) is already in [0, v) even for negative
        # big_k (inactive slots), so it indexes safely as-is.
        stage_params = jax.tree.map(
            lambda p: jax.lax.dynamic_index_in_dim(
                p, virt, 0, keepdims=False),
            params)
        out = stage_fn(stage_params, state)
        write = active & (virt == v - 1) & (idx == n - 1)
        outputs = jnp.where(
            write,
            jax.lax.dynamic_update_index_in_dim(outputs, out, m_safe, 0),
            outputs,
        )
        state = jax.lax.ppermute(out, axis_name, perm)
        return state, outputs

    state = jnp.zeros_like(microbatches[0])
    outputs = jnp.zeros_like(microbatches)
    _, outputs = jax.lax.fori_loop(
        0, total_ticks, tick, (state, outputs)
    )
    outputs = jnp.where(idx == n - 1, outputs, 0)
    return jax.lax.psum(outputs, axis_name)


def interleave_stage_params(stacked_params: Any, n_devices: int) -> Any:
    """[S, ...]-stacked stage params → the [v, n_devices, ...] layout
    :func:`spmd_pipeline_interleaved` consumes (stage ``s = q*n + d``
    lands at position ``[q, d]``, i.e. device ``d`` holds the cyclic
    set of stages — a plain reshape, since ``s → (s // n, s % n)``)."""

    def reshape(p):
        if p.shape[0] % n_devices:
            raise ValueError(
                f"stage count {p.shape[0]} % devices {n_devices}")
        return p.reshape((p.shape[0] // n_devices, n_devices)
                         + p.shape[1:])

    return jax.tree.map(reshape, stacked_params)


def spmd_pipeline_interleaved(
    stage_fn: StageFn,
    stacked_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    n_microbatches: int,
    n_virtual: int,
    axis_name: str = "pipeline",
    batch_axis: str = None,
) -> jax.Array:
    """Interleaved (virtual-stage / "circular") pipeline schedule.

    Same contract as :func:`spmd_pipeline` but with
    ``S = n_virtual * n_devices`` total stages, device ``d`` holding
    the cyclic stage set ``{q*n + d}``. Each tick runs ONE virtual
    stage (1/v of a GPipe tick), and the fill/drain cost stays at
    ``n - 1`` of these small ticks — so the idle fraction drops from
    GPipe's ``(n-1)/(n_micro + n-1)`` to
    ``(n-1)/(n_micro*v + n-1)`` (see
    :func:`bubble_fraction_interleaved`), bought with v× more
    ppermute hops per microbatch (cheap on ICI).

    ``stacked_params``: pytree with leading dims ``[n_virtual,
    n_devices, ...]`` in the layout of :func:`interleave_stage_params`.
    """
    n_stages = mesh.shape[axis_name]
    leaf = jax.tree.leaves(stacked_params)[0]
    if leaf.shape[:1] != (n_virtual,) or leaf.shape[1] != n_stages:
        raise ValueError(
            f"stacked_params leading dims {leaf.shape[:2]} != "
            f"(n_virtual={n_virtual}, pipeline={n_stages})")
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(f"batch {batch} % microbatches {n_microbatches}")
    mb = batch // n_microbatches
    if batch_axis is not None and mb % mesh.shape[batch_axis]:
        raise ValueError(
            f"microbatch rows {mb} % {batch_axis} axis "
            f"{mesh.shape[batch_axis]}")
    microbatches = x.reshape((n_microbatches, mb) + x.shape[1:])

    param_spec = jax.tree.map(lambda _: P(None, axis_name),
                              stacked_params)
    mb_spec = P(None, batch_axis) if batch_axis else P()

    def inner(params, mbs):
        params = jax.tree.map(lambda p: p[:, 0], params)  # squeeze dev
        return _interleaved_inner(stage_fn, params, mbs, axis_name,
                                  n_virtual)

    out = jax.shard_map(
        inner,
        mesh=mesh,
        in_specs=(param_spec, mb_spec),
        out_specs=mb_spec,
        check_vma=False,
    )(stacked_params, microbatches)
    return out.reshape((batch,) + out.shape[2:])


def bubble_fraction(n_stages: int, n_microbatches: int) -> float:
    """GPipe schedule idle fraction — the depth-usability number.

    The SPMD loop issues ``n_micro + n_stages - 1`` ticks; every stage
    executes on every tick, but only ``n_stages * n_micro`` stage-ticks
    carry a live microbatch, so the idle fraction is
    ``(n_stages - 1) / (n_micro + n_stages - 1)`` — identically for
    the backward pass (autodiff reverses the same loop), so this is
    the whole-step figure. 1F1B *reorders* fwd/bwd work (an activation-
    memory win) but fills none of these idle slots; only interleaved /
    virtual-stage schedules shrink the bubble
    (:func:`spmd_pipeline_interleaved`,
    :func:`bubble_fraction_interleaved`), at the cost of ``v``-fold
    more ppermute hops. Microbatch count is the lever: bubble < 10%
    needs ``n_micro > 9 * (n_stages - 1)``.
    """
    if n_stages < 1 or n_microbatches < 1:
        raise ValueError(
            f"need n_stages >= 1 and n_microbatches >= 1; got "
            f"{n_stages}, {n_microbatches}")
    return (n_stages - 1) / (n_microbatches + n_stages - 1)


def bubble_fraction_interleaved(n_stages: int, n_microbatches: int,
                                n_virtual: int) -> float:
    """Idle fraction of the circular schedule in
    :func:`spmd_pipeline_interleaved`.

    The loop issues ``((M-1)//n)*n*v + (M-1)%n + n*v`` ticks; each
    device carries ``M * v`` live virtual-stage executions. When
    ``n`` divides ``M`` this reduces to ``(n-1)/(M*v + n-1)`` — the
    GPipe bubble with the microbatch count multiplied by ``v``
    (Megatron-LM's interleaved-schedule result: fill/drain is still
    ``n-1`` hops, but each hop is 1/v of a device's per-microbatch
    work). Doubling ``v`` roughly halves the bubble at fixed M.
    """
    if n_stages < 1 or n_microbatches < 1 or n_virtual < 1:
        raise ValueError(
            f"need n_stages, n_microbatches, n_virtual >= 1; got "
            f"{n_stages}, {n_microbatches}, {n_virtual}")
    n, m, v = n_stages, n_microbatches, n_virtual
    ticks = ((m - 1) // n) * n * v + ((m - 1) % n) + n * v
    return (ticks - m * v) / ticks


def stack_stage_params(param_list) -> Any:
    """Stack per-stage param pytrees into one tree with a leading
    stage dimension (the layout :func:`spmd_pipeline` consumes)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_list)
