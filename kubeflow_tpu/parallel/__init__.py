from kubeflow_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    build_mesh,
    batch_sharding,
    replicated,
    fsdp_params_sharding,
    logical_sharding,
)
