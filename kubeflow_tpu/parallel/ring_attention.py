# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Ring attention + Ulysses all-to-all: sequence/context parallelism.

The reference has no long-context story at all (no sequence models, no
sequence parallelism — its one distributed strategy is the parameter-
server topology, ``kubeflow/tf-job/prototypes/tf-cnn-benchmarks.jsonnet:41``).
These are the TPU-native long-context strategies, first-class per the
rebuild spec:

- **Ring attention**: each device on the ``seq`` mesh axis holds one
  sequence shard of Q/K/V. KV shards rotate around the ring with
  ``lax.ppermute`` (nearest-neighbor ICI hops — the cheapest collective
  on a torus) while each device accumulates attention for its local
  queries with the online-softmax update
  (:func:`kubeflow_tpu.ops.attention.attention_block_update`). Peak
  memory is O(L/N · L/N) per device, enabling sequences N× longer than
  one chip could hold; compute overlaps the next shard's transfer
  because XLA pipelines the ppermute DMA against the einsum.
- **Ulysses (all-to-all)**: re-shard from sequence-parallel to
  head-parallel with ``lax.all_to_all``, run fused flash attention on
  full sequences for a subset of heads, and re-shard back. Cheaper at
  moderate lengths (2 all-to-alls vs N-1 ring steps), but caps the seq
  axis at the head count; ring has no such cap.

Both run inside :func:`jax.shard_map` over the standard mesh
(:mod:`kubeflow_tpu.parallel.mesh`): batch on
``(dcn_data, data, fsdp)``, sequence on ``seq``, heads optionally on
``tensor``.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu.ops.flash_attention import flash_attention
from kubeflow_tpu.ops.attention import (
    attention_block_update,
    attention_finalize,
    attention_init_carry,
)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "seq",
    causal: bool = False,
    scale: Optional[float] = None,
    kv_segment_valid: Optional[jax.Array] = None,
) -> jax.Array:
    """Ring attention over ``axis_name``. Call INSIDE shard_map.

    ``q, k, v``: local shards ``[batch, seq_local, heads, head_dim]``,
    the global sequence laid out contiguously along the axis (device i
    holds positions ``[i*L, (i+1)*L)``). ``kv_segment_valid`` is the
    local [batch, seq_local] 0/1 padding mask; it rotates around the
    ring with its KV shard.
    """
    b, l_local, h, d = q.shape
    scale = d ** -0.5 if scale is None else scale
    n = jax.lax.axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    q_offset = my_idx * l_local
    # Rotate KV shards "forward" one neighbor per step: after s steps,
    # device i holds the shard that started on device (i - s) mod n.
    perm = [(j, (j + 1) % n) for j in range(n)]
    has_mask = kv_segment_valid is not None

    def body(step, carry):
        o, m, l, ring = carry
        src_idx = (my_idx - step) % n
        o, m, l = attention_block_update(
            (o, m, l), q, ring[0], ring[1],
            scale=scale, q_offset=q_offset,
            kv_offset=src_idx * l_local, causal=causal,
            kv_segment_valid=ring[2] if has_mask else None,
        )
        # No permute needed after the final accumulation.
        ring = jax.lax.cond(
            step < n - 1,
            lambda t: jax.lax.ppermute(t, axis_name, perm),
            lambda t: t,
            ring,
        )
        return o, m, l, ring

    ring = (k, v, kv_segment_valid) if has_mask else (k, v)
    carry = (*attention_init_carry(b, l_local, h, d), ring)
    o, _, l, _ = jax.lax.fori_loop(0, n, body, carry)
    return attention_finalize(o, l, q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "seq",
    causal: bool = False,
    scale: Optional[float] = None,
    kv_segment_valid: Optional[jax.Array] = None,
) -> jax.Array:
    """All-to-all sequence parallelism. Call INSIDE shard_map.

    Re-shards [B, L/N, H, D] → [B, L, H/N, D] (full sequence, head
    subset), runs fused flash attention, and re-shards back. Head
    counts must divide by the axis size. ``kv_segment_valid`` is the
    local [B, L/N] padding mask.
    """
    n = jax.lax.axis_size(axis_name)
    if n == 1:
        # Same O(L·block) local path as the n > 1 case — dense here
        # would materialize the L×L scores exactly at the lengths
        # this strategy exists for.
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               kv_segment_valid=kv_segment_valid)

    def seq_to_heads(x):
        # [B, L/N, H, D] → [B, L, H/N, D]: split heads, gather seq.
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    full_mask = None
    if kv_segment_valid is not None:
        # Heads are re-sharded but keys become full-length: every
        # device needs the whole [B, L] padding mask.
        full_mask = jax.lax.all_gather(
            kv_segment_valid, axis_name, axis=1, tiled=True)
    # Local attention over the gathered FULL sequence: use the fused
    # flash kernel — at the long contexts that motivate sequence
    # parallelism, a dense local attention would materialize the
    # (L × L) score matrix this strategy exists to avoid (on non-TPU
    # backends / odd shapes flash_attention degrades to the XLA
    # blockwise path, still O(L·block) memory).
    o = flash_attention(
        seq_to_heads(q), seq_to_heads(k), seq_to_heads(v),
        causal=causal, scale=scale, kv_segment_valid=full_mask,
    )
    return heads_to_seq(o)


def make_sequence_parallel_attention(
    mesh: Mesh,
    *,
    strategy: str = "ring",
    causal: bool = False,
    scale: Optional[float] = None,
    batch_axes=("dcn_data", "data", "fsdp"),
    seq_axis: str = "seq",
    head_axis: Optional[str] = "tensor",
) -> Callable[[jax.Array, jax.Array, jax.Array], jax.Array]:
    """Wrap ring/ulysses attention in shard_map over ``mesh``.

    Returns a function on globally-addressed [B, L, H, D] arrays; the
    mesh's sharding does batch on ``batch_axes``, sequence on
    ``seq_axis``, heads on ``head_axis`` (ring only — Ulysses uses the
    head dimension for its own re-sharding).
    """
    if strategy == "ring":
        inner = functools.partial(
            ring_attention, axis_name=seq_axis, causal=causal, scale=scale
        )
        h_axis = head_axis
    elif strategy == "ulysses":
        inner = functools.partial(
            ulysses_attention, axis_name=seq_axis, causal=causal, scale=scale
        )
        h_axis = None
    else:
        raise ValueError(f"unknown strategy {strategy!r}")

    spec = P(batch_axes, seq_axis, h_axis, None)
    mask_spec = P(batch_axes, seq_axis)

    def fn(q, k, v, *, kv_segment_valid=None):
        if kv_segment_valid is None:
            return jax.shard_map(
                lambda a, b, c: inner(a, b, c),
                mesh=mesh,
                in_specs=(spec, spec, spec),
                out_specs=spec,
                check_vma=False,
            )(q, k, v)
        return jax.shard_map(
            lambda a, b, c, mv: inner(a, b, c, kv_segment_valid=mv),
            mesh=mesh,
            in_specs=(spec, spec, spec, mask_spec),
            out_specs=spec,
            check_vma=False,
        )(q, k, v, kv_segment_valid)

    return fn
