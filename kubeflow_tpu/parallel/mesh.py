# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Device-mesh construction and sharding presets.

This is the TPU-native replacement for the reference's entire
parameter-server topology (MASTER/WORKER/PS replicas wired through
``TF_CONFIG``, reference ``tf-controller-examples/tf-cnn/launcher.py:64-77``
and ``kubeflow/tf-job/tf-job.libsonnet:5-35``): instead of workers
pushing gradients to PS pods over gRPC, every strategy is a sharding of
one SPMD program over a :class:`jax.sharding.Mesh`, and XLA inserts the
collectives (all-reduce over ICI within a slice, DCN across slices).

Standard axis names, used consistently across models and the trainer:

- ``dcn_data`` — OUTERMOST: data parallelism *across slices/pods*
  (gradient all-reduce over DCN). Hierarchical collectives fall out
  of axis order: XLA reduce-scatters within a slice over ICI, then
  all-reduces the per-slice partial over DCN — the bandwidth-correct
  decomposition, without any NCCL/MPI-style topology code.
- ``data``  — data parallelism within a slice (batch axis, ICI).
- ``fsdp``  — parameter sharding (ZeRO-3 style), also used as a second
  batch axis.
- ``tensor`` — tensor (megatron-style) model parallelism.
- ``seq``   — sequence/context parallelism (ring attention).
- ``expert`` — MoE expert parallelism.

A mesh spec only names the axes it uses; absent axes have size 1.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_ORDER: Tuple[str, ...] = (
    "dcn_data", "data", "fsdp", "pipeline", "seq", "expert", "tensor")

# The multi-slice (megascale) env contract: on a real multi-slice TPU
# deployment the runtime reads these to wire the cross-slice DCN
# transport; the TPUJob operator injects them on every worker of a
# numSlices > 1 job (operator/reconciler.py), and build_mesh() below
# reads the slice count so the hybrid dcn_data layout comes from the
# deployment env instead of per-program mesh flags. Parity: the
# reference operator's essential job was assembling the cluster spec
# and injecting it into every pod as TF_CONFIG
# (kubeflow/core/tf-job.libsonnet:31-95); MEGASCALE_* + KFT_* is the
# TPU translation (SURVEY §2.4).
ENV_MEGASCALE_SLICES = "MEGASCALE_NUM_SLICES"
ENV_MEGASCALE_SLICE_ID = "MEGASCALE_SLICE_ID"
ENV_MEGASCALE_COORD = "MEGASCALE_COORDINATOR_ADDRESS"


def slice_count_from_env(env=os.environ) -> int:
    """Number of TPU slices this job spans, per the megascale env
    (1 when unset — single-slice jobs carry no MEGASCALE_* vars)."""
    raw = env.get(ENV_MEGASCALE_SLICES, "").strip()
    if not raw:
        return 1
    count = int(raw)
    if count < 1:
        raise ValueError(f"{ENV_MEGASCALE_SLICES}={raw!r} must be >= 1")
    return count


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Sizes for each mesh axis. ``-1`` on at most one axis means
    "all remaining devices" (like a reshape wildcard)."""

    data: int = 1
    fsdp: int = 1
    pipeline: int = 1
    seq: int = 1
    expert: int = 1
    tensor: int = 1
    dcn_data: int = 1  # cross-slice (DCN) data parallelism, outermost

    def sizes(self) -> Dict[str, int]:
        return {name: getattr(self, name) for name in AXIS_ORDER}

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = self.sizes()
        wildcards = [k for k, v in sizes.items() if v == -1]
        if len(wildcards) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {wildcards}")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wildcards:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wildcards[0]] = n_devices // fixed
        total = math.prod(sizes.values())
        if total != n_devices:
            raise ValueError(
                f"mesh spec {sizes} needs {total} devices, have {n_devices}"
            )
        return MeshSpec(**sizes)


def build_mesh(
    spec: Optional[MeshSpec] = None,
    devices: Optional[Sequence[Any]] = None,
) -> Mesh:
    """Build a Mesh over ``devices`` (default: all).

    Axis order puts ``dcn_data`` outermost (slice boundaries), then
    ``data``, with ``tensor`` innermost so tensor-parallel collectives
    ride the fastest ICI links — the scaling-book recipe:
    bandwidth-hungry axes get the contiguous device neighborhoods that
    ``mesh_utils`` maps to physical torus proximity.

    Multi-slice: when the operator injected ``MEGASCALE_NUM_SLICES``
    (numSlices > 1 TPUJobs), a spec that doesn't name ``dcn_data``
    gets it set to the slice count automatically — the program
    describes its within-slice layout, the deployment env supplies the
    cross-slice axis. A spec that NAMES a conflicting dcn_data fails
    loudly (a mesh disagreeing with the provisioned topology would
    route ICI-intensity collectives over DCN or crash at runtime).
    """
    devices = list(devices if devices is not None else jax.devices())
    spec = spec or MeshSpec(data=-1)
    env_slices = slice_count_from_env()
    if env_slices > 1:
        if spec.dcn_data in (1, -1):
            spec = dataclasses.replace(spec, dcn_data=env_slices)
        elif spec.dcn_data != env_slices:
            raise ValueError(
                f"mesh spec dcn_data={spec.dcn_data} contradicts "
                f"{ENV_MEGASCALE_SLICES}={env_slices} — the job was "
                f"provisioned with {env_slices} slices")
    spec = spec.resolve(len(devices))
    sizes = spec.sizes()
    shape = tuple(sizes[name] for name in AXIS_ORDER)
    try:
        from jax.experimental import mesh_utils

        if sizes["dcn_data"] > 1:
            # Hybrid layout: the dcn axis spans slice/granule
            # boundaries, all other axes stay within a slice so their
            # collectives ride ICI. Falls back to a plain reshape when
            # slice metadata is unavailable (CPU simulation).
            ici_shape = (1,) + shape[1:]
            dcn_shape = (sizes["dcn_data"],) + (1,) * (len(shape) - 1)
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices)
        else:
            dev_array = mesh_utils.create_device_mesh(
                shape, devices=devices)
    except Exception:
        if (sizes["dcn_data"] > 1 and devices
                and getattr(devices[0], "platform", "") == "tpu"):
            # On real TPU slices a failed hybrid construction (e.g.
            # dcn_data != slice count) must not silently degrade to a
            # reshape that routes ICI-intensity axes over DCN.
            raise
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def respec_for_devices(spec: MeshSpec, n_devices: int) -> MeshSpec:
    """Refit a MeshSpec to a DIFFERENT device count by re-solving its
    data-parallel axes — the elastic-gang resize move (r16): a lost
    host shrinks the device pool, the model-parallel axes (tensor /
    pipeline / seq / expert / dcn_data) must keep their sizes (the
    parameter factorization is baked into the checkpoint shapes), so
    only ``data × fsdp`` re-factorizes. ``fsdp`` keeps as much of its
    size as still divides the remainder (gcd), the rest folds into
    ``data``. Raises when the model axes alone don't divide
    ``n_devices`` — that loss is not elastically recoverable."""
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    sizes = spec.sizes()
    model_axes = {k: v for k, v in sizes.items()
                  if k not in ("data", "fsdp")}
    if any(v == -1 for v in model_axes.values()):
        raise ValueError(
            f"respec_for_devices needs concrete model axes, got "
            f"{model_axes}")
    fixed = math.prod(model_axes.values())
    if n_devices % fixed:
        raise ValueError(
            f"model axes {model_axes} (product {fixed}) do not "
            f"divide {n_devices} devices — not elastically "
            f"recoverable")
    remaining = n_devices // fixed
    fsdp = sizes["fsdp"] if sizes["fsdp"] != -1 else remaining
    fsdp = math.gcd(max(1, fsdp), remaining)
    return MeshSpec(**{**model_axes,
                       "fsdp": fsdp, "data": remaining // fsdp})


def batch_sharding(mesh: Mesh, ndim: int = 0) -> NamedSharding:
    """Sharding for a batch: leading axis split over
    (dcn_data, data, fsdp).

    ``ndim`` 0 means "any rank" (only the leading dim is constrained).
    """
    del ndim
    return NamedSharding(mesh, P(("dcn_data", "data", "fsdp")))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def fsdp_params_sharding(mesh: Mesh, params: Any,
                         min_weight_size: int = 2 ** 16) -> Any:
    """ZeRO-3-style sharding tree for a param pytree.

    Each large-enough weight is sharded along its largest
    fsdp-divisible dimension; everything else is replicated. This is
    deliberately shape-driven rather than name-driven so it works for
    any model; models with stronger opinions use logical axis
    annotations instead (:func:`logical_sharding`).
    """
    fsdp_size = mesh.shape["fsdp"]

    def spec_for(x: Any) -> NamedSharding:
        shape = getattr(x, "shape", ())
        if fsdp_size == 1 or math.prod(shape or (0,)) < min_weight_size:
            return NamedSharding(mesh, P())
        candidates = [
            (dim_size, idx)
            for idx, dim_size in enumerate(shape)
            if dim_size % fsdp_size == 0
        ]
        if not candidates:
            return NamedSharding(mesh, P())
        _, idx = max(candidates)
        spec = [None] * len(shape)
        spec[idx] = "fsdp"
        return NamedSharding(mesh, P(*spec))

    return jax.tree.map(spec_for, params)


def mirror_param_shardings(opt_tree: Any, params_sh: Any,
                           replicated_sh: NamedSharding) -> Any:
    """Shard optimizer-state leaves like the params they mirror.

    Optax states embed copies of the param tree (adam ``mu``/``nu``,
    sgd ``trace``), so a mirrored leaf's tree path *ends with* the full
    path of its param. Matching by path rather than shape keeps
    same-shaped params with different layouts (e.g. an attention query
    kernel ``('embed','heads')`` vs its out kernel
    ``('heads','embed')``, both (d, d)) on their own shardings —
    a shape match would force resharding collectives between grads and
    moments every step. Leaves that mirror no param (step counters)
    replicate.
    """
    flat, _ = jax.tree_util.tree_flatten_with_path(params_sh)
    by_path = {tuple(map(str, path)): sh for path, sh in flat}

    def lookup(path, leaf):
        del leaf
        keys = tuple(map(str, path))
        for start in range(len(keys)):
            sh = by_path.get(keys[start:])
            if sh is not None:
                return sh
        return replicated_sh

    return jax.tree_util.tree_map_with_path(lookup, opt_tree)


def logical_sharding(mesh: Mesh, logical_axes: Any,
                     rules: Dict[str, Optional[str]]) -> Any:
    """Map a pytree of logical-axis tuples to NamedShardings via rules.

    ``logical_axes`` mirrors the param tree with tuples like
    ``("embed", "mlp")``; ``rules`` maps logical names to mesh axes
    (or None for replication). The flax-partitioning idea without the
    flax dependency, so haiku/plain-pytree models can use it too.
    """

    def to_sharding(axes: Any) -> NamedSharding:
        if axes is None:
            return NamedSharding(mesh, P())
        spec = tuple(rules.get(a) for a in axes)
        return NamedSharding(mesh, P(*spec))

    return jax.tree.map(
        to_sharding, logical_axes,
        is_leaf=lambda x: x is None or isinstance(x, tuple),
    )
