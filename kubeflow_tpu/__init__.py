# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""kubeflow_tpu — a TPU-native ML-platform deployment framework.

A ground-up rebuild of the capabilities of early Kubeflow
(reference: chairco/kubeflow) designed TPU-first:

- ``manifests``/``params``/``cli``: a typed Kubernetes manifest compiler
  replacing the ksonnet/Jsonnet prototype layer (reference
  ``kubeflow/*/prototypes/*.jsonnet`` + ``*.libsonnet``).
- ``operator``: a TPUJob CRD + reconciler with gang (whole-slice)
  scheduling, replacing the parameter-server tf-operator
  (reference ``kubeflow/core/tf-job.libsonnet``).
- ``models``/``ops``/``parallel``/``training``: the JAX/XLA training engine
  (pjit/shard_map over a device mesh, pallas kernels) replacing
  TensorFlow + tf_cnn_benchmarks.
- ``serving``: a versioned-model TPU predictor + REST proxy replacing
  tensorflow_model_server + the Tornado http-proxy
  (reference ``kubeflow/tf-serving``, ``components/k8s-model-server``).
- ``hub``: notebook-spawner configuration defaulting to jax[tpu] kernels
  (reference ``kubeflow/core/jupyterhub*``).
- ``testing``: junit/golden/e2e harness (reference ``testing/``).
"""

__version__ = "0.1.0"
