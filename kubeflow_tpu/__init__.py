"""kubeflow_tpu — a TPU-native ML-platform deployment framework.

A ground-up rebuild of the capabilities of early Kubeflow
(reference: chairco/kubeflow) designed TPU-first:

- ``manifests``/``params``/``cli``: a typed Kubernetes manifest compiler
  replacing the ksonnet/Jsonnet prototype layer (reference
  ``kubeflow/*/prototypes/*.jsonnet`` + ``*.libsonnet``).
- ``operator``: a TPUJob CRD + reconciler with gang (whole-slice)
  scheduling, replacing the parameter-server tf-operator
  (reference ``kubeflow/core/tf-job.libsonnet``).
- ``models``/``ops``/``parallel``/``training``: the JAX/XLA training engine
  (pjit/shard_map over a device mesh, pallas kernels) replacing
  TensorFlow + tf_cnn_benchmarks.
- ``serving``: a versioned-model TPU predictor + REST proxy replacing
  tensorflow_model_server + the Tornado http-proxy
  (reference ``kubeflow/tf-serving``, ``components/k8s-model-server``).
- ``hub``: notebook-spawner configuration defaulting to jax[tpu] kernels
  (reference ``kubeflow/core/jupyterhub*``).
- ``testing``: junit/golden/e2e harness (reference ``testing/``).
"""

__version__ = "0.1.0"
