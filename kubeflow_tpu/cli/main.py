# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""``kft`` — the platform CLI (replacement for the ksonnet ``ks`` workflow).

Subcommands mirror the reference's documented user workflow
(``README.md:69-93``, ``user_guide.md:19-77``): init / prototype list /
generate / param set / show / apply / delete. Implemented in
``kubeflow_tpu.cli.app``; this module is the console-script entrypoint.
"""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    from kubeflow_tpu.cli.app import run

    return run(sys.argv[1:] if argv is None else argv)


if __name__ == "__main__":
    raise SystemExit(main())
