"""``kft`` — the platform CLI (replacement for the ksonnet ``ks`` workflow).

Subcommands mirror the reference's documented user workflow
(``README.md:69-93``, ``user_guide.md:19-77``): init / prototype list /
generate / param set / show / apply / delete. Implemented in
``kubeflow_tpu.cli.app``; this module is the console-script entrypoint.
"""

from __future__ import annotations

import sys


def main(argv=None) -> int:
    from kubeflow_tpu.cli.app import run

    return run(sys.argv[1:] if argv is None else argv)


if __name__ == "__main__":
    raise SystemExit(main())
