# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""kft CLI implementation.

Workflow parity with the reference's documented ks flow
(``README.md:69-93``): an *app directory* holds per-component params
and per-environment overlays; ``generate`` instantiates a prototype
into the app, ``param set`` edits overlays, ``show`` renders YAML,
``apply``/``delete`` talk to the cluster (via kubectl when present;
``--dry-run`` otherwise). Unlike ksonnet there is no vendored jsonnet —
prototypes are code in ``kubeflow_tpu.manifests``.
"""

from __future__ import annotations

import argparse
import json
import shutil
import subprocess
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional

import yaml

from kubeflow_tpu.params.registry import get_prototype, list_prototypes

APP_FILE = "kft.json"


def _load_app(app_dir: Path) -> Dict[str, Any]:
    path = app_dir / APP_FILE
    if not path.exists():
        raise SystemExit(
            f"error: {path} not found — run `kft init {app_dir}` first"
        )
    return json.loads(path.read_text())


def _save_app(app_dir: Path, app: Dict[str, Any]) -> None:
    (app_dir / APP_FILE).write_text(json.dumps(app, indent=2, sort_keys=True) + "\n")


def _parse_kv(pairs: List[str]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for pair in pairs:
        if "=" not in pair:
            raise SystemExit(f"error: expected key=value, got {pair!r}")
        k, _, v = pair.partition("=")
        out[k] = v
    return out


def _component_objects(app: Dict[str, Any], name: str,
                       env: Optional[str]) -> List[dict]:
    try:
        comp = app["components"][name]
    except KeyError:
        raise SystemExit(
            f"error: component {name!r} not generated; "
            f"have {sorted(app.get('components', {}))}"
        )
    proto = get_prototype(comp["prototype"])
    overrides = dict(comp.get("params", {}))
    if env:
        overrides.update(app.get("environments", {}).get(env, {})
                         .get("components", {}).get(name, {}))
    return proto.build(overrides)


def cmd_init(args: argparse.Namespace) -> int:
    app_dir = Path(args.dir)
    app_dir.mkdir(parents=True, exist_ok=True)
    if (app_dir / APP_FILE).exists() and not args.force:
        raise SystemExit(f"error: {app_dir / APP_FILE} already exists")
    _save_app(app_dir, {"apiVersion": "kft/v1", "components": {},
                        "environments": {"default": {"components": {}}}})
    print(f"initialized kft app at {app_dir}")
    return 0


def cmd_prototype_list(args: argparse.Namespace) -> int:
    for proto in list_prototypes():
        print(f"{proto.package}/{proto.name:32s} {proto.description}")
    return 0


def cmd_prototype_describe(args: argparse.Namespace) -> int:
    proto = get_prototype(args.prototype)
    print(f"{proto.name} ({proto.package}): {proto.description}")
    for p in proto.params:
        req = "required" if p.required else f"default={p.default!r}"
        print(f"  --{p.name:24s} [{p.kind}] {req}  {p.doc}")
    return 0


def cmd_generate(args: argparse.Namespace) -> int:
    app_dir = Path(args.app_dir)
    app = _load_app(app_dir)
    proto = get_prototype(args.prototype)
    name = args.name or proto.name
    params = _parse_kv(args.param or [])
    # Validate early: unknown params AND bad coercions fail at generate
    # time (missing required params stay lazy until show/apply, like ks).
    specs = proto.param_set().overlay(params).specs
    for key, value in params.items():
        specs[key].coerce(value)
    # ksonnet passed the component name as the prototype's `name` param
    # implicitly (`ks generate tf-job myjob` ⇒ name=myjob); same here.
    if "name" in specs and "name" not in params:
        params["name"] = name
    app.setdefault("components", {})[name] = {
        "prototype": proto.name,
        "params": params,
    }
    _save_app(app_dir, app)
    print(f"generated component {name!r} from prototype {proto.name!r}")
    return 0


def cmd_param_set(args: argparse.Namespace) -> int:
    app_dir = Path(args.app_dir)
    app = _load_app(app_dir)
    comp = app.get("components", {}).get(args.component)
    if comp is None:
        raise SystemExit(f"error: unknown component {args.component!r}")
    if args.env:
        target = (
            app.setdefault("environments", {})
            .setdefault(args.env, {})
            .setdefault("components", {})
            .setdefault(args.component, {})
        )
    else:
        target = comp.setdefault("params", {})
    target[args.name] = args.value
    # Validate the merged overlay still resolves/coerces.
    _component_objects(app, args.component, args.env)
    _save_app(app_dir, app)
    print(f"set {args.component}.{args.name}={args.value}"
          + (f" (env {args.env})" if args.env else ""))
    return 0


def cmd_show(args: argparse.Namespace) -> int:
    app = _load_app(Path(args.app_dir))
    names = args.component or sorted(app.get("components", {}))
    docs: List[dict] = []
    for name in names:
        docs.extend(_component_objects(app, name, args.env))
    sys.stdout.write(yaml.safe_dump_all(docs, sort_keys=False))
    return 0


def _kubectl(objects: List[dict], verb: str, dry_run: bool) -> int:
    manifest = yaml.safe_dump_all(objects, sort_keys=False)
    if dry_run or shutil.which("kubectl") is None:
        if not dry_run:
            print("kubectl not found; printing manifests (use --dry-run to "
                  "silence this note)", file=sys.stderr)
        sys.stdout.write(manifest)
        return 0
    cmd = ["kubectl", verb, "-f", "-"]
    if verb == "apply":
        cmd.insert(2, "--server-side")
    proc = subprocess.run(cmd, input=manifest.encode())
    return proc.returncode


def cmd_apply(args: argparse.Namespace) -> int:
    app = _load_app(Path(args.app_dir))
    names = args.component or sorted(app.get("components", {}))
    objs: List[dict] = []
    for name in names:
        objs.extend(_component_objects(app, name, args.env))
    return _kubectl(objs, "apply", args.dry_run)


def cmd_delete(args: argparse.Namespace) -> int:
    app = _load_app(Path(args.app_dir))
    names = args.component or sorted(app.get("components", {}))
    objs: List[dict] = []
    for name in names:
        objs.extend(_component_objects(app, name, args.env))
    return _kubectl(objs, "delete", args.dry_run)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="kft", description="TPU-native Kubeflow platform CLI"
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("init", help="initialize an app directory")
    p.add_argument("dir")
    p.add_argument("--force", action="store_true")
    p.set_defaults(fn=cmd_init)

    p = sub.add_parser("prototype", help="list or describe prototypes")
    psub = p.add_subparsers(dest="subcommand", required=True)
    pl = psub.add_parser("list")
    pl.set_defaults(fn=cmd_prototype_list)
    pd = psub.add_parser("describe")
    pd.add_argument("prototype")
    pd.set_defaults(fn=cmd_prototype_describe)

    p = sub.add_parser("generate", help="instantiate a prototype as a component")
    p.add_argument("prototype")
    p.add_argument("name", nargs="?")
    p.add_argument("--app-dir", default=".")
    p.add_argument("--param", action="append", metavar="KEY=VALUE")
    p.set_defaults(fn=cmd_generate)

    p = sub.add_parser("param", help="set component params")
    psub = p.add_subparsers(dest="subcommand", required=True)
    ps = psub.add_parser("set")
    ps.add_argument("component")
    ps.add_argument("name")
    ps.add_argument("value")
    ps.add_argument("--app-dir", default=".")
    ps.add_argument("--env")
    ps.set_defaults(fn=cmd_param_set)

    for verb, fn in (("show", cmd_show), ("apply", cmd_apply),
                     ("delete", cmd_delete)):
        p = sub.add_parser(verb)
        p.add_argument("component", nargs="*")
        p.add_argument("--app-dir", default=".")
        p.add_argument("--env")
        if verb != "show":
            p.add_argument("--dry-run", action="store_true")
        p.set_defaults(fn=fn)

    return parser


def run(argv: List[str]) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except (KeyError, ValueError) as e:
        # Param/prototype errors are user errors, not crashes: print
        # the message (KeyError reprs its arg, so unwrap) and exit 1.
        msg = e.args[0] if e.args else str(e)
        print(f"error: {msg}", file=sys.stderr)
        return 1
