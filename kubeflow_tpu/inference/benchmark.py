# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Autoregressive decode benchmark (tokens/sec, per-token latency).

The serving-side counterpart of the LoRA fine-tune bench: proves the
KV-cache decode loop (inference/generate.py) at production scale —
Llama-2-7B in bf16 fits one 16 GB chip with its cache. Decode is
HBM-bound (every step streams the full weight set), so the ceiling is
``hbm_bytes_per_step / hbm_bandwidth``, not MXU FLOPs; the bench
reports achieved bandwidth against that model.

The reference has no generation surface at all (classify-style
serving only); this is beyond-parity, measured with the same fencing
discipline as training/benchmark.py (host value pull, single-dispatch
scan decode so the tunnel round-trip amortizes).
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp

from kubeflow_tpu.models.registry import get_model


@dataclasses.dataclass
class DecodeBenchConfig:
    model: str = "llama2-7b"
    batch_size: int = 1
    prompt_len: int = 128
    max_new_tokens: int = 128
    temperature: float = 0.0
    seed: int = 0


def _init_bench_model(config: DecodeBenchConfig):
    """(model, params, param_bytes): one bf16 in-jit init shared by
    the single run and the batch sweep (a 7B init is the expensive
    part — the sweep must not repeat it per batch size)."""
    entry = get_model(config.model)
    cache = config.prompt_len + config.max_new_tokens
    model = entry.make(cache_size=cache)
    rng = jax.random.PRNGKey(config.seed)

    # Init in bf16 *inside* the jit (flax param default is f32 — 2×
    # the bytes; the cast inside one jit frees each f32 temp as it is
    # produced, so a 7B model never peaks at 27 GB).
    plain = entry.make()

    def init_params(r):
        import flax.linen as nn

        from kubeflow_tpu.utils.trees import cast_floating

        variables = plain.init(r, jnp.zeros((1, 1), jnp.int32))
        return cast_floating(nn.meta.unbox(variables["params"]),
                             jnp.bfloat16)

    params = jax.jit(init_params)(rng)
    jax.block_until_ready(params)
    param_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
    return model, params, param_bytes


def _measure_decode(config: DecodeBenchConfig, model, params,
                    param_bytes: int, batch_size: int) -> Dict[str, Any]:
    """The timed section at one batch size (prefill-differenced)."""
    from kubeflow_tpu.inference.generate import generate

    entry = get_model(config.model)
    vocab = entry.num_classes_or_vocab
    rng = jax.random.PRNGKey(config.seed)
    prompt = jax.random.randint(
        rng, (batch_size, config.prompt_len), 0, vocab)

    def run(n: int):
        tokens, _ = generate(
            model, params, prompt, max_new_tokens=n,
            temperature=config.temperature, rng=rng)
        return int(tokens[0, -1])  # host pull = fence

    n = config.max_new_tokens
    t0 = time.perf_counter()
    run(n)  # compile + warmup (full)
    run(1)  # compile + warmup (prefill-dominated probe)
    compile_s = time.perf_counter() - t0

    # Separate prefill from decode: t(prefill + 1 token) vs
    # t(prefill + n tokens) — the difference is (n-1) pure decode
    # steps. Timing the full call alone would fold the whole prompt
    # forward pass into "per-token decode latency".
    t0 = time.perf_counter()
    run(1)
    prefill_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    run(n)
    full_s = time.perf_counter() - t0

    decode_s = max(full_s - prefill_s, 1e-9)
    # Per STEP (one step advances every row); tokens/s is aggregate
    # across the batch — the serving-throughput number.
    per_token_ms = decode_s / (n - 1) * 1e3 if n > 1 else float("nan")
    return {
        "model": config.model,
        "batch_size": batch_size,
        "prompt_len": config.prompt_len,
        "max_new_tokens": n,
        "decode_tokens_per_sec":
            batch_size * (n - 1) / decode_s if n > 1 else 0.0,
        "per_token_ms": per_token_ms,
        "prefill_ms": prefill_s * 1e3,
        "end_to_end_s": full_s,
        "param_bytes": param_bytes,
        # Decode streams every weight once per STEP (shared by all
        # batch rows — the whole reason batching is near-free):
        # achieved HBM GB/s.
        "weight_stream_gb_per_sec":
            param_bytes / (per_token_ms / 1e3) / 1e9 if n > 1 else 0.0,
        "compile_plus_warmup_s": compile_s,
    }


def run_decode_benchmark(config: DecodeBenchConfig) -> Dict[str, Any]:
    """Returns decode tokens/sec + per-token ms + weight-streaming GB/s."""
    model, params, param_bytes = _init_bench_model(config)
    return _measure_decode(config, model, params, param_bytes,
                           config.batch_size)


def run_decode_batch_sweep(
    config: DecodeBenchConfig,
    batch_sizes: Sequence[int] = (1, 4, 8),
) -> Dict[str, Any]:
    """Decode throughput vs batch size, one shared model/params init.

    Decode at B=1 is HBM-bound — each step streams the full weight
    set to produce ONE token — so rows added to the step are near-free
    until the per-step matvecs turn into compute-bound matmuls or the
    KV-cache traffic (batch-proportional) catches up. This measures
    where that holds: expect aggregate tokens/s ≈ B × the B=1 row in
    the HBM-bound regime (the serving batcher's coalescing premise).
    """
    model, params, param_bytes = _init_bench_model(config)
    rows = [
        _measure_decode(config, model, params, param_bytes, b)
        for b in batch_sizes
    ]
    base = next((r for r in rows if r["batch_size"] == 1), rows[0])
    base_tps = max(base["decode_tokens_per_sec"], 1e-9)
    return {
        "model": config.model,
        "prompt_len": config.prompt_len,
        "max_new_tokens": config.max_new_tokens,
        "param_bytes": param_bytes,
        "rows": rows,
        "speedup_vs_b1": {
            str(r["batch_size"]):
                round(r["decode_tokens_per_sec"] / base_tps, 3)
            for r in rows
        },
    }


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog="decode-bench")
    parser.add_argument("--model", default="llama2-7b")
    parser.add_argument("--batch_size", type=int, default=1)
    parser.add_argument("--prompt_len", type=int, default=128)
    parser.add_argument("--max_new_tokens", type=int, default=128)
    parser.add_argument("--sweep_batch_sizes", default="",
                        help="comma-separated batch sizes (e.g. 1,4,8):"
                             " run the decode batch sweep instead of a "
                             "single measurement")
    args = parser.parse_args(argv)
    config = DecodeBenchConfig(
        model=args.model, batch_size=args.batch_size,
        prompt_len=args.prompt_len,
        max_new_tokens=args.max_new_tokens)
    if args.sweep_batch_sizes:
        sizes = tuple(int(s) for s in args.sweep_batch_sizes.split(",")
                      if s.strip())
        print(json.dumps(run_decode_batch_sweep(config, sizes)))
        return 0
    print(json.dumps(run_decode_benchmark(config)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
