from kubeflow_tpu.inference.generate import generate  # noqa: F401
