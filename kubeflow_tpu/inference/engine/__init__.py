# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Continuous-batching decode engine (slot-based, paged KV cache).

The execution plane behind streaming ``:generate`` serving: a
persistent decode loop over N slots where finished rows retire and
queued requests admit *between* K-token slices (prefill into a free
slot — no full-batch recompile), with the KV cache page-managed
(:mod:`paged_kv`) instead of rebuilt per batch, and tokens streamed
back incrementally as they are sampled.

With ``EngineConfig.prefix_cache`` (ISSUE 11), a content-addressed
radix index over the page pool (:mod:`prefix_cache`) shares common
prompt prefixes copy-on-write across requests: admission matches the
longest cached prefix, ref-counts the shared pages, and prefills only
the tail — bitwise equal to cold prefill.
"""

from kubeflow_tpu.inference.engine.engine import (  # noqa: F401
    DecodeEngine,
    EngineConfig,
    GenerateStream,
    TokenEvent,
)
from kubeflow_tpu.inference.engine.paged_kv import (  # noqa: F401
    PageAllocator,
    PagedKVCache,
)
from kubeflow_tpu.inference.engine.prefix_cache import (  # noqa: F401
    PrefixCache,
    PrefixMatch,
)
from kubeflow_tpu.inference.engine.slots import (  # noqa: F401
    Slot,
    SlotScheduler,
)
