# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Cross-request prefix KV cache: a content-addressed radix index
over the paged pool (ISSUE 11).

At fleet scale most prompts share a long common prefix (system
prompt, few-shot header, chat history), yet every admission
re-prefills it from scratch — the dominant TTFT cost (PERF r11's
prefill/decode split). This module is the host-side half of the fix:
an index mapping **hashed token blocks** to **resident pool pages**,
so admission can match the longest cached prefix, share those pages
read-only (ref-counted by :class:`~.paged_kv.PageAllocator`), and
prefill only the tail.

Design:

- **Chain-hashed blocks.** A prompt is split into page-sized token
  blocks; block ``j``'s key is ``H(key_{j-1} ‖ tokens[j·P,(j+1)·P))``
  — the chain makes the flat dict a radix tree (a block key encodes
  its whole prefix), and the stored tokens are compared on match so a
  hash collision degrades to a miss, never to wrong K/V. This is
  sound because K/V at position ``i`` is a pure function of tokens
  ``[0, i]`` — exactly what the chain key addresses.
- **One partial boundary child per node.** Prompts rarely end on a
  page boundary; the final partial block is indexed too (longest
  fill wins), and a match into it triggers a **copy-on-write fork**
  at admission: the matched head rows are copied into a private page
  (via the tail-prefill cache) because the new request's tail prefill
  and decode will write past them. Full-block pages are never
  written by sharers (decode writes land at positions strictly past
  the matched prefix), so full blocks share zero-copy.
- **LRU eviction of zero-ref pages only.** A page referenced by any
  live slot is pinned; when its last slot retires it moves to
  *retained* custody (resident, evictable, counted as allocator
  headroom). ``reclaim`` pops least-recently-used idle pages when
  ``alloc`` outruns the free list. Pinning an idle page consumes
  availability, so the allocator refuses pins that would starve an
  outstanding reservation — the FIFO admission queue can always make
  progress against cached pages (no-deadlock rule, fuzz-tested).

Engine-thread only (same single-mutator discipline as the allocator
and slot scheduler); readers of the counters see GIL-consistent ints.
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["PrefixCache", "PrefixMatch"]

_ROOT = b"prefix-root"


def _block_key(parent: bytes, tokens: Sequence[int]) -> bytes:
    h = hashlib.blake2b(parent, digest_size=16)
    h.update(np.asarray(tokens, np.int32).tobytes())
    return h.digest()


@dataclasses.dataclass
class _Entry:
    key: bytes  # chain key (full blocks) / parent chain key (partial)
    tokens: Tuple[int, ...]  # block content (== page_size iff full)
    page: int
    full: bool


@dataclasses.dataclass
class PrefixMatch:
    """Longest cached prefix for one prompt: ``entries`` are the
    matched FULL blocks in chain order; ``fork`` is the partially
    matched boundary entry (``fork_len`` of its tokens are common) —
    its page is read once for the CoW copy, never placed in the
    sharer's table. ``matched`` counts prefix tokens covered.

    ``host_entries`` (ISSUE 20) are host-tier blocks continuing the
    chain past the last HBM-resident block: their K/V is spliced into
    the gathered prefill cache and re-adopted into PRIVATE pages, so
    they appear after ``entries`` in chain order but never in
    ``shared_pages`` (they hold no allocator custody and need no
    pin — the match's Python reference keeps the arrays alive)."""

    entries: List[_Entry]
    fork: Optional[_Entry]
    fork_len: int
    matched: int
    host_entries: List[Any] = dataclasses.field(default_factory=list)

    @property
    def shared_pages(self) -> List[int]:
        return [e.page for e in self.entries]


class PrefixCache:
    """The index + LRU; implements the allocator's retained-page
    protocol (``holds`` / ``on_idle`` / ``on_pinned`` / ``reclaim``).
    """

    def __init__(self, page_size: int, allocator):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = page_size
        self.allocator = allocator
        self._full: Dict[bytes, _Entry] = {}
        self._partial: Dict[bytes, _Entry] = {}  # parent key -> entry
        self._by_page: Dict[int, _Entry] = {}
        # Zero-ref resident pages, least-recently-used first. Order is
        # maintained by the pin/idle transitions: matching pins a page
        # out of here; retiring re-inserts it at the MRU end.
        self._idle: "OrderedDict[int, None]" = OrderedDict()
        # Monotonic counters (stats()/metrics).
        self.hits = 0
        self.misses = 0
        self.evicted_pages = 0
        self.saved_tokens_total = 0
        # Tiered KV memory (ISSUE 20): the host-RAM tier behind this
        # index, and the spill hook ``reclaim`` calls for each FULL
        # entry BEFORE its page returns to the free list (the page's
        # K/V is still valid there — the snapshot races nothing).
        # Both stay None on a single-tier engine, which keeps every
        # r15 path bitwise untouched.
        self.host = None
        self._spill = None
        allocator.set_cache(self)

    def set_host_tier(self, tier) -> None:
        """Attach the host-RAM tier ``match`` continues into."""
        self.host = tier

    def set_spill(self, fn) -> None:
        """Install the evict-to-host hook (``fn(entry)``); the callee
        must never raise — a failed spill degrades to the r15 drop."""
        self._spill = fn

    # -- queries ---------------------------------------------------------

    def resident_pages(self) -> int:
        return len(self._by_page)

    def idle_pages(self) -> List[int]:
        return list(self._idle)

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self) -> dict:
        return {
            "enabled": True,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hit_rate(), 4),
            "cached_pages": len(self._by_page),
            "cached_idle_pages": len(self._idle),
            "evicted_pages": self.evicted_pages,
            "saved_prefill_tokens": self.saved_tokens_total,
        }

    # -- matching (engine thread) ----------------------------------------

    def match(self, prompt: Sequence[int]) -> PrefixMatch:
        """Longest cached prefix of ``prompt``: whole blocks down the
        chain, then at most one partial boundary block. Capped at
        ``len(prompt) - 1``: at least one prompt token must prefill
        so the admission has next-token logits to sample from."""
        tokens = [int(t) for t in prompt]
        limit = len(tokens) - 1
        p = self.page_size
        entries: List[_Entry] = []
        parent = _ROOT
        covered = 0
        while covered + p <= limit:
            block = tuple(tokens[covered:covered + p])
            entry = self._full.get(_block_key(parent, block))
            if entry is None or entry.tokens != block:
                break
            entries.append(entry)
            parent = entry.key
            covered += p
        # Tier continuation (ISSUE 20): where the HBM chain ends, the
        # host tier may carry the next blocks (they were evicted
        # here, or fleet-fetched in). Once the walk goes host it
        # STAYS host — shared pages must be a contiguous table
        # prefix, so a deeper HBM block past a host block cannot be
        # shared in place (rare by construction: children idle, and
        # therefore evict, before their parents).
        host_entries: List[Any] = []
        if self.host is not None:
            while covered + p <= limit:
                block = tuple(tokens[covered:covered + p])
                hb = self.host.get(_block_key(parent, block), block)
                if hb is None:
                    break
                host_entries.append(hb)
                parent = hb.key
                covered += p
        fork = None
        fork_len = 0
        partial = self._partial.get(parent)
        if partial is not None:
            tail = tokens[covered:limit]
            common = 0
            for a, b in zip(partial.tokens, tail):
                if a != b:
                    break
                common += 1
            if common > 0:
                fork, fork_len = partial, common
        return PrefixMatch(entries=entries, fork=fork,
                           fork_len=fork_len,
                           matched=covered + fork_len,
                           host_entries=host_entries)

    def chain_blocks(self, prompt: Sequence[int]):
        """Full-block chain walk WITHOUT :meth:`match`'s ``len-1``
        cap — the fleet export side (serving/kv_store.py) wants every
        resident full block of ``prompt``, including one ending
        exactly at the prompt's end. Yields ``(block_tokens, entry,
        is_hbm)`` in chain order, stopping at the first gap; entries
        are HBM ``_Entry`` (``is_hbm=True``) until the chain crosses
        into the host tier, then host blocks (same stickiness rule
        as ``match``). Engine thread only (reads the live index)."""
        tokens = [int(t) for t in prompt]
        p = self.page_size
        parent = _ROOT
        covered = 0
        in_host = False
        while covered + p <= len(tokens):
            block = tuple(tokens[covered:covered + p])
            key = _block_key(parent, block)
            entry = None if in_host else self._full.get(key)
            if entry is not None and entry.tokens == block:
                yield block, entry, True
            else:
                hb = (self.host.get(key, block)
                      if self.host is not None else None)
                if hb is None:
                    return
                in_host = True
                yield block, hb, False
            parent = key
            covered += p

    def pin(self, match: PrefixMatch) -> PrefixMatch:
        """Take a slot reference on every matched page, shallowest
        first. A pin the allocator refuses (reservation starvation
        guard) TRUNCATES the match there — the caller admits with the
        shorter prefix instead of waiting on pages it may never get.
        Returns the effectively pinned match."""
        pinned: List[_Entry] = []
        for e in match.entries:
            if not self.allocator.ref(e.page):
                # The chain is broken at an unpinnable HBM block —
                # host blocks hanging past it are unreachable too.
                return PrefixMatch(entries=pinned, fork=None,
                                   fork_len=0,
                                   matched=len(pinned) * self.page_size)
            pinned.append(e)
        if match.fork is not None and \
                not self.allocator.ref(match.fork.page):
            # Host blocks need no pin (no allocator custody): a
            # refused FORK pin only sheds the fork, never the host
            # chain already matched under it.
            covered = (len(pinned) + len(match.host_entries)) * \
                self.page_size
            return PrefixMatch(entries=pinned, fork=None, fork_len=0,
                               matched=covered,
                               host_entries=list(match.host_entries))
        return match

    def unpin(self, match: PrefixMatch,
              include_fork: bool = True) -> None:
        """Drop the references :meth:`pin` took (admission failed, or
        the fork donor's copy is done)."""
        for e in match.entries:
            self.allocator.unref(e.page)
        if include_fork and match.fork is not None:
            self.allocator.unref(match.fork.page)

    def unpin_fork(self, match: PrefixMatch) -> None:
        if match.fork is not None:
            self.allocator.unref(match.fork.page)

    # -- registration (engine thread) ------------------------------------

    def register(self, prompt: Sequence[int],
                 pages: Sequence[int]) -> int:
        """Index an admitted prompt's resident pages: ``pages[j]``
        backs token block ``j``. Blocks already present just stay
        (their existing page serves future matches); new full blocks
        insert; a partial boundary block replaces the node's existing
        partial only when it fills strictly more tokens (longest
        wins). Returns the number of NEW pages indexed."""
        tokens = [int(t) for t in prompt]
        p = self.page_size
        n_full = len(tokens) // p
        parent = _ROOT
        added = 0
        for j in range(n_full):
            block = tuple(tokens[j * p:(j + 1) * p])
            key = _block_key(parent, block)
            entry = self._full.get(key)
            if entry is None and int(pages[j]) not in self._by_page:
                entry = _Entry(key=key, tokens=block,
                               page=int(pages[j]), full=True)
                self._full[key] = entry
                self._by_page[entry.page] = entry
                added += 1
            elif entry is None:
                # The page already backs another entry (it was matched
                # shared); a chain that diverges earlier cannot reuse
                # it — stop indexing this prompt here.
                return added
            parent = key
        rest = tuple(tokens[n_full * p:])
        if rest and n_full < len(pages):
            page = int(pages[n_full])
            existing = self._partial.get(parent)
            if existing is not None and \
                    len(existing.tokens) >= len(rest):
                return added  # keep the longer (or equal) fill
            if page in self._by_page:
                return added  # page is a shared full block elsewhere
            if existing is not None:
                self._drop_entry(existing, free_idle=True)
            entry = _Entry(key=parent, tokens=rest, page=page,
                           full=False)
            self._partial[parent] = entry
            self._by_page[page] = entry
            added += 1
        return added

    # -- allocator protocol ----------------------------------------------

    def holds(self, page: int) -> bool:
        return int(page) in self._by_page

    def on_idle(self, page: int) -> None:
        self._idle[int(page)] = None
        self._idle.move_to_end(int(page))

    def on_pinned(self, page: int) -> None:
        self._idle.pop(int(page), None)

    def reclaimable(self) -> int:
        return len(self._idle)

    def reclaim(self, n: int) -> List[int]:
        """Evict up to ``n`` least-recently-used idle pages: drop
        their index entries and hand the page ids back to the
        allocator (which moves them retained → free). With a host
        tier attached (ISSUE 20) a FULL entry's K/V is spilled to
        host buffers FIRST — the page is still resident here, so the
        snapshot reads exactly the bytes a sharer would have; the
        drop then proceeds as before (evict-to-host, not drop)."""
        out: List[int] = []
        while len(out) < n and self._idle:
            page, _ = self._idle.popitem(last=False)
            entry = self._by_page.get(page)
            if entry is not None:
                if self._spill is not None and entry.full:
                    self._spill(entry)
                self._drop_entry(entry, free_idle=False)
            out.append(page)
            self.evicted_pages += 1
        return out

    def _drop_entry(self, entry: _Entry, *, free_idle: bool) -> None:
        """Remove one entry from the index. Children chained under a
        dropped full block become unreachable for matching but stay
        in the LRU — they evict on their own (reverse-order retire
        idling makes children older than parents, so in practice
        children leave first)."""
        if entry.full:
            if self._full.get(entry.key) is entry:
                del self._full[entry.key]
        elif self._partial.get(entry.key) is entry:
            del self._partial[entry.key]
        self._by_page.pop(entry.page, None)
        if free_idle and entry.page in self._idle:
            self._idle.pop(entry.page, None)
            self.allocator.discard_retained(entry.page)

    # -- lifecycle -------------------------------------------------------

    def clear(self) -> int:
        """Drop every index entry; idle pages return to the free list
        immediately, pinned ones when their last slot retires (the
        allocator's ``holds`` check then finds nothing). Returns the
        number of pages released to the free list. Engine-thread /
        quiesced callers only (warmup teardown, tests, stop())."""
        released = 0
        for entry in list(self._by_page.values()):
            idle = entry.page in self._idle
            self._drop_entry(entry, free_idle=True)
            released += int(idle)
        self._idle.clear()
        return released

    def check_invariants(self) -> None:
        """Index-side half of the fuzz harness's per-step check."""
        for key, e in self._full.items():
            assert e.full and e.key == key and \
                len(e.tokens) == self.page_size, f"bad full entry {e}"
            assert self._by_page.get(e.page) is e, \
                f"full entry page {e.page} not in by_page"
        for key, e in self._partial.items():
            assert not e.full and e.key == key and \
                0 < len(e.tokens) < self.page_size, \
                f"bad partial entry {e}"
            assert self._by_page.get(e.page) is e, \
                f"partial entry page {e.page} not in by_page"
        assert len(self._by_page) == \
            len(self._full) + len(self._partial), \
            "by_page count drifted from entry maps"
        for page in self._idle:
            assert page in self._by_page, \
                f"idle page {page} has no index entry"
            assert self.allocator.refcount(page) == 0, \
                f"idle page {page} has live refs"
