# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Paged KV cache: a shared page pool + per-slot page tables.

The r6 batched decode rebuilt one left-padded cache per coalesced
batch and every row held its full-width slice until the LONGEST row
finished. Here the cache is persistent and page-granular:

- **Physical storage** per layer is ``[num_pages, page_size, kv_heads,
  head_dim]`` — one shared pool, page 0 reserved as the *null page*
  (unallocated page-table entries point at it; its contents are only
  ever read through masked attention positions and written by retired
  or overshooting slots, so it just has to stay finite).
- **Page tables** map each slot's logical time axis onto pool pages
  (``tables[slot, j]`` backs logical positions ``[j·P, (j+1)·P)``).
  The decode slice gathers a slot-batch logical view ``[N, C', ...]``
  (``C' = pages_per_slot × page_size``), runs the model on it, and
  scatters only the newly written token range back — so a slice costs
  one gather + one scatter, not a per-step rebuild.
- **Allocation** is reservation-based (:class:`PageAllocator`): a
  request reserves its worst case ``ceil((prompt_bucket +
  max_new_tokens)/P)`` pages at admission (no mid-decode OOM, no
  preemption machinery), allocates lazily as its sequence crosses page
  boundaries, and frees everything at retire — an early-EOS row hands
  its unused reservation straight back to the admission gate.

All shapes stay static (TPU rule): paging is index arithmetic, the
gather/scatter are ``jnp.take``-family ops, and the per-(bucket,
page-count) helper jits compile once each.
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class PageAllocator:
    """Host-side free list + reservation accounting for the pool.

    Only the engine thread mutates it; readers (metrics callbacks,
    admission estimates) see GIL-consistent ints. Page 0 is the null
    page and is never handed out.
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(f"need >= 2 pages (1 null + 1 usable), "
                             f"got {num_pages}")
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._reserved = 0

    @property
    def free_pages(self) -> int:
        """Pages physically free (some may be spoken for)."""
        return len(self._free)

    @property
    def reserved_pages(self) -> int:
        return self._reserved

    def available(self) -> int:
        """Pages neither allocated nor reserved — the admission gate's
        number."""
        return len(self._free) - self._reserved

    def reserve(self, n: int) -> bool:
        """Promise ``n`` pages to a slot (allocated later, lazily).
        False = pool can't cover it; the caller must not admit."""
        if self.available() < n:
            return False
        self._reserved += n
        return True

    def unreserve(self, n: int) -> None:
        if n > self._reserved:
            raise ValueError(
                f"unreserve({n}) exceeds outstanding reservation "
                f"{self._reserved}")
        self._reserved -= n

    def alloc(self, n: int) -> List[int]:
        """Convert ``n`` pages of reservation into concrete page ids.
        The reservation invariant makes this infallible for reserved
        callers; misuse raises rather than corrupting the pool."""
        if n > self._reserved:
            raise ValueError(
                f"alloc({n}) without reservation (reserved="
                f"{self._reserved})")
        if n > len(self._free):
            raise RuntimeError(
                f"pool corrupted: {n} pages reserved but only "
                f"{len(self._free)} free")
        self._reserved -= n
        return [self._free.pop() for _ in range(n)]

    def free(self, pages: Sequence[int]) -> None:
        for p in pages:
            if p == 0:
                raise ValueError("page 0 is the null page")
            self._free.append(int(p))


def _is_kv(leaf: jax.Array) -> bool:
    """KV leaves are [*, time, heads, dim]; the per-layer scalar
    ``index`` cache variables are 0-d."""
    return getattr(leaf, "ndim", 0) == 4


@jax.jit
def _gather_logical(physical: Any, tables: jax.Array) -> Any:
    """Page-table gather: physical pools → the slot-batch logical
    cache collection the model decodes over ([N, C', heads, dim] per
    layer; scalar index leaves ride along as zeros — the per-row
    decode path never reads them)."""
    n, mpp = tables.shape

    def g(leaf):
        if not _is_kv(leaf):
            return jnp.zeros_like(leaf)
        _, p, h, d = leaf.shape
        return leaf[tables.reshape(-1)].reshape(n, mpp * p, h, d)

    return jax.tree.map(g, physical)


@functools.partial(jax.jit, static_argnames=("num_steps",))
def _scatter_token_range(physical: Any, logical: Any,
                         tables: jax.Array, start_pos: jax.Array, *,
                         num_steps: int) -> Any:
    """Write the slice's freshly decoded token range ``[start_pos_i,
    start_pos_i + num_steps)`` of every slot back into the pool.
    Positions beyond a slot's allocated pages resolve to the null page
    (table entries are 0 there), so retired/overshooting rows scribble
    harmlessly instead of needing per-row masks."""
    pos = start_pos[:, None] + jnp.arange(num_steps)[None, :]  # [N, K]

    def s(ph, lg):
        if not _is_kv(ph):
            return ph
        _, p, _, _ = ph.shape
        page_idx = jnp.take_along_axis(
            tables, jnp.clip(pos // p, 0, tables.shape[1] - 1), axis=1)
        offset = pos % p
        vals = jnp.take_along_axis(lg, pos[..., None, None], axis=1)
        return ph.at[page_idx, offset].set(vals)

    return jax.tree.map(s, physical, logical)


@functools.partial(jax.jit, static_argnames=("n_pages",))
def _adopt_prefill(physical: Any, prefill_cache: Any,
                   page_ids: jax.Array, *, n_pages: int) -> Any:
    """Copy a B=1 prefill cache's first ``n_pages`` pages worth of
    slots into the pool pages just allocated to the admitting slot."""

    def a(ph, pc):
        if not _is_kv(ph):
            return ph
        _, p, h, d = ph.shape
        need = n_pages * p
        row = pc[0]
        if row.shape[0] < need:  # cache_size not a page multiple
            row = jnp.pad(row, ((0, need - row.shape[0]),
                                (0, 0), (0, 0)))
        return ph.at[page_ids].set(row[:need].reshape(n_pages, p, h, d))

    return jax.tree.map(a, physical, prefill_cache)


class PagedKVCache:
    """The pool arrays + table bookkeeping for one decode engine.

    ``physical`` mirrors the model's cache-collection pytree with
    every KV leaf re-shaped to pages; gather/scatter/adopt are the
    jitted helpers above. Host-side ``tables`` is the source of truth
    (numpy); ``device_tables()`` snapshots it for a slice dispatch.
    """

    def __init__(self, cache_template: Any, *, num_slots: int,
                 page_size: int, cache_size: int,
                 num_pages: Optional[int] = None, mesh: Any = None):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = page_size
        self.cache_size = cache_size
        self.num_slots = num_slots
        self.mesh = mesh
        self.pages_per_slot = -(-cache_size // page_size)
        self.logical_len = self.pages_per_slot * page_size
        if num_pages is None:
            # Default: every slot can hold a full-length sequence,
            # plus the null page. Sizing it smaller is the memory
            # lever (admission then gates on reservations).
            num_pages = num_slots * self.pages_per_slot + 1
        self.allocator = PageAllocator(num_pages)
        kv_sharding = None
        if mesh is not None and mesh.shape.get("tensor", 1) > 1:
            # Serving-mesh placement (serving/sharding.py): the pool
            # shards along kv_heads on the SAME tensor axis the params
            # ride, so per-chip KV memory shrinks with the model and
            # the decode step's attention reads stay chip-local (no
            # cross-chip KV gather). Head counts not divisible by the
            # axis replicate — correctness first, memory second.
            from jax.sharding import NamedSharding, PartitionSpec as P

            kv_sharding = NamedSharding(mesh, P(None, None, "tensor"))

        def to_pages(leaf):
            if not _is_kv(leaf):
                return jnp.zeros(leaf.shape, leaf.dtype)
            _, _, h, d = leaf.shape
            pool = jnp.zeros((num_pages, page_size, h, d), leaf.dtype)
            if kv_sharding is not None and \
                    h % mesh.shape["tensor"] == 0:
                pool = jax.device_put(pool, kv_sharding)
            return pool

        self.physical = jax.tree.map(to_pages, cache_template)
        self.tables = np.zeros((num_slots, self.pages_per_slot),
                               np.int32)

    # -- accounting ------------------------------------------------------

    def pages_for(self, length: int) -> int:
        """Pages needed to back ``length`` cache slots."""
        return -(-length // self.page_size)

    def device_tables(self) -> jax.Array:
        return jnp.asarray(self.tables)

    # -- slot operations (engine thread only) ----------------------------

    def extend_slot(self, slot_index: int, allocated: int,
                    upto_position: int, budget_pages: int) -> int:
        """Allocate pages so slot ``slot_index`` can write through
        cache position ``upto_position`` (exclusive), never past its
        ``budget_pages`` reservation. Returns the new allocated count;
        page ids land in the host table (push with device_tables)."""
        need = min(self.pages_for(upto_position), budget_pages)
        if need <= allocated:
            return allocated
        new_pages = self.allocator.alloc(need - allocated)
        self.tables[slot_index, allocated:need] = new_pages
        return need

    def adopt(self, slot_index: int, prefill_cache: Any,
              prompt_width: int, budget_pages: int) -> int:
        """Admission: allocate the prompt's pages for ``slot_index``
        and copy the B=1 prefill cache into them. Returns the
        allocated page count."""
        n_pages = min(self.pages_for(prompt_width), budget_pages)
        pages = self.allocator.alloc(n_pages)
        self.tables[slot_index, :n_pages] = pages
        self.physical = _adopt_prefill(
            self.physical, prefill_cache,
            jnp.asarray(np.asarray(pages, np.int32)), n_pages=n_pages)
        return n_pages

    def release_slot(self, slot_index: int, allocated: int,
                     unreserved_remainder: int) -> None:
        """Retire: free the slot's pages, drop its remaining
        reservation, null its table row."""
        if allocated:
            self.allocator.free(
                self.tables[slot_index, :allocated].tolist())
        if unreserved_remainder:
            self.allocator.unreserve(unreserved_remainder)
        self.tables[slot_index, :] = 0

    def gather(self, tables: jax.Array) -> Any:
        return _gather_logical(self.physical, tables)

    def scatter(self, logical: Any, tables: jax.Array,
                start_pos: jax.Array, num_steps: int) -> None:
        self.physical = _scatter_token_range(
            self.physical, logical, tables, start_pos,
            num_steps=num_steps)
