# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Paged KV cache: a shared page pool + per-slot page tables.

The r6 batched decode rebuilt one left-padded cache per coalesced
batch and every row held its full-width slice until the LONGEST row
finished. Here the cache is persistent and page-granular:

- **Physical storage** per layer is ``[num_pages, page_size, kv_heads,
  head_dim]`` — one shared pool, page 0 reserved as the *null page*
  (unallocated page-table entries point at it; its contents are only
  ever read through masked attention positions and written by retired
  or overshooting slots, so it just has to stay finite).
- **Page tables** map each slot's logical time axis onto pool pages
  (``tables[slot, j]`` backs logical positions ``[j·P, (j+1)·P)``).
  The decode slice gathers a slot-batch logical view ``[N, C', ...]``
  (``C' = pages_per_slot × page_size``), runs the model on it, and
  scatters only the newly written token range back — so a slice costs
  one gather + one scatter, not a per-step rebuild.
- **Allocation** is reservation-based (:class:`PageAllocator`): a
  request reserves its worst case ``ceil((prompt_bucket +
  max_new_tokens)/P)`` pages at admission (no mid-decode OOM, no
  preemption machinery), allocates lazily as its sequence crosses page
  boundaries, and frees everything at retire — an early-EOS row hands
  its unused reservation straight back to the admission gate.

All shapes stay static (TPU rule): paging is index arithmetic, the
gather/scatter are ``jnp.take``-family ops, and the per-(bucket,
page-count) helper jits compile once each.
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class PageAllocator:
    """Host-side free list + reservation + ref-count accounting.

    Only the engine thread mutates it; readers (metrics callbacks,
    admission estimates) see GIL-consistent ints. Page 0 is the null
    page and is never handed out.

    Ref counts (prefix sharing, ISSUE 11): every allocated page
    carries a count of the slots using it. ``alloc`` hands pages out
    at ref 1; a slot adopting another request's resident prefix pages
    ``ref``\\ s them instead of allocating copies, and ``unref`` at
    retire is the ONLY decrementer. A page whose count reaches zero
    either returns to the free list or — when the attached prefix
    cache still indexes it — moves to *retained* custody: resident,
    evictable, counted as headroom by ``available()`` and reclaimed
    LRU-first when ``alloc`` outruns the free list. The FIFO
    no-deadlock rule lives in two guards here: ``reserve`` admits
    against free+retained (retained pages are always reclaimable, so
    a reservation can never wait on a page only a live slot can
    release), and ``ref`` refuses to pin a retained page when that
    would eat a page an outstanding reservation was promised.
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(f"need >= 2 pages (1 null + 1 usable), "
                             f"got {num_pages}")
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._reserved = 0
        self._refs: dict = {}  # page id -> live slot count (>= 1)
        self._retained: set = set()  # zero-ref pages the cache holds
        self._cache = None  # prefix cache (holds/on_idle/on_pinned/
        #                     reclaim/reclaimable protocol) or None

    def set_cache(self, cache) -> None:
        """Attach the prefix cache that may retain zero-ref pages."""
        self._cache = cache

    @property
    def free_pages(self) -> int:
        """Pages physically free (some may be spoken for)."""
        return len(self._free)

    @property
    def reserved_pages(self) -> int:
        return self._reserved

    @property
    def retained_pages(self) -> int:
        """Zero-ref pages kept resident by the prefix cache
        (evictable on demand — headroom, not pressure)."""
        return len(self._retained)

    @property
    def inuse_pages(self) -> int:
        """Pages referenced by at least one live slot."""
        return len(self._refs)

    def refcount(self, page: int) -> int:
        return self._refs.get(int(page), 0)

    def available(self) -> int:
        """Pages neither allocated nor reserved — the admission gate's
        number. Retained (zero-ref cached) pages count: they reclaim
        on demand inside ``alloc``."""
        return len(self._free) + len(self._retained) - self._reserved

    def reserve(self, n: int) -> bool:
        """Promise ``n`` pages to a slot (allocated later, lazily).
        False = pool can't cover it; the caller must not admit."""
        if self.available() < n:
            return False
        self._reserved += n
        return True

    def unreserve(self, n: int) -> None:
        if n > self._reserved:
            raise ValueError(
                f"unreserve({n}) exceeds outstanding reservation "
                f"{self._reserved}")
        self._reserved -= n

    def alloc(self, n: int) -> List[int]:
        """Convert ``n`` pages of reservation into concrete page ids
        (each at ref count 1), evicting LRU retained pages when the
        free list alone can't cover it. The reservation invariant
        makes this infallible for reserved callers; misuse raises
        rather than corrupting the pool."""
        if n > self._reserved:
            raise ValueError(
                f"alloc({n}) without reservation (reserved="
                f"{self._reserved})")
        if n > len(self._free) and self._cache is not None:
            for p in self._cache.reclaim(n - len(self._free)):
                self._retained.discard(int(p))
                self._free.append(int(p))
        if n > len(self._free):
            raise RuntimeError(
                f"pool corrupted: {n} pages reserved but only "
                f"{len(self._free)} free + {len(self._retained)} "
                f"retained")
        self._reserved -= n
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._refs[p] = 1
        return out

    def ref(self, page: int) -> bool:
        """Pin one more user onto a resident page. Pinning a RETAINED
        page consumes a unit of availability, so it fails (False) when
        outstanding reservations have already spoken for every
        reclaimable page — the caller must treat the page as a miss,
        never hold the admission line on it (FIFO no-deadlock rule)."""
        page = int(page)
        if page in self._refs:
            self._refs[page] += 1
            return True
        if page in self._retained:
            if self.available() < 1:
                return False
            self._retained.discard(page)
            self._refs[page] = 1
            if self._cache is not None:
                self._cache.on_pinned(page)
            return True
        raise ValueError(f"ref({page}): page is neither allocated "
                         f"nor retained")

    def unref(self, page: int) -> None:
        """Drop one user. At zero the page returns to the free list,
        or to retained custody when the prefix cache still indexes
        it."""
        page = int(page)
        count = self._refs.get(page)
        if count is None:
            raise ValueError(f"unref({page}): page has no refs")
        if count > 1:
            self._refs[page] = count - 1
            return
        del self._refs[page]
        if self._cache is not None and self._cache.holds(page):
            self._retained.add(page)
            self._cache.on_idle(page)
        else:
            self._free.append(page)

    def discard_retained(self, page: int) -> None:
        """The prefix cache dropped its entry for an idle page —
        return it to the free list."""
        page = int(page)
        if page not in self._retained:
            raise ValueError(f"discard_retained({page}): not retained")
        self._retained.discard(page)
        self._free.append(page)

    def free(self, pages: Sequence[int]) -> None:
        """Force-return pages to the free list regardless of count
        (legacy single-owner paths and tests; shared pages must go
        through ``unref``)."""
        for p in pages:
            if p == 0:
                raise ValueError("page 0 is the null page")
            self._refs.pop(int(p), None)
            self._free.append(int(p))

    def check_invariants(self) -> None:
        """Raise AssertionError on any accounting violation — the
        eviction-fuzz harness calls this after every step."""
        usable = self.num_pages - 1
        free = set(self._free)
        assert len(free) == len(self._free), \
            f"duplicate pages on the free list: {sorted(self._free)}"
        inuse = set(self._refs)
        assert not (free & inuse), f"free∩inuse: {free & inuse}"
        assert not (free & self._retained), \
            f"free∩retained: {free & self._retained}"
        assert not (inuse & self._retained), \
            f"inuse∩retained: {inuse & self._retained}"
        total = len(free) + len(inuse) + len(self._retained)
        assert total == usable, \
            f"page leak: {len(free)} free + {len(inuse)} inuse + " \
            f"{len(self._retained)} retained != {usable} usable"
        assert all(c >= 1 for c in self._refs.values()), \
            f"non-positive refcount: {self._refs}"
        assert 0 not in free | inuse | self._retained, \
            "null page escaped into circulation"
        assert self._reserved >= 0, f"negative reservation " \
            f"{self._reserved}"
        assert self._reserved <= len(free) + len(self._retained), \
            f"reservation {self._reserved} exceeds reclaimable " \
            f"{len(free)} free + {len(self._retained)} retained"
        if self._cache is not None:
            assert self._retained == set(self._cache.idle_pages()), \
                "allocator retained set drifted from the cache's " \
                "idle set"


def _is_kv(leaf: jax.Array) -> bool:
    """KV leaves are [*, time, heads, dim]; the per-layer scalar
    ``index`` cache variables are 0-d."""
    return getattr(leaf, "ndim", 0) == 4


@jax.jit
def _gather_logical(physical: Any, tables: jax.Array) -> Any:
    """Page-table gather: physical pools → the slot-batch logical
    cache collection the model decodes over ([N, C', heads, dim] per
    layer; scalar index leaves ride along as zeros — the per-row
    decode path never reads them)."""
    n, mpp = tables.shape

    def g(leaf):
        if not _is_kv(leaf):
            return jnp.zeros_like(leaf)
        _, p, h, d = leaf.shape
        return leaf[tables.reshape(-1)].reshape(n, mpp * p, h, d)

    return jax.tree.map(g, physical)


@functools.partial(jax.jit, static_argnames=("num_steps",))
def _scatter_token_range(physical: Any, logical: Any,
                         tables: jax.Array, start_pos: jax.Array, *,
                         num_steps: int) -> Any:
    """Write the slice's freshly decoded token range ``[start_pos_i,
    start_pos_i + num_steps)`` of every slot back into the pool.
    Positions beyond a slot's allocated pages resolve to the null page
    (table entries are 0 there), so retired/overshooting rows scribble
    harmlessly instead of needing per-row masks."""
    pos = start_pos[:, None] + jnp.arange(num_steps)[None, :]  # [N, K]

    def s(ph, lg):
        if not _is_kv(ph):
            return ph
        _, p, _, _ = ph.shape
        page_idx = jnp.take_along_axis(
            tables, jnp.clip(pos // p, 0, tables.shape[1] - 1), axis=1)
        offset = pos % p
        vals = jnp.take_along_axis(lg, pos[..., None, None], axis=1)
        return ph.at[page_idx, offset].set(vals)

    return jax.tree.map(s, physical, logical)


@functools.partial(jax.jit, static_argnames=("n_pages",))
def _adopt_prefill(physical: Any, prefill_cache: Any,
                   page_ids: jax.Array, first_page: jax.Array, *,
                   n_pages: int) -> Any:
    """Copy ``n_pages`` pages of a B=1 prefill cache, starting at
    logical page ``first_page`` (traced — no recompile per prefix
    length), into the pool pages just allocated to the admitting
    slot. ``first_page`` is 0 for a cold adoption; a prefix-cache hit
    skips the shared pages and adopts only the privately prefilled
    tail — including the copy-on-write fork of a partially-matched
    boundary page, whose shared head rows were gathered into the
    prefill cache before the tail prefill wrote past them."""

    def a(ph, pc):
        if not _is_kv(ph):
            return ph
        _, p, h, d = ph.shape
        need = n_pages * p
        row = pc[0]
        pad = need  # worst-case start overhang, clamped by the slice
        row = jnp.pad(row, ((0, pad), (0, 0), (0, 0)))
        seg = jax.lax.dynamic_slice(
            row, (first_page * p, 0, 0), (need, row.shape[1],
                                          row.shape[2]))
        return ph.at[page_ids].set(seg.reshape(n_pages, p, h, d))

    return jax.tree.map(a, physical, prefill_cache)


@jax.jit
def _gather_pages_to_cache(physical: Any, page_ids: jax.Array,
                           template: Any, fill_len: jax.Array) -> Any:
    """Materialize a slot-shaped page list as a contiguous B=1 cache:
    page ``j`` of ``page_ids`` lands at cache rows ``[j·P, (j+1)·P)``
    (null-page entries contribute zeros), and the scalar ``index``
    leaves are set to ``fill_len`` so the model's scalar append path
    continues the sequence at position ``fill_len`` — the
    continuation-prefill half of a prefix-cache hit. One compile:
    ``page_ids`` is always the full ``pages_per_slot`` row."""

    def g(ph, t):
        if not _is_kv(ph):
            return jnp.full(t.shape, fill_len, t.dtype)
        _, _, h, d = ph.shape
        rows = ph[page_ids].reshape(-1, h, d)
        return rows[: t.shape[1]][None, ...]

    return jax.tree.map(g, physical, template)


class PagedKVCache:
    """The pool arrays + table bookkeeping for one decode engine.

    ``physical`` mirrors the model's cache-collection pytree with
    every KV leaf re-shaped to pages; gather/scatter/adopt are the
    jitted helpers above. Host-side ``tables`` is the source of truth
    (numpy); ``device_tables()`` snapshots it for a slice dispatch.
    """

    def __init__(self, cache_template: Any, *, num_slots: int,
                 page_size: int, cache_size: int,
                 num_pages: Optional[int] = None, mesh: Any = None):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = page_size
        self.cache_size = cache_size
        self.num_slots = num_slots
        self.mesh = mesh
        self.pages_per_slot = -(-cache_size // page_size)
        self.logical_len = self.pages_per_slot * page_size
        if num_pages is None:
            # Default: every slot can hold a full-length sequence,
            # plus the null page. Sizing it smaller is the memory
            # lever (admission then gates on reservations).
            num_pages = num_slots * self.pages_per_slot + 1
        self.allocator = PageAllocator(num_pages)
        kv_sharding = None
        if mesh is not None and mesh.shape.get("tensor", 1) > 1:
            # Serving-mesh placement (serving/sharding.py): the pool
            # shards along kv_heads on the SAME tensor axis the params
            # ride, so per-chip KV memory shrinks with the model and
            # the decode step's attention reads stay chip-local (no
            # cross-chip KV gather). Head counts not divisible by the
            # axis replicate — correctness first, memory second.
            from jax.sharding import NamedSharding, PartitionSpec as P

            kv_sharding = NamedSharding(mesh, P(None, None, "tensor"))

        def to_pages(leaf):
            if not _is_kv(leaf):
                return jnp.zeros(leaf.shape, leaf.dtype)
            _, _, h, d = leaf.shape
            pool = jnp.zeros((num_pages, page_size, h, d), leaf.dtype)
            if kv_sharding is not None and \
                    h % mesh.shape["tensor"] == 0:
                pool = jax.device_put(pool, kv_sharding)
            return pool

        self.physical = jax.tree.map(to_pages, cache_template)
        self.tables = np.zeros((num_slots, self.pages_per_slot),
                               np.int32)

    # -- accounting ------------------------------------------------------

    def pages_for(self, length: int) -> int:
        """Pages needed to back ``length`` cache slots."""
        return -(-length // self.page_size)

    def device_tables(self) -> jax.Array:
        return jnp.asarray(self.tables)

    # -- slot operations (engine thread only) ----------------------------

    def extend_slot(self, slot_index: int, allocated: int,
                    upto_position: int, budget_pages: int) -> int:
        """Allocate pages so slot ``slot_index`` can write through
        cache position ``upto_position`` (exclusive), never past its
        ``budget_pages`` reservation. Returns the new allocated count;
        page ids land in the host table (push with device_tables)."""
        need = min(self.pages_for(upto_position), budget_pages)
        if need <= allocated:
            return allocated
        new_pages = self.allocator.alloc(need - allocated)
        self.tables[slot_index, allocated:need] = new_pages
        return need

    def adopt(self, slot_index: int, prefill_cache: Any,
              prompt_width: int, budget_pages: int,
              shared_pages: Sequence[int] = ()) -> int:
        """Admission: point the slot's leading table rows at the
        already-resident ``shared_pages`` (the caller ref-counted
        them), allocate private pages for the rest of the prompt, and
        copy that tail range of the B=1 prefill cache into them.
        Returns the total table rows filled (shared + private)."""
        shared = len(shared_pages)
        n_pages = min(self.pages_for(prompt_width), budget_pages)
        if shared:
            self.tables[slot_index, :shared] = list(shared_pages)
        n_priv = n_pages - shared
        if n_priv > 0:
            pages = self.allocator.alloc(n_priv)
            self.tables[slot_index, shared:n_pages] = pages
            self.physical = _adopt_prefill(
                self.physical, prefill_cache,
                jnp.asarray(np.asarray(pages, np.int32)),
                jnp.asarray(shared, jnp.int32), n_pages=n_priv)
        return n_pages

    def read_page_layers(self, page: int) -> List[np.ndarray]:
        """Snapshot one pool page's K/V to host memory: one
        ``[page_size, heads, dim]`` array per KV leaf, in
        tree-flatten order (the same deterministic order
        ``kv_tier.splice_host_blocks`` writes back in). The
        evict-to-host copy (ISSUE 20): called inside the prefix
        cache's ``reclaim`` while the page still holds valid K/V —
        jax arrays are immutable, so the snapshot is exact whatever
        the pool does next."""
        out: List[np.ndarray] = []
        for leaf in jax.tree_util.tree_leaves(self.physical):
            if _is_kv(leaf):
                out.append(np.asarray(leaf[int(page)]))
        return out

    def gather_prefix_cache(self, page_ids: Sequence[int],
                            template: Any, fill_len: int) -> Any:
        """Shared prefix pages (padded with the null page to the full
        slot row) → a contiguous B=1 cache with ``index = fill_len``,
        ready for a continuation prefill of the unmatched tail."""
        row = np.zeros((self.pages_per_slot,), np.int32)
        row[: len(page_ids)] = list(page_ids)
        return _gather_pages_to_cache(
            self.physical, jnp.asarray(row), template,
            jnp.asarray(fill_len, jnp.int32))

    def truncate_slot(self, slot_index: int, allocated: int,
                      keep_upto_position: int) -> int:
        """Roll back a speculative multi-token append: keep only the
        pages backing cache positions ``[0, keep_upto_position)`` and
        hand the over-allocated tail back — each dropped page loses
        this slot's reference (a privately allocated decode page goes
        straight back to the free list; ``unref`` keeps shared /
        prefix-retained custody correct if a caller ever truncates
        into shared territory) and its page worth of reservation is
        restored, so the slot can re-extend over the same range as
        its sequence re-advances. Rejected K/V left in KEPT pages
        past ``keep_upto_position`` needs no scrubbing: the decode
        validity mask never attends past a row's write position, and
        the next append overwrites it. Returns the new allocated
        count. Engine thread only (same custody rule as the other
        slot operations)."""
        keep = self.pages_for(max(0, keep_upto_position))
        if keep >= allocated:
            return allocated
        dropped = self.tables[slot_index, keep:allocated].tolist()
        for p in reversed(dropped):
            self.allocator.unref(int(p))
        if not self.allocator.reserve(len(dropped)):
            # Unreachable: unref just returned len(dropped) pages of
            # availability (free or retained custody) — surface
            # loudly rather than silently under-reserving.
            raise RuntimeError(
                f"truncate_slot: could not restore {len(dropped)} "
                f"pages of reservation")
        self.tables[slot_index, keep:allocated] = 0
        return keep

    def release_slot(self, slot_index: int, allocated: int,
                     unreserved_remainder: int) -> None:
        """Retire: drop the slot's reference on every table row
        (shared prefix pages survive under their other users or the
        prefix cache's custody; single-owner pages free), drop its
        remaining reservation, null its table row. Rows unref in
        REVERSE so deeper prefix blocks go idle — and therefore evict
        — before their parents (an orphaned child is unreachable for
        matching but still occupies a page)."""
        for p in reversed(self.tables[slot_index, :allocated].tolist()):
            self.allocator.unref(int(p))
        if unreserved_remainder:
            self.allocator.unreserve(unreserved_remainder)
        self.tables[slot_index, :] = 0

    def gather(self, tables: jax.Array) -> Any:
        return _gather_logical(self.physical, tables)

    def scatter(self, logical: Any, tables: jax.Array,
                start_pos: jax.Array, num_steps: int) -> None:
        self.physical = _scatter_token_range(
            self.physical, logical, tables, start_pos,
            num_steps=num_steps)
