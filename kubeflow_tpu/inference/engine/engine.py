# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""The continuous-batching decode engine.

One persistent decode loop over N slots (grown from the r5
decode-slicing seam in :mod:`inference.generate`): decode runs in
K-token slices; BETWEEN slices finished rows retire (EOS, token
budget, deadline, cancel) and queued requests admit — a B=1 prefill
into a free slot plus a page adoption, never a full-batch recompile.
Tokens stream back per slot as they are sampled.

Why this is the goodput lever: decode is weight-streaming bound
(~20 ms/token at 82% HBM peak on the 7B, PERF r5), so a decode step
costs the same whether 1 or N slots are live — every slot kept full
multiplies tokens/s at near-zero marginal cost, and the r6
admit-at-dispatch coalescer could not keep slots full (a 16-token
request rode until its 128-token neighbor finished; a 1 ms-late
arrival waited a whole decode).

Output contract: every slot's token stream is bitwise equal to the
same request run alone through :func:`inference.generate.generate`
(greedy and sampled) — the per-row decode path reuses the same
decode-step math, per-row rng streams, and left-pad masking whose
row-equality the r6 tests established; tests/test_engine_continuous.py
asserts it under adversarial admit/retire orderings.

Deadlines (r8 contract, extended per-token): a request's budget is
checked at submit (shed), at admission (expired in queue), and at
every slice boundary (mid-decode eviction frees the slot's pages for
the queue). Obs (r9, extended per-token): time-to-first-token and
inter-token histograms, slot-occupancy / free-page gauges, per-request
engine spans.

Speculative decoding (ISSUE 16): with a draft model attached, each
slice becomes a ROUND — k cheap draft steps propose tokens, ONE
batched verifier forward scores the whole [t0, d1..dk] block, and the
longest agreeing prefix is accepted. Targets are sampled from the
VERIFIER's logits with the slot's own step keys, so the emitted
stream is bitwise the vanilla stream whatever the drafts say;
acceptance only decides how many verifier weight-reads that stream
cost. Speculated K/V is written through the page tables and truncated
back to the accepted length (``paged_kv.truncate_slot``).

Chunked prefill (ISSUE 16): long prompts admit into a slot in the
PREFILLING state and feed one page-aligned chunk per engine lap,
interleaved with decode slices — a 4k-token prompt can no longer
stall a decode slot beyond one chunk's compute. The same slot-bound
path now serves ``run_prefill`` in prefix mode, so a prefill-role
replica registers and hits the r15 prefix index (the documented
"prefill pool stays cold" limitation is gone).
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.inference.engine.paged_kv import (
    PagedKVCache,
    _gather_logical,
    _is_kv,
    _scatter_token_range,
)
from kubeflow_tpu.inference.engine.kv_tier import (
    HostKVTier,
    splice_host_blocks,
)
from kubeflow_tpu.inference.engine.prefix_cache import (
    _ROOT,
    PrefixMatch,
    _block_key,
)
from kubeflow_tpu.inference.engine.slots import Slot, SlotScheduler
from kubeflow_tpu.inference.generate import (
    _prefill_jit,
    _sample_logits,
    init_cache,
    prompt_bucket,
)
from kubeflow_tpu.obs import metrics as obs_metrics
from kubeflow_tpu.obs.tracing import TRACER, span_args
from kubeflow_tpu.serving import tenancy
from kubeflow_tpu.serving.overload import (
    DeadlineExceededError,
    LatencyEstimator,
    OverloadedError,
)

logger = logging.getLogger(__name__)

__all__ = ["DecodeEngine", "EngineConfig", "GenerateStream",
           "TokenEvent"]

#: Admission safety factor (same rationale as the micro-batcher's):
#: shed unless the estimated time-to-first-token fits inside this
#: fraction of the remaining budget.
ADMISSION_SAFETY = 0.8

# Engine observability families (bound per engine name; the gauges use
# render-time callbacks with owner-checked clears, like ServedModel's).
_M_SLOTS = obs_metrics.Gauge(
    "kft_engine_active_slots",
    "Decode slots currently bound to a request", ("model",))
_M_QUEUE = obs_metrics.Gauge(
    "kft_engine_queue_depth",
    "Requests admitted by submit() but not yet bound to a slot",
    ("model",))
_M_FREE_PAGES = obs_metrics.Gauge(
    "kft_engine_free_pages",
    "KV-cache pages neither allocated nor reserved", ("model",))
_M_TOKENS = obs_metrics.Counter(
    "kft_engine_tokens_total",
    "Tokens sampled and delivered to streams", ("model",))
_M_ADMITTED = obs_metrics.Counter(
    "kft_engine_admitted_total",
    "Requests prefillled into a slot", ("model",))
_M_RETIRED = obs_metrics.Counter(
    "kft_engine_retired_total",
    "Slots retired, by reason", ("model", "reason"))
_M_SHED = obs_metrics.Counter(
    "kft_engine_shed_total",
    "Requests shed at submit (estimated TTFT over the remaining "
    "deadline budget)", ("model",))
_M_TTFT = obs_metrics.Histogram(
    "kft_serving_ttft_seconds",
    "Submit to first streamed token (queue wait + prefill)",
    ("model",), exemplars=True)
_M_INTER = obs_metrics.Histogram(
    "kft_serving_inter_token_seconds",
    "Per-token decode pacing (slice wall time / slice tokens)",
    ("model",))
# Prefix-cache families (ISSUE 11): hit/miss/evict counters plus the
# saved-prefill-tokens histogram the TTFT win is made of. Evicted and
# cached-pages ride render-time callbacks off the live cache (one
# source of truth, owner-checked clears at stop()).
_M_PREFIX_HITS = obs_metrics.Counter(
    "kft_engine_prefix_hits_total",
    "Admissions that matched a cached prompt prefix", ("model",))
_M_PREFIX_MISSES = obs_metrics.Counter(
    "kft_engine_prefix_misses_total",
    "Admissions with no cached prefix match", ("model",))
_M_PREFIX_EVICTED = obs_metrics.Counter(
    "kft_engine_prefix_evicted_pages_total",
    "Cached prefix pages evicted under page pressure (LRU over "
    "zero-ref pages)", ("model",))
_M_PREFIX_SAVED = obs_metrics.Histogram(
    "kft_engine_prefix_saved_tokens",
    "Prefill tokens skipped per prefix-cache hit",
    ("model",),
    buckets=(1.0, 4.0, 16.0, 64.0, 256.0, 1024.0, 4096.0))
_M_PREFIX_PAGES = obs_metrics.Gauge(
    "kft_engine_prefix_cached_pages",
    "Resident pages indexed by the prefix cache", ("model",))
_M_PAGE_OCC = obs_metrics.Gauge(
    "kft_engine_page_occupancy",
    "Fraction of the KV page pool allocated or reserved "
    "(cached-idle pages count as headroom)", ("model",))
# Speculative-decode families (ISSUE 16): the acceptance economics
# the draft lane is judged by. drafted = k per live slot per round;
# accepted = drafted tokens actually emitted (each one a verifier
# forward NOT paid); the rate gauge is lifetime accepted/drafted via
# a render-time callback off the engine's counters.
_M_SPEC_DRAFTED = obs_metrics.Counter(
    "kft_engine_spec_drafted_tokens_total",
    "Draft-model tokens proposed to the verifier", ("model",))
_M_SPEC_ACCEPTED = obs_metrics.Counter(
    "kft_engine_spec_accepted_tokens_total",
    "Drafted tokens accepted and emitted (verifier forwards saved)",
    ("model",))
_M_SPEC_REJECTED = obs_metrics.Counter(
    "kft_engine_spec_rejected_tokens_total",
    "Drafted tokens discarded at verification", ("model",))
_M_SPEC_RATE = obs_metrics.Gauge(
    "kft_engine_spec_acceptance_rate",
    "Lifetime drafted-token acceptance rate", ("model",))
# Tiered KV memory families (ISSUE 20): the host tier's block flow
# (spill in, re-adopt out, LRU eviction) plus the fleet pull-through
# counters. All ride render-time callbacks off the live tier — one
# source of truth, owner-checked clears at stop(), same discipline as
# the prefix-cache families above.
_M_HOST_SPILLED = obs_metrics.Counter(
    "kft_engine_kv_host_spilled_blocks_total",
    "Prefix KV blocks evicted from HBM into the host-RAM tier",
    ("model",))
_M_HOST_READOPTED = obs_metrics.Counter(
    "kft_engine_kv_host_readopted_blocks_total",
    "Host-tier KV blocks spliced back HBM-ward on a prefix match",
    ("model",))
_M_HOST_EVICTED = obs_metrics.Counter(
    "kft_engine_kv_host_evicted_blocks_total",
    "Host-tier KV blocks dropped by the byte-budget LRU", ("model",))
_M_HOST_BYTES = obs_metrics.Gauge(
    "kft_engine_kv_host_resident_bytes",
    "Bytes of KV blocks resident in the host-RAM tier", ("model",))
_M_HOST_BLOCKS = obs_metrics.Gauge(
    "kft_engine_kv_host_resident_blocks",
    "KV blocks resident in the host-RAM tier", ("model",))
_M_KV_FETCH = obs_metrics.Counter(
    "kft_engine_kv_fetch_total",
    "Fleet KV pull-through fetches, by outcome (a 'miss' or 'error' "
    "outcome always falls back to local prefill — never an error)",
    ("model", "outcome"))
_M_KV_FETCH_BLOCKS = obs_metrics.Counter(
    "kft_engine_kv_fetched_blocks_total",
    "KV blocks imported from fleet peers into the host tier",
    ("model",))


@dataclasses.dataclass
class TokenEvent:
    """One streamed event: a sampled token, or the terminal marker
    (``final=True``; ``error`` set when the request failed
    mid-stream)."""

    token: Optional[int]
    index: int
    final: bool = False
    error: Optional[BaseException] = None


class GenerateStream:
    """The caller's handle on one request: an incremental token-event
    queue plus the collected result. Engine thread emits; any number
    of consumer threads may drain (SSE handler, gRPC stream, a plain
    ``result()`` waiter)."""

    def __init__(self, max_new_tokens: int, obs_ctx: Any = None):
        self.max_new_tokens = max_new_tokens
        self.obs_ctx = obs_ctx
        self._cv = threading.Condition()
        self._queue: Deque[TokenEvent] = deque()
        self._tokens: List[int] = []
        self._result: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None
        self._final = False
        self._notify: Optional[Callable[[], None]] = None
        self.cancelled = False
        #: Mid-stream resume seam (ISSUE 13): the context a PEER
        #: engine needs to reproduce this request's remaining tokens
        #: bitwise if this replica dies mid-decode — {"prompt" (the
        #: full context ids), "step_keys" (the remaining sampling
        #: schedule), "max_new_tokens"}. None when unresumable (a
        #: left-layout handoff carries no prompt ids).
        self.resume_ctx: Optional[dict] = None

    # -- engine side -----------------------------------------------------

    def _emit(self, event: TokenEvent) -> None:
        with self._cv:
            if self._final:
                return
            self._queue.append(event)
            if not event.final and event.token is not None:
                self._tokens.append(event.token)
            if event.final:
                self._final = True
                self._error = event.error
                if event.error is None and self._result is None:
                    self._result = np.asarray(self._tokens, np.int32)
            self._cv.notify_all()
        cb = self._notify
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — a consumer bug must
                logger.exception("stream notify callback failed")

    def _finish(self, tokens: np.ndarray) -> None:
        with self._cv:
            self._result = np.asarray(tokens, np.int32)
        self._emit(TokenEvent(token=None, index=len(self._tokens),
                              final=True))

    def _fail(self, error: BaseException) -> None:
        self._emit(TokenEvent(token=None, index=len(self._tokens),
                              final=True, error=error))

    # -- consumer side ---------------------------------------------------

    def set_notify(self, cb: Optional[Callable[[], None]]) -> None:
        """Called (from the ENGINE thread) after each emit — the hook
        async transports use to schedule a drain on their own loop."""
        self._notify = cb

    def next_event(self, timeout: float) -> Optional[TokenEvent]:
        """Pop the next event, waiting up to ``timeout``; None on
        timeout. The terminal event stays poppable exactly once."""
        with self._cv:
            if not self._cv.wait_for(lambda: bool(self._queue),
                                     timeout=timeout):
                return None
            return self._queue.popleft()

    def drain(self) -> List[TokenEvent]:
        """Pop everything queued right now (non-blocking)."""
        with self._cv:
            out = list(self._queue)
            self._queue.clear()
            return out

    def events(self, timeout_per_event: float = 60.0
               ) -> Iterator[TokenEvent]:
        """Iterate events up to AND including the terminal one.
        Raises TimeoutError if the engine stalls past the per-event
        timeout (bounded waits — serving discipline)."""
        while True:
            ev = self.next_event(timeout_per_event)
            if ev is None:
                raise TimeoutError(
                    f"no token event within {timeout_per_event}s")
            yield ev
            if ev.final:
                return

    @property
    def done(self) -> bool:
        with self._cv:
            return self._final

    @property
    def tokens_so_far(self) -> List[int]:
        with self._cv:
            return list(self._tokens)

    def result(self, timeout: float = 120.0) -> np.ndarray:
        """Block for the full token array (padded to the request's
        ``max_new_tokens`` with the EOS id on early retirement — the
        same latched shape the monolithic generate returns)."""
        with self._cv:
            if not self._cv.wait_for(lambda: self._final,
                                     timeout=timeout):
                raise TimeoutError(
                    f"generation did not finish within {timeout}s")
            if self._error is not None:
                raise self._error
            return np.array(self._result)

    def cancel(self) -> None:
        """Client hung up: the engine retires the slot at the next
        slice boundary and frees its pages."""
        self.cancelled = True


@dataclasses.dataclass
class PrefillHandoff:
    """The page-adopt seam's transferable half: everything a decode
    engine needs to resume a request whose prefill ran ELSEWHERE —
    on a prefill-role replica (role-split routing, serving/wire.py
    carries it between processes) or simply on another engine in
    this process. ``step_keys`` travels whole because
    ``jax.random.split(key, n)`` depends on n: re-deriving on the
    decode side with a different budget would silently fork the
    sampled sequence away from the single-replica path."""

    cache: Any  # B=1 prefill cache pytree ([1, C, h, d] KV leaves)
    first_token: int
    done: bool
    prompt_len: int  # true prompt token count
    prompt_width: int  # prefill bucket width (pad + prompt)
    max_new_tokens: int
    step_keys: np.ndarray  # [max_new_tokens, 2] uint32
    #: Cache layout the prefill ran in: ``left`` (classic left-padded
    #: prompt at ``[width-len, width)``) or ``right`` (prefix-cache
    #: pad-0 — prompt at ``[0, len)``, ``prompt_width == prompt_len``).
    #: An engine only adopts its own layout; the server maps the
    #: mismatch to a 400 and the proxy falls back to classic routing.
    layout: str = "left"
    #: The prompt ids themselves (``right`` layout): the adopting
    #: engine indexes the carried pages in ITS prefix cache, which is
    #: what turns the r14 handoff blob into a fleet-wide warm
    #: transfer — prefill once, adopt (and cache) everywhere.
    prompt_tokens: Optional[np.ndarray] = None


@dataclasses.dataclass(eq=False)  # identity equality: the queued-
# cancel sweep removes by instance, and the generated field-wise eq
# compares numpy prompts (ambiguous broadcast ValueError between two
# different-length queued requests)
class _Request:
    prompt: np.ndarray  # [L] int32
    step_keys: np.ndarray  # [max_new_tokens, 2] uint32 sampling keys
    max_new_tokens: int
    deadline: Optional[float]
    stream: GenerateStream
    submitted_at: float
    request_id: str = ""
    #: Tenant identity (ISSUE 14): names this request's weighted-fair
    #: sub-queue and tags its TTFT/usage metrics. Empty = the default
    #: tenant (single-tenant deployments — one sub-queue, bitwise the
    #: old FIFO).
    tenant: str = ""
    #: Adopt-don't-prefill: the request arrives WITH its prefilled
    #: cache (role-split KV handoff); admission copies the pages in
    #: and decode starts at the first slice.
    handoff: Optional[PrefillHandoff] = None
    #: Prefill-only (ISSUE 16): the prefix-mode ``run_prefill`` path.
    #: The request binds a slot, prefills (chunked when configured),
    #: registers its pages in the prefix index, then retires with a
    #: :class:`PrefillHandoff` in ``prefill_box`` instead of
    #: decoding — that slot-bound hop is what finally warms the
    #: prefill-role pool's index.
    prefill_only: bool = False
    prefill_box: Optional[dict] = None
    #: Fleet KV fetch wall (ISSUE 20): seconds the serving layer
    #: spent pulling this request's prefix blocks from the rendezvous
    #: owner before submit. Attributed as its own ``kv_fetch_ms``
    #: bucket in the engine_request span so a tier fetch is never
    #: mistaken for queue wait or decode time.
    kv_fetch_s: float = 0.0


@dataclasses.dataclass
class EngineConfig:
    """Static decode configuration (mirrors generate_config) + the
    engine's capacity knobs."""

    max_new_tokens: int
    max_prompt_len: int
    temperature: float = 0.0
    eos_id: Optional[int] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    seed: int = 0
    #: decode slots (the persistent batch width; one compile).
    num_slots: int = 4
    #: KV-cache page granularity (cache slots per page).
    page_size: int = 16
    #: decode steps per slice — the admit/retire cadence AND the
    #: streaming granularity (tokens reach the host per slice).
    slice_tokens: int = 4
    #: prompt length buckets (None = powers of two); each bucket is
    #: one prefill compile.
    prompt_buckets: Optional[Sequence[int]] = None
    #: physical page-pool size (None = every slot can go full length).
    num_pages: Optional[int] = None
    #: admission-queue depth bound: deadline-free submits past it shed
    #: with OverloadedError (the r8 queue_capacity invariant — without
    #: it a flood of deadline-free requests grows pending without
    #: limit while the deadline gate never fires).
    queue_capacity: int = 4096
    #: cross-request prefix KV cache (ISSUE 11): admissions switch to
    #: the pad-0 (right-padded) prompt layout so prompt token i always
    #: lands at cache position i, prompt pages are content-indexed by
    #: a radix of token-block hashes, and a matching prefix is shared
    #: copy-on-write instead of re-prefilled. Output stays bitwise
    #: equal to cold prefill (greedy + sampled).
    prefix_cache: bool = False
    #: speculative decoding (ISSUE 16): draft tokens per verify round
    #: (0 = vanilla decode). Takes effect only when the engine is
    #: built with a draft model; output stays bitwise vanilla either
    #: way.
    speculate_tokens: int = 0
    #: chunked prefill (ISSUE 16): page-aligned prompt tokens fed per
    #: engine lap for prompts whose unmatched tail exceeds one chunk
    #: (0 = one-shot prefill). Prefix-cache mode only — chunks
    #: accumulate in the pad-0 layout.
    prefill_chunk: int = 0
    #: tiered KV memory (ISSUE 20): byte budget for the host-RAM
    #: prefix tier (0 = off). With a budget, LRU eviction of zero-ref
    #: retained pages becomes evict-to-host, matches continue past
    #: the HBM chain into host blocks (spliced back bitwise), and the
    #: fleet pull-through endpoint (:kvfetch) can import blocks from
    #: peer replicas. Prefix-cache mode only.
    host_cache_bytes: int = 0

    @staticmethod
    def from_generate_config(cfg: dict, max_prompt_len: int,
                             queue_capacity: Optional[int] = None
                             ) -> "EngineConfig":
        """Build from an export's ``generate_config`` (the ``engine_*``
        keys are the serving-side capacity knobs, docs/streaming.md)."""
        return EngineConfig(
            max_new_tokens=int(cfg.get("max_new_tokens", 32)),
            max_prompt_len=max_prompt_len,
            temperature=float(cfg.get("temperature", 0.0)),
            eos_id=cfg.get("eos_id"),
            top_k=cfg.get("top_k"),
            top_p=cfg.get("top_p"),
            seed=int(cfg.get("seed", 0)),
            num_slots=int(cfg.get("engine_slots", 4)),
            page_size=int(cfg.get("engine_page_size", 16)),
            slice_tokens=int(cfg.get("engine_slice_tokens", 4)),
            prompt_buckets=cfg.get("prompt_buckets"),
            num_pages=cfg.get("engine_num_pages"),
            queue_capacity=(4096 if queue_capacity is None
                            else int(queue_capacity)),
            prefix_cache=bool(cfg.get("engine_prefix_cache", False)),
            speculate_tokens=int(cfg.get("engine_draft_tokens", 0)),
            prefill_chunk=int(cfg.get("engine_prefill_chunk", 0)),
            host_cache_bytes=int(
                cfg.get("engine_host_cache_bytes", 0)),
        )


@functools.partial(
    jax.jit,
    static_argnames=("model", "temperature", "eos_id", "top_k",
                     "top_p"))
def _prefill_ctx_jit(model, params, token_block, cache, start,
                     last_col, first_rng, *, temperature, eos_id,
                     top_k, top_p):
    """Pad-0 (right-padded) prompt pass for prefix-cache mode, cold
    AND continuation in one program: ``token_block`` [1, W] holds
    prompt tokens ``[start, start + real)`` right-padded to a static
    bucket width, and ``cache`` carries the already-resident prefix
    at positions ``[0, start)`` with its scalar ``index`` leaves at
    ``start`` (the zero template with index 0 for a cold prefill).
    The model's scalar append path writes the block at ``[start,
    start + W)`` and attends causally from ``q_offset = start``, so
    the right-pad garbage never reaches a real token's attention
    (causality IS the mask — same argument as the slice path's
    validity==causality contract) and garbage K/V lands only at
    positions the decode overwrites or masks. Next-token logits are
    read at the LAST REAL column ``last_col``; ``start``/``last_col``
    are traced, so prefix hits of every length share one compile per
    block width."""
    b, width = token_block.shape
    positions = start + jnp.broadcast_to(
        jnp.arange(width, dtype=jnp.int32)[None, :], (b, width))
    logits, mutated = model.apply(
        {"params": params, "cache": cache}, token_block, positions,
        mutable=["cache"])
    last_logits = jnp.take(logits, last_col, axis=1)  # [B, V]
    first = _sample_logits(last_logits, first_rng, temperature,
                           top_k, top_p)
    done = (first == eos_id) if eos_id is not None else \
        jnp.zeros((b,), bool)
    return mutated["cache"], first, done


def _decode_slice(model, params, physical, tables, write_pos,
                  pad_lens, tokens, done, step_rngs,
                  *, temperature, eos_id, top_k, top_p):
    """One K-token slice over the slot batch: gather the logical
    cache from pages ONCE, scan the per-row decode step over it,
    scatter the K newly written token positions back. The step math is
    the same sample → EOS-latch → advance as generate's
    ``_make_decode_step``; only the cache write is per-row
    (``decode_positions``) instead of the shared scalar index — that
    is what lets rows sit at different sequence positions."""
    logical = _gather_logical(physical, tables)

    def step(carry, rngs_k):
        cache, tok, wpos, dn = carry
        positions = (wpos - pad_lens)[:, None]
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, tok[:, None],
            positions, mutable=["cache"], pad_lengths=pad_lens,
            decode_positions=wpos)
        logits = logits[:, 0]
        next_tok = _sample_logits(logits, rngs_k, temperature,
                                  top_k, top_p)
        if eos_id is not None:
            next_tok = jnp.where(dn, eos_id, next_tok)
            dn = dn | (next_tok == eos_id)
        return (mutated["cache"], next_tok, wpos + 1, dn), next_tok

    (logical, last_tok, _, done), out = jax.lax.scan(
        step, (logical, tokens, write_pos, done), step_rngs)
    physical = _scatter_token_range(physical, logical, tables,
                                    write_pos,
                                    num_steps=step_rngs.shape[0])
    return physical, out.swapaxes(0, 1), last_tok, done


def _draft_slice(draft_model, draft_params, draft_cache, tokens,
                 write_pos, pad_lens, done, step_rngs,
                 *, temperature, eos_id, top_k, top_p):
    """k single-token draft steps over the persistent DENSE draft
    cache (B = num_slots, one row per slot — the draft is small
    enough that paging it would cost more in gather/scatter than the
    rows hold). Same step math as :func:`_decode_slice` minus the
    page plumbing, and sampled with the SAME step keys the verifier
    will use — with similar logits the categorical draw then lands
    on the same token, which is what acceptance is made of. Rejected
    rows need no rollback: position validity is the slot's write_pos
    frontier, and the next round overwrites stale K/V before any
    query can attend to it.

    The scan runs k+1 steps for k proposals — the extra step exists
    ONLY to write the k-th draft's K/V into the cache. On a full
    accept the next round starts past that position, and without the
    write it would hold zeros forever (never overwritten, silently
    poisoning every later draft — acceptance collapses while output
    stays correct). The k+1-th proposal is discarded."""
    def step(carry, rngs_k):
        cache, tok, wpos, dn = carry
        positions = (wpos - pad_lens)[:, None]
        logits, mutated = draft_model.apply(
            {"params": draft_params, "cache": cache}, tok[:, None],
            positions, mutable=["cache"], pad_lengths=pad_lens,
            decode_positions=wpos)
        next_tok = _sample_logits(logits[:, 0], rngs_k, temperature,
                                  top_k, top_p)
        if eos_id is not None:
            next_tok = jnp.where(dn, eos_id, next_tok)
            dn = dn | (next_tok == eos_id)
        return (mutated["cache"], next_tok, wpos + 1, dn), next_tok

    (cache, _, _, _), drafts = jax.lax.scan(
        step, (draft_cache, tokens, write_pos, done), step_rngs)
    return cache, drafts.swapaxes(0, 1)  # [N, k]


def _verify_slice(model, params, physical, tables, write_pos,
                  pad_lens, tokens, drafts, done, step_rngs,
                  *, temperature, eos_id, top_k, top_p):
    """ONE batched verifier forward over each slot's speculative
    block [t0, d1..dk] (k+1 positions), then the sample → EOS-latch
    chain replayed over the k+1 logit columns with the slot's own
    step keys — target j is bitwise the token the vanilla slice
    would have sampled at that step, whatever the drafts proposed.
    Acceptance is the longest agreeing draft prefix (cumprod of the
    match mask). The block's K/V is written through the page tables
    at [write_pos, write_pos + k + 1); the host truncates back to
    the accepted length (``paged_kv.truncate_slot``). The model runs
    its l > 1 attention per-query at single-token shapes
    (models/llama.py unrolls) — one [l, S] GEMM would reassociate
    the value contraction vs the l == 1 GEMV and break the bitwise
    token contract."""
    logical = _gather_logical(physical, tables)
    block = jnp.concatenate([tokens[:, None], drafts], axis=1)
    width = block.shape[1]
    positions = (write_pos - pad_lens)[:, None] + \
        jnp.arange(width, dtype=jnp.int32)[None, :]
    logits, mutated = model.apply(
        {"params": params, "cache": logical}, block, positions,
        mutable=["cache"], pad_lengths=pad_lens,
        decode_positions=write_pos)

    def step(dn, xs):
        col_logits, rngs_k = xs
        next_tok = _sample_logits(col_logits, rngs_k, temperature,
                                  top_k, top_p)
        if eos_id is not None:
            next_tok = jnp.where(dn, eos_id, next_tok)
            dn = dn | (next_tok == eos_id)
        return dn, next_tok

    _, targets = jax.lax.scan(
        step, done, (logits.swapaxes(0, 1), step_rngs))
    targets = targets.swapaxes(0, 1)  # [N, k+1]
    agree = (drafts == targets[:, :-1]).astype(jnp.int32)
    accepts = jnp.sum(jnp.cumprod(agree, axis=1), axis=1)  # [N]
    physical = _scatter_token_range(physical, mutated["cache"],
                                    tables, write_pos,
                                    num_steps=width)
    return physical, targets, accepts


@jax.jit
def _insert_cache_row(batched, single, row):
    """Land a B=1 prefill cache in row ``row`` of a B=N cache — the
    draft cache's admission write. KV leaves only: the batched
    cache's scalar index leaves stay untouched because the decode
    path addresses positions explicitly (``decode_positions``).
    ``row`` is traced, so every slot shares one compile."""
    def ins(dst, src):
        if not _is_kv(dst):
            return dst
        return jax.lax.dynamic_update_slice(
            dst, src.astype(dst.dtype),
            (row,) + (0,) * (dst.ndim - 1))
    return jax.tree.map(ins, batched, single)


class DecodeEngine:
    """Slot-based continuous-batching decode over one model.

    ``submit()`` is thread-safe and returns a :class:`GenerateStream`;
    all device work happens on the single engine thread (started
    lazily, like the micro-batcher's). ``model`` must be built with a
    ``cache_size >= max_prompt_len + max_new_tokens``.
    """

    def __init__(self, model: Any, params: Any, config: EngineConfig,
                 *, name: str = "engine", mesh: Any = None,
                 draft_model: Any = None, draft_params: Any = None):
        if model.cache_size < config.max_prompt_len + \
                config.max_new_tokens:
            raise ValueError(
                f"cache_size {model.cache_size} < max_prompt_len "
                f"{config.max_prompt_len} + max_new_tokens "
                f"{config.max_new_tokens}")
        if config.speculate_tokens < 0:
            raise ValueError(
                f"speculate_tokens {config.speculate_tokens} < 0")
        self._spec_on = (draft_model is not None
                         and config.speculate_tokens > 0)
        if config.speculate_tokens > 0 and draft_model is None:
            # The knob survived export but the draft weights didn't
            # load (serving/model.py degrades here): vanilla decode,
            # never a failed engine — output is bitwise identical
            # either way, only the verifier-forward count differs.
            logger.warning(
                "engine %s: engine_draft_tokens=%d but no draft "
                "model — speculative decoding disabled, decoding "
                "vanilla", name, config.speculate_tokens)
        if self._spec_on:
            if draft_model.cache_size != model.cache_size:
                raise ValueError(
                    f"draft cache_size {draft_model.cache_size} != "
                    f"verifier cache_size {model.cache_size} — the "
                    f"draft writes at the verifier's slot positions")
            if draft_model.vocab_size != model.vocab_size:
                raise ValueError(
                    f"draft vocab_size {draft_model.vocab_size} != "
                    f"verifier vocab_size {model.vocab_size}")
        self._model = model
        self._params = params
        self._draft_model = draft_model
        self._draft_params = draft_params
        self.config = config
        self.name = name
        #: tp/fsdp serving mesh (serving/sharding.py) the params live
        #: on; the page pool shards its kv_heads dim along the same
        #: tensor axis. None = classic single-device serving.
        self.mesh = mesh
        template = init_cache(model, params, 1)
        # Reused for every admission's B=1 prefill: init_cache runs a
        # full abstract model trace (~150ms even for a toy model —
        # measured dominating admission 184:6 over the actual prefill
        # dispatch), and the prefill is functional, so one zero
        # template serves every request.
        self._prefill_template = template
        self.kv = PagedKVCache(
            template, num_slots=config.num_slots,
            page_size=config.page_size, cache_size=model.cache_size,
            num_pages=config.num_pages, mesh=mesh)
        self.scheduler = SlotScheduler(config.num_slots,
                                       self.kv.allocator)
        #: Tenant-quota weights for the fair admission queue (ISSUE
        #: 14): ``set_tenant_weights`` installs the registry's
        #: ``weight(tenant)`` lookup; unset, every tenant weighs 1.0.
        #: Cross-request prefix cache (prefix_cache.py) or None. Built
        #: here so the allocator's retained-page custody is wired
        #: before the first admission.
        self.prefix = None
        if config.prefix_cache:
            from kubeflow_tpu.inference.engine.prefix_cache import (
                PrefixCache,
            )

            self.prefix = PrefixCache(config.page_size,
                                      self.kv.allocator)
        if config.prefill_chunk:
            if self.prefix is None:
                raise ValueError(
                    "engine_prefill_chunk requires engine_prefix_cache"
                    " — chunks accumulate in the pad-0 layout and "
                    "land in the prefix index")
            if config.prefill_chunk % config.page_size:
                raise ValueError(
                    f"engine_prefill_chunk {config.prefill_chunk} "
                    f"must be a multiple of engine_page_size "
                    f"{config.page_size} (page-aligned slices)")
        if config.host_cache_bytes < 0:
            raise ValueError(
                f"engine_host_cache_bytes {config.host_cache_bytes} "
                f"< 0 (0 disables the host tier)")
        #: Host-RAM KV tier (ISSUE 20) or None. Wired here so the
        #: prefix cache's reclaim spills from the first eviction.
        self.host_tier: Optional[HostKVTier] = None
        if config.host_cache_bytes > 0:
            if self.prefix is None:
                # The knob survived export without the prefix cache:
                # there is no index to tier — degrade, never a failed
                # engine (same contract as the draft-tokens knob).
                logger.warning(
                    "engine %s: engine_host_cache_bytes=%d but "
                    "engine_prefix_cache is off — host KV tier "
                    "disabled", name, config.host_cache_bytes)
            else:
                self.host_tier = HostKVTier(config.host_cache_bytes)
                self.prefix.set_host_tier(self.host_tier)
                self.prefix.set_spill(self._spill_entry)
        # Expected per-page host layer shapes ([page_size, heads,
        # dim] per KV leaf, tree-flatten order): the shape gate every
        # fleet-fetched block must pass before it can be spliced.
        self._kv_leaf_shapes = [
            tuple(leaf.shape[1:])
            for leaf in jax.tree_util.tree_leaves(self.kv.physical)
            if _is_kv(leaf)]
        # Fleet pull-through accounting (GIL-consistent ints; the
        # serving layer increments via note_kv_fetch from request
        # threads).
        self._kv_fetch_hits = 0
        self._kv_fetch_misses = 0
        self._kv_fetch_errors = 0
        self._kv_fetched_blocks_total = 0
        self._cv = threading.Condition()
        # Engine-thread control queue (ISSUE 20): closures posted by
        # _run_on_engine and drained at the top of each lap, so
        # request threads can read engine-owned state (the prefix
        # index, live pool pages) without torn reads.
        self._control: Deque[Callable[[], None]] = deque()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._rng_counter = 0
        # TTFT/pacing estimators feed the submit-side admission gate.
        self._prefill_est = LatencyEstimator(prior_s=0.05)
        self._token_est = LatencyEstimator(prior_s=0.01)
        # Compile-event tracking (ISSUE 15): the first dispatch of a
        # distinct (program, static-shape) key IS the jit trace +
        # compile — later hits are cached. Recording that first call
        # as an engine_compile span makes a recompile storm (bucket
        # churn, slice-length churn) visible in the timeline instead
        # of inferred from a throughput dip. Mutated from the engine
        # thread AND run_prefill request threads — a lost check-then-
        # add race records one duplicate span, never corrupts.
        self._compile_seen: set = set()
        self._slices = 0
        # The jitted slice closes over model + sampling config; one
        # compile per distinct slice length (K_eff shrinks near a
        # request's budget end — a handful of variants, cached).
        self._slice_jit = jax.jit(functools.partial(
            _decode_slice, model,
            temperature=config.temperature, eos_id=config.eos_id,
            top_k=config.top_k, top_p=config.top_p))
        # Draft lane (ISSUE 16): a persistent dense draft cache (one
        # row per slot) plus SPLIT draft/verify dispatches, so the
        # attribution report can tell draft wall from verify wall
        # (the spec_verify span / draft_ms-verify_ms request args).
        self._draft_cache = None
        self._draft_prefill_template = None
        self._spec_rounds = 0
        self._spec_drafted_total = 0
        self._spec_accepted_total = 0
        if self._spec_on:
            self._draft_cache = init_cache(draft_model, draft_params,
                                           config.num_slots)
            self._draft_prefill_template = init_cache(
                draft_model, draft_params, 1)
            self._draft_jit = jax.jit(functools.partial(
                _draft_slice, draft_model,
                temperature=config.temperature,
                eos_id=config.eos_id, top_k=config.top_k,
                top_p=config.top_p))
            self._verify_jit = jax.jit(functools.partial(
                _verify_slice, model,
                temperature=config.temperature,
                eos_id=config.eos_id, top_k=config.top_k,
                top_p=config.top_p))
        # Metric children (owner-checked gauge callbacks).
        self._m_tokens = _M_TOKENS.labels(name)
        self._m_admitted = _M_ADMITTED.labels(name)
        self._m_shed = _M_SHED.labels(name)
        self._m_ttft = _M_TTFT.labels(name)
        self._m_inter = _M_INTER.labels(name)
        self._g_slots = _M_SLOTS.labels(name)
        self._g_slots.set_function(self.scheduler.occupancy)
        self._g_queue = _M_QUEUE.labels(name)
        self._g_queue.set_function(self.scheduler.queue_depth)
        self._g_pages = _M_FREE_PAGES.labels(name)
        self._g_pages.set_function(self.kv.allocator.available)
        self._g_occupancy = _M_PAGE_OCC.labels(name)
        self._g_occupancy.set_function(self.page_occupancy)
        if self._spec_on:
            self._m_spec_drafted = _M_SPEC_DRAFTED.labels(name)
            self._m_spec_accepted = _M_SPEC_ACCEPTED.labels(name)
            self._m_spec_rejected = _M_SPEC_REJECTED.labels(name)
            self._g_spec_rate = _M_SPEC_RATE.labels(name)
            self._g_spec_rate.set_function(self.spec_acceptance_rate)
        if self.prefix is not None:
            self._m_prefix_hits = _M_PREFIX_HITS.labels(name)
            self._m_prefix_misses = _M_PREFIX_MISSES.labels(name)
            self._m_prefix_saved = _M_PREFIX_SAVED.labels(name)
            self._m_prefix_evicted = _M_PREFIX_EVICTED.labels(name)
            self._m_prefix_evicted.set_function(
                self._prefix_evicted_total)
            self._g_prefix_pages = _M_PREFIX_PAGES.labels(name)
            self._g_prefix_pages.set_function(
                self.prefix.resident_pages)
        if self.host_tier is not None:
            self._m_host_spilled = _M_HOST_SPILLED.labels(name)
            self._m_host_spilled.set_function(self._host_spilled)
            self._m_host_readopted = _M_HOST_READOPTED.labels(name)
            self._m_host_readopted.set_function(self._host_readopted)
            self._m_host_evicted = _M_HOST_EVICTED.labels(name)
            self._m_host_evicted.set_function(self._host_evicted)
            self._g_host_bytes = _M_HOST_BYTES.labels(name)
            self._g_host_bytes.set_function(
                self.host_tier.resident_bytes)
            self._g_host_blocks = _M_HOST_BLOCKS.labels(name)
            self._g_host_blocks.set_function(
                self.host_tier.resident_blocks)
            self._m_kv_fetch_hit = _M_KV_FETCH.labels(name, "hit")
            self._m_kv_fetch_miss = _M_KV_FETCH.labels(name, "miss")
            self._m_kv_fetch_error = _M_KV_FETCH.labels(name, "error")
            self._m_kv_fetched_blocks = _M_KV_FETCH_BLOCKS.labels(
                name)

    # -- submit side -----------------------------------------------------

    def set_tenant_weights(self,
                           weight_of: Optional[Callable[[str], float]]
                           ) -> None:
        """Install the tenant-quota weight lookup the fair admission
        queue drains by (idempotent; safe while traffic flows — the
        queue reads it per scheduling decision)."""
        self.scheduler.pending.weight_of = weight_of

    def _next_key(self) -> np.ndarray:
        base = jax.random.PRNGKey(self.config.seed)
        with self._cv:
            self._rng_counter += 1
            counter = self._rng_counter
        return np.asarray(jax.random.fold_in(base, counter))

    def estimated_ttft_s(self) -> float:
        """Submit-time TTFT estimate: queue-ahead prefills plus the
        slice currently occupying the executor. Deliberately simple —
        it gates deadline shedding, not scheduling."""
        queued = self.scheduler.queue_depth()
        prefill = self._prefill_est.estimate_s()
        slice_s = self._token_est.estimate_s() * \
            self.config.slice_tokens
        return (queued + 1) * prefill + slice_s * (
            1.0 + queued / max(1, self.config.num_slots))

    def page_occupancy(self) -> float:
        """Fraction of the pool allocated to live slots or spoken for
        by reservations — the page-pressure number /healthz and the
        autoscaler read. Cached-idle pages count as headroom (they
        reclaim on demand), matching the admission gate's own
        arithmetic."""
        alloc = self.kv.allocator
        total = alloc.num_pages - 1
        return (total - alloc.available()) / total if total else 1.0

    def spec_acceptance_rate(self) -> float:
        """Lifetime drafted-token acceptance rate (0.0 before the
        first speculative round)."""
        if not self._spec_drafted_total:
            return 0.0
        return self._spec_accepted_total / self._spec_drafted_total

    def _prefix_evicted_total(self) -> float:
        return float(self.prefix.evicted_pages) if self.prefix \
            else 0.0

    def _host_spilled(self) -> float:
        return float(self.host_tier.spilled_blocks) \
            if self.host_tier else 0.0

    def _host_readopted(self) -> float:
        return float(self.host_tier.readopted_blocks) \
            if self.host_tier else 0.0

    def _host_evicted(self) -> float:
        return float(self.host_tier.evicted_blocks) \
            if self.host_tier else 0.0

    def _spill_entry(self, entry) -> None:
        """Evict-to-host hook (PrefixCache.set_spill): snapshot a
        full block's page to host buffers under its chain key. Runs
        INSIDE reclaim on the engine thread, before the page id
        returns to the free list — the copy reads valid K/V. Never
        raises: a failed spill degrades to the r15 drop (the next
        match re-prefills), it must not poison the allocation that
        triggered the eviction."""
        try:
            self.host_tier.put(
                entry.key, entry.tokens,
                self.kv.read_page_layers(entry.page))
        except Exception:  # noqa: BLE001 — degrade to plain drop
            logger.exception(
                "engine %s: host-tier spill failed; page dropped "
                "cold", self.name)

    def note_kv_fetch(self, outcome: str, *, blocks: int = 0) -> None:
        """Record one fleet pull-through attempt from the serving
        layer (``hit`` / ``miss`` / ``error``). Thread-safe (GIL
        ints + metric children)."""
        if self.host_tier is None:
            return
        if outcome == "hit":
            self._kv_fetch_hits += 1
            self._m_kv_fetch_hit.inc()
            if blocks:
                self._kv_fetched_blocks_total += blocks
                self._m_kv_fetched_blocks.inc(blocks)
        elif outcome == "miss":
            self._kv_fetch_misses += 1
            self._m_kv_fetch_miss.inc()
        else:
            self._kv_fetch_errors += 1
            self._m_kv_fetch_error.inc()

    # -- fleet KV tier (ISSUE 20) ----------------------------------------

    def _run_on_engine(self, fn: Callable[[], Any],
                       timeout_s: float = 5.0) -> Any:
        """Run ``fn`` on the engine thread between laps and return
        its result (bounded wait). The engine's single-mutator
        discipline covers the prefix index and the pool's page
        custody; a request thread that walked them directly could
        read a page id mid-reassignment. Inline when the engine
        thread isn't running — nothing else owns the state then."""
        with self._cv:
            thread = self._thread
        if thread is None or not thread.is_alive():
            return fn()
        done = threading.Event()
        box: dict = {}

        def wrapped() -> None:
            try:
                box["result"] = fn()
            except BaseException as e:  # noqa: BLE001 — carried back
                box["error"] = e
            finally:
                done.set()

        with self._cv:
            self._control.append(wrapped)
            self._cv.notify_all()
        if not done.wait(timeout_s):
            raise TimeoutError(
                f"engine {self.name} control op timed out after "
                f"{timeout_s:.1f}s")
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def probe_prefix(self, prompt: np.ndarray) -> int:
        """Cheap, lock-free estimate of how many prompt tokens this
        engine could serve from its own tiers (HBM chain + host
        continuation + one boundary partial). Dict reads off the
        engine thread — a torn read costs one suboptimal fetch
        decision, never correctness (the authoritative match runs at
        admission). 0 when prefix caching is off."""
        if self.prefix is None:
            return 0
        try:
            return self.prefix.match(
                np.asarray(prompt, np.int32).reshape(-1)).matched
        except Exception:  # noqa: BLE001 — benign-race probe
            return 0

    def export_prefix_blocks(self, prompt: np.ndarray, *,
                             timeout_s: float = 5.0
                             ) -> List[tuple]:
        """Owner-side half of the fleet pull-through: every resident
        FULL block of ``prompt`` (HBM or host tier), chain order, as
        ``(block_tokens, layers)`` pairs ready for the wire codec.
        Runs the walk + page snapshots on the engine thread (torn
        page reads are wrong K/V — not acceptable even on a
        best-effort path); any failure or timeout returns [] and the
        fetcher falls back to prefill."""
        if self.prefix is None:
            return []
        tokens = np.asarray(prompt, np.int32).reshape(-1)

        def walk() -> List[tuple]:
            out = []
            for block, entry, is_hbm in self.prefix.chain_blocks(
                    tokens):
                layers = (self.kv.read_page_layers(entry.page)
                          if is_hbm else entry.layers)
                out.append((block, layers))
            return out

        try:
            return self._run_on_engine(walk, timeout_s=timeout_s)
        except Exception:  # noqa: BLE001 — best-effort export
            logger.warning(
                "engine %s: prefix-block export failed; peer will "
                "prefill cold", self.name, exc_info=True)
            return []

    def import_prefix_blocks(self, blocks: Sequence[tuple]) -> int:
        """Fleet-fetch landing: index carried ``(tokens, layers)``
        blocks in the HOST tier under chain keys recomputed from the
        carried tokens (never trusting the peer's hashes), after a
        shape gate against this engine's pool. Import stops at the
        first malformed block — a chain is only as good as its
        prefix. Thread-safe: the host tier locks internally, and the
        engine thread only ever reads blocks it got back from its
        own match. Returns blocks actually inserted."""
        if self.host_tier is None:
            return 0
        p = self.config.page_size
        parent = _ROOT
        imported = 0
        for tokens, layers in blocks:
            block = tuple(int(t) for t in np.asarray(tokens).reshape(-1))
            if len(block) != p:
                break
            arrays = [np.asarray(a) for a in layers]
            if [tuple(a.shape) for a in arrays] != \
                    self._kv_leaf_shapes:
                break
            key = _block_key(parent, block)
            if self.host_tier.put(key, block, arrays, imported=True):
                imported += 1
            parent = key
        return imported

    def clear_prefix_cache(self) -> int:
        """Drop every cached prefix (idle pages return to the free
        list immediately; pinned ones when their slot retires).
        QUIESCED callers only — warmup teardown and tests: the index
        is engine-thread state, and this must not race a live
        admission."""
        return self.prefix.clear() if self.prefix is not None else 0

    def run_prefill(self, prompt: np.ndarray, *,
                    rng: Optional[np.ndarray] = None,
                    max_new_tokens: Optional[int] = None,
                    obs_ctx: Any = None,
                    timeout_s: float = 300.0
                    ) -> PrefillHandoff:
        """Run the B=1 prefill WITHOUT decoding: the prefill-role
        half of KV handoff. The returned handoff feeds
        ``submit(handoff=...)`` on this or ANY engine serving the
        same export — the adopt path makes the resumed decode bitwise
        equal to a local one.

        In prefix-cache mode (ISSUE 16) the prefill rides the ENGINE
        thread as a slot-bound prefill-only request: it matches and
        REGISTERS in the r15 prefix index (chunked across laps when
        ``prefill_chunk`` is set), which is what finally warms a
        prefill-role replica's cache — the old slot-less functional
        path re-paid every prefill and left the index cold. The call
        blocks up to ``timeout_s`` (bounded wait, serving
        discipline). Classic (left-layout) mode keeps the functional
        path: no prefix index exists to warm, and request-thread
        callability stays useful there."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if not 1 <= prompt.shape[0] <= self.config.max_prompt_len:
            raise ValueError(
                f"prompt length {prompt.shape[0]} outside "
                f"[1, {self.config.max_prompt_len}]")
        budget = (self.config.max_new_tokens if max_new_tokens is None
                  else int(max_new_tokens))
        if not 1 <= budget <= self.config.max_new_tokens:
            raise ValueError(
                f"max_new_tokens {budget} outside "
                f"[1, {self.config.max_new_tokens}]")
        key = self._next_key() if rng is None else np.asarray(rng)
        step_keys = np.asarray(jax.random.split(
            jnp.asarray(key, jnp.uint32), budget))
        length = int(prompt.shape[0])
        width = self._bucket(length)
        t0 = time.monotonic()

        def note_spans(program: str, block_width: int) -> None:
            # The prefill-role hop's engine work must join the
            # request's trace (ISSUE 15 satellite: before this, the
            # split path's first hop was span-less and its prefill
            # cost could only be inferred from the hop wall time).
            dur = time.monotonic() - t0
            self._note_compile(program, f"tokens[1,{block_width}]",
                               t0, dur,
                               link=span_args(obs_ctx))
            if TRACER.enabled and obs_ctx is not None:
                TRACER.record(
                    "engine_prefill", "engine", t0, dur,
                    span_args(obs_ctx, model=self.name,
                              prompt_len=length, handoff=True))

        if self.prefix is not None:
            # Prefix-cache engines prefill in the pad-0 layout (prompt
            # at [0, L), garbage right-pad masked by causality) so the
            # blob's pages adopt straight into the shared-page layout
            # AND carry the prompt ids for the adopter's index — the
            # warm-transfer half of the seam. The work itself runs as
            # a slot-bound prefill-only admission on the engine
            # thread, hitting and registering the prefix index.
            if self.kv.pages_for(length) > \
                    self.kv.allocator.num_pages - 1:
                raise ValueError(
                    f"prompt needs {self.kv.pages_for(length)} pages "
                    f"but the pool has only "
                    f"{self.kv.allocator.num_pages - 1}")
            stream = GenerateStream(budget, obs_ctx=obs_ctx)
            box: dict = {"handoff": None}
            req = _Request(
                prompt=prompt, step_keys=step_keys,
                max_new_tokens=budget, deadline=None, stream=stream,
                submitted_at=t0, tenant=tenancy.DEFAULT_TENANT,
                prefill_only=True, prefill_box=box)
            with self._cv:
                if self._closed:
                    raise RuntimeError("engine is stopped")
                self.scheduler.pending.append(req)
                self._cv.notify_all()
            self._ensure_thread()
            stream.result(timeout=timeout_s)  # raises on engine error
            handoff = box["handoff"]
            if handoff is None:
                raise RuntimeError(
                    "prefill-only request finished without a handoff")
            if TRACER.enabled and obs_ctx is not None:
                TRACER.record(
                    "engine_prefill", "engine", t0,
                    time.monotonic() - t0,
                    span_args(obs_ctx, model=self.name,
                              prompt_len=length, handoff=True))
            return handoff
        pad = width - length
        padded = np.zeros((1, width), np.int32)
        padded[0, pad:] = prompt
        carry, _ = _prefill_jit(
            self._model, self._params, jnp.asarray(padded),
            jnp.asarray(step_keys[0:1]), self._prefill_template,
            jnp.asarray([pad], jnp.int32),
            temperature=self.config.temperature,
            eos_id=self.config.eos_id, top_k=self.config.top_k,
            top_p=self.config.top_p)
        prefill_cache, first, _, done = carry
        handoff = PrefillHandoff(
            cache=jax.tree.map(np.asarray, prefill_cache),
            first_token=int(np.asarray(first)[0]),
            done=bool(np.asarray(done)[0]),
            prompt_len=length, prompt_width=width,
            max_new_tokens=budget, step_keys=step_keys)
        note_spans("prefill", width)
        return handoff

    def submit(self, prompt: Optional[np.ndarray] = None, *,
               rng: Optional[np.ndarray] = None,
               max_new_tokens: Optional[int] = None,
               deadline: Optional[float] = None,
               obs_ctx: Any = None,
               request_id: str = "",
               tenant: str = "",
               handoff: Optional[PrefillHandoff] = None,
               step_keys: Optional[np.ndarray] = None,
               kv_fetch_s: float = 0.0
               ) -> GenerateStream:
        """Queue one request; tokens stream on the returned handle.

        ``max_new_tokens`` may be LESS than the engine's configured
        budget (a short request retires early and frees its slot —
        the per-request knob the fixed-shape coalescer could never
        offer); ``rng`` is the request's sampling key ([2] — the same
        key reproduces the same tokens at B=1 through generate()).
        With ``handoff`` (KV handoff, role-split routing) the prompt's
        prefill already ran elsewhere: admission adopts the carried
        cache pages instead of prefilling, and ``prompt``/``rng``/
        ``max_new_tokens`` are taken FROM the handoff (a divergent
        caller budget would fork the rng schedule — rejected).

        With ``step_keys`` (mid-stream decode resume, ISSUE 13) the
        caller supplies the EXPLICIT remaining sampling schedule
        ([budget, 2] uint32) instead of an rng seed: ``prompt`` is the
        full resume context (original prompt + tokens already emitted
        on the dead replica), the budget is the schedule's length, and
        the prefill over the context reproduces the next token
        bitwise (K/V at position i is a pure function of tokens
        [0, i]; the schedule picks the same sample). The context may
        legally exceed ``max_prompt_len`` — the true bound is
        ``cache_size - budget``, the same total the original request
        fit in.

        Raises :class:`OverloadedError` /
        :class:`DeadlineExceededError` synchronously when admission
        control sheds the request."""
        if handoff is not None and step_keys is not None:
            raise ValueError("handoff and step_keys are mutually "
                             "exclusive (the handoff carries its own "
                             "key schedule)")
        if handoff is not None:
            if (max_new_tokens is not None
                    and int(max_new_tokens) != handoff.max_new_tokens):
                raise ValueError(
                    f"max_new_tokens {max_new_tokens} != handoff's "
                    f"{handoff.max_new_tokens} — the step-key "
                    f"schedule was derived at prefill time")
            layout = getattr(handoff, "layout", "left") or "left"
            expected = "right" if self.prefix is not None else "left"
            if layout != expected:
                # Adopting a left-padded cache into the pad-0 shared
                # layout (or vice versa) would place the prompt at the
                # wrong cache positions — reject with a clear error
                # (the server maps it to a 400; the proxy falls back
                # to the classic path during a mixed rollout).
                raise ValueError(
                    f"handoff layout {layout!r} incompatible with "
                    f"this engine's {expected!r} layout (prefix "
                    f"caching {'on' if expected == 'right' else 'off'})")
            max_bucket = self._bucket(self.config.max_prompt_len)
            if not 1 <= handoff.prompt_width <= max_bucket:
                raise ValueError(
                    f"handoff prompt_width {handoff.prompt_width} "
                    f"outside [1, {max_bucket}]")
            if not 1 <= handoff.prompt_len <= handoff.prompt_width:
                raise ValueError(
                    f"handoff prompt_len {handoff.prompt_len} outside "
                    f"[1, width {handoff.prompt_width}]")
            budget = int(handoff.max_new_tokens)
            if len(np.asarray(handoff.step_keys)) != budget:
                raise ValueError(
                    f"handoff carries {len(handoff.step_keys)} step "
                    f"keys for a {budget}-token budget")
            if handoff.prompt_tokens is not None:
                prompt = np.asarray(handoff.prompt_tokens,
                                    np.int32).reshape(-1)
                if prompt.shape[0] != handoff.prompt_len:
                    raise ValueError(
                        f"handoff carries {prompt.shape[0]} prompt "
                        f"tokens but claims prompt_len "
                        f"{handoff.prompt_len}")
            else:
                prompt = np.zeros((handoff.prompt_len,), np.int32)
        elif step_keys is not None:
            # Mid-stream resume continuation: the context is the
            # original prompt + tokens already emitted elsewhere, and
            # the remaining schedule IS the budget.
            if rng is not None:
                raise ValueError("step_keys and rng are mutually "
                                 "exclusive (the schedule is explicit)")
            prompt = np.asarray(prompt, np.int32).reshape(-1)
            step_keys = np.ascontiguousarray(
                np.asarray(step_keys, np.uint32).reshape(-1, 2))
            budget = len(step_keys)
            if (max_new_tokens is not None
                    and int(max_new_tokens) != budget):
                raise ValueError(
                    f"max_new_tokens {max_new_tokens} != the "
                    f"{budget}-key resume schedule")
            limit = self._model.cache_size - budget
            if not 1 <= prompt.shape[0] <= limit:
                raise ValueError(
                    f"resume context length {prompt.shape[0]} outside "
                    f"[1, {limit}] (cache_size {self._model.cache_size}"
                    f" - {budget} remaining tokens)")
        else:
            prompt = np.asarray(prompt, np.int32).reshape(-1)
            if not 1 <= prompt.shape[0] <= self.config.max_prompt_len:
                raise ValueError(
                    f"prompt length {prompt.shape[0]} outside "
                    f"[1, {self.config.max_prompt_len}]")
            budget = (self.config.max_new_tokens
                      if max_new_tokens is None else int(max_new_tokens))
        if not 1 <= budget <= self.config.max_new_tokens:
            raise ValueError(
                f"max_new_tokens {budget} outside "
                f"[1, {self.config.max_new_tokens}]")
        if self._closed:
            raise RuntimeError("engine is stopped")
        # A worst-case reservation that can NEVER fit the pool would
        # sit at the FIFO head forever (admission holds the line for
        # the head) — fail it at submit, not by hanging the queue.
        # (The worst case assumes NO prefix hit: a matched prefix can
        # be evicted between submit and admission.)
        if handoff is not None and self.prefix is None:
            width = handoff.prompt_width
        else:
            width = self._prompt_width(int(prompt.shape[0]))
        need = self.kv.pages_for(width + budget)
        usable = self.kv.allocator.num_pages - 1
        if need > usable:
            raise ValueError(
                f"request needs {need} pages worst-case "
                f"(prompt bucket {width} + "
                f"{budget} new tokens at page_size "
                f"{self.kv.page_size}) but the pool has only "
                f"{usable} — raise engine_num_pages or lower the "
                f"request budget")
        tenant = tenant or tenancy.DEFAULT_TENANT
        if self.scheduler.queue_depth() >= self.config.queue_capacity:
            # Attributable shed (ISSUE 14 satellite): global depth
            # alone can't tell an operator WHOSE burst filled the
            # queue — name the submitting tenant's own depth and the
            # top queue holder so the 503 (and batch_stats) point at
            # the noisy neighbor, not just at "full".
            depths = self.scheduler.tenant_depths()
            top = max(depths.items(), key=lambda kv: kv[1],
                      default=(tenant, 0))
            self._m_shed.inc()
            tenancy.note_shed(tenant, "overload")
            raise OverloadedError(
                f"engine queue full "
                f"({self.config.queue_capacity} pending; tenant "
                f"{tenant!r} holds {depths.get(tenant, 0)}, top "
                f"holder {top[0]!r} with {top[1]})",
                retry_after_s=self.estimated_ttft_s())
        now = time.monotonic()
        if deadline is not None:
            remaining = deadline - now
            if remaining <= 0:
                raise DeadlineExceededError(
                    "deadline expired before submit")
            est = self.estimated_ttft_s()
            if handoff is not None:
                # A page-adopt admission skips ITS OWN prefill (the
                # expensive term); pricing it anyway would shed
                # adoptable requests and force the proxy to redo the
                # whole prefill on the classic path — strictly worse
                # than admitting.
                est = max(0.0, est - self._prefill_est.estimate_s())
            if est > remaining * ADMISSION_SAFETY:
                self._m_shed.inc()
                tenancy.note_shed(tenant, "overload")
                raise OverloadedError(
                    f"engine overloaded: estimated time-to-first-"
                    f"token {est * 1e3:.0f}ms exceeds remaining "
                    f"budget {remaining * 1e3:.0f}ms",
                    retry_after_s=est)
        if handoff is not None:
            step_keys = np.asarray(handoff.step_keys)
        elif step_keys is None:
            key = self._next_key() if rng is None else np.asarray(rng)
            step_keys = np.asarray(jax.random.split(
                jnp.asarray(key, jnp.uint32), budget))
        stream = GenerateStream(budget, obs_ctx=obs_ctx)
        if handoff is None or handoff.prompt_tokens is not None:
            # The peer-resume context (serving/server.py emits it as
            # an SSE ``resume`` event when asked): a left-layout
            # handoff's placeholder prompt is NOT resumable — zeros
            # are not the context.
            # Reference, not copy: the request's prompt array is
            # never mutated (prefill writes into its own padded
            # block), and _Request holds the same reference anyway.
            stream.resume_ctx = {
                "prompt": prompt,
                "step_keys": np.asarray(step_keys),
                "max_new_tokens": budget,
            }
        req = _Request(prompt=prompt, step_keys=step_keys,
                       max_new_tokens=budget, deadline=deadline,
                       stream=stream, submitted_at=now,
                       request_id=request_id, tenant=tenant,
                       handoff=handoff,
                       kv_fetch_s=max(0.0, float(kv_fetch_s)))
        with self._cv:
            if self._closed:
                raise RuntimeError("engine is stopped")
            self.scheduler.pending.append(req)
            self._cv.notify_all()
        self._ensure_thread()
        return stream

    def _ensure_thread(self) -> None:
        with self._cv:
            if self._thread is None and not self._closed:
                self._thread = threading.Thread(
                    target=self._loop, name=f"engine-{self.name}",
                    daemon=True)
                self._thread.start()

    def stop(self) -> None:
        with self._cv:
            self._closed = True
            thread, self._thread = self._thread, None
            self._cv.notify_all()
        still_running = False
        if thread is not None:
            thread.join(timeout=10)
            still_running = thread.is_alive()
        err = RuntimeError("engine shutting down")
        if still_running:
            # The engine thread is mid-slice (a cold compile can take
            # tens of seconds on-chip) and still owns the slot/
            # allocator state — racing _retire against it corrupts the
            # free list. Fail the streams (their locks are per-stream,
            # safe from any thread) and leave the device-side
            # bookkeeping to die with the object.
            logger.warning(
                "engine %s thread still busy at stop(); failing "
                "streams without touching slot state", self.name)
            for slot in self.scheduler.active_slots():
                slot.request.stream._fail(err)
        else:
            for slot in self.scheduler.active_slots():
                self._retire(slot, "shutdown", error=err)
        for req in list(self.scheduler.pending):
            req.stream._fail(err)
        self.scheduler.pending.clear()
        if self.prefix is not None:
            if not still_running:
                # Drain the cache so the pool releases cleanly (the
                # acceptance invariant: a stopped engine holds zero
                # resident pages). A busy thread still owns the
                # allocator — leave custody to die with the object.
                self.prefix.clear()
            # Callback clears are pure registry ops — run them even
            # when the thread is busy, or the registry-lifetime
            # gauges pin the dead engine (params + page pool) and
            # keep exporting its stale stats.
            self._m_prefix_evicted.clear_function(self)
            self._g_prefix_pages.clear_function(self.prefix)
        if self.host_tier is not None:
            if not still_running:
                self.host_tier.clear()
            self._m_host_spilled.clear_function(self)
            self._m_host_readopted.clear_function(self)
            self._m_host_evicted.clear_function(self)
            self._g_host_bytes.clear_function(self.host_tier)
            self._g_host_blocks.clear_function(self.host_tier)
        if self._spec_on:
            self._g_spec_rate.clear_function(self)
        self._g_slots.clear_function(self.scheduler)
        self._g_queue.clear_function(self.scheduler)
        self._g_pages.clear_function(self.kv.allocator)
        self._g_occupancy.clear_function(self)

    def stats(self) -> dict:
        alloc = self.kv.allocator
        out = {
            "slots": self.config.num_slots,
            "active_slots": self.scheduler.occupancy(),
            "queue_depth": self.scheduler.queue_depth(),
            "admitted": self.scheduler.admitted,
            "retired": dict(self.scheduler.retired_by),
            "free_pages": alloc.free_pages,
            "reserved_pages": alloc.reserved_pages,
            "retained_pages": alloc.retained_pages,
            "total_pages": alloc.num_pages - 1,
            "page_size": self.kv.page_size,
            "page_occupancy": round(self.page_occupancy(), 4),
            "est_ttft_ms": round(self.estimated_ttft_s() * 1e3, 3),
            # Profiling hooks (ISSUE 15): decode slices run and
            # distinct jit programs traced (white-box for the
            # compile-event spans; a growing count at steady state IS
            # a recompile storm).
            "slices": self._slices,
            "compiled_programs": len(self._compile_seen),
            # Per-tenant queue depths (ISSUE 14): the attribution for
            # queue-full sheds, rides healthz → dashboard/autoscaler
            # (capped: top-K + 'other', like every reporting surface).
            "tenant_queue_depths": tenancy.cap_depths(
                self.scheduler.tenant_depths()),
        }
        if self.prefix is not None:
            out["prefix_cache"] = self.prefix.stats()
        if self._spec_on:
            # The acceptance economics: verify_forwards is what the
            # "< 1 verifier forwards per emitted token" bench claim
            # divides by.
            out["spec"] = {
                "k": self.config.speculate_tokens,
                "rounds": self._spec_rounds,
                "verify_forwards": self._spec_rounds,
                "drafted_tokens": self._spec_drafted_total,
                "accepted_tokens": self._spec_accepted_total,
                "acceptance_rate": round(
                    self.spec_acceptance_rate(), 4),
            }
        if self.config.prefill_chunk:
            out["prefill_chunk"] = self.config.prefill_chunk
        if self.host_tier is not None:
            # The tiered-KV block /healthz saturation (and through
            # it the dashboard's per-tier Pages breakdown and the
            # autoscaler's host-occupancy sample) reads.
            out["kv_tier"] = {
                "host": self.host_tier.stats(),
                "fetch_hits": self._kv_fetch_hits,
                "fetch_misses": self._kv_fetch_misses,
                "fetch_errors": self._kv_fetch_errors,
                "fetched_blocks": self._kv_fetched_blocks_total,
            }
        return out

    # -- engine thread ---------------------------------------------------

    def _drain_control(self) -> None:
        """Run every posted control closure (engine thread). The
        closures carry their own error boxes (_run_on_engine); the
        belt-and-braces except keeps a broken closure from killing
        innocent in-flight slots via _loop's handler."""
        while True:
            with self._cv:
                if not self._control:
                    return
                fn = self._control.popleft()
            try:
                fn()
            except Exception:  # noqa: BLE001 — already boxed
                logger.exception("engine control op failed")

    def _loop(self) -> None:
        while True:
            with self._cv:
                if self._closed:
                    return
                if (not self.scheduler.pending
                        and not self.scheduler.active_slots()
                        and not self._control):
                    self._cv.wait(timeout=0.05)
                    continue
            try:
                self._drain_control()
                self._expire()
                self._admit()
                self._advance_prefills()
                if self.scheduler.decoding_slots():
                    self._run_slice()
                elif self.scheduler.prefilling_slots():
                    # Prefill-only laps: work advanced, no nap.
                    pass
                else:
                    # Queued-but-unadmittable head with nothing
                    # decoding: bounded nap instead of a hot spin
                    # (only expiry/cancel can change the picture, and
                    # _expire reruns each lap).
                    with self._cv:
                        if not self._closed:
                            self._cv.wait(timeout=0.05)
            except Exception as e:  # noqa: BLE001 — fail the streams,
                # keep the engine alive for later requests.
                logger.exception("engine slice failed")
                for slot in self.scheduler.active_slots():
                    self._retire(slot, "error", error=e)

    def _note_compile(self, program: str, shapes: str,
                      start_s: float, dur_s: float,
                      link: Optional[dict] = None) -> None:
        """Record the engine_compile span for a first-seen program/
        shape key. ``shapes`` doubles as the cache key's shape half —
        it names the abstract shapes the trace specialized on.
        ``link`` (a span_args dict) attributes a request-triggered
        compile to THAT request's trace, so a cold-start waterfall
        literally contains its compile events; slice compiles (no
        single owner) stay documented roots."""
        key = (program, shapes)
        if key in self._compile_seen:
            return
        self._compile_seen.add(key)
        if TRACER.enabled:
            args = {"model": self.name, "program": program,
                    "shapes": shapes}
            for k in ("request_id", "trace_id", "parent_id", "leg"):
                if link and k in link:
                    args[k] = link[k]
            TRACER.record("engine_compile", "engine", start_s, dur_s,
                          args)

    def _bucket(self, n: int) -> int:
        return prompt_bucket(n, self.config.max_prompt_len,
                             self.config.prompt_buckets)

    def _prompt_width(self, length: int) -> int:
        """Prefill block width for a ``length``-token context: exact
        in the pad-0 prefix layout, bucketed classically — except a
        resume continuation longer than ``max_prompt_len`` (legal:
        its true bound is the cache) takes its exact width, because
        ``prompt_bucket`` CLAMPS to max_prompt_len and a clamped
        width would truncate the context."""
        if self.prefix is not None:
            return length
        if length > self.config.max_prompt_len:
            return length
        return self._bucket(length)

    def _budget_pages(self, req: _Request) -> int:
        if req.handoff is not None and self.prefix is None:
            width = req.handoff.prompt_width
        else:
            width = self._prompt_width(len(req.prompt))
        # Prefill-only requests never decode: the prompt's pages are
        # the whole budget.
        new_tokens = 0 if req.prefill_only else req.max_new_tokens
        return self.kv.pages_for(width + new_tokens)

    def _tail_width(self, length: int, start: int) -> int:
        """Static block width for the continuation prefill of prompt
        tokens ``[start, length)``: the shared prompt-bucket policy,
        except never past the model's cache end — the scalar append's
        ``dynamic_update_slice`` would CLAMP an overhanging write
        backwards over the shared prefix. An overshooting bucket
        falls back to the exact tail length (one extra compile in a
        rare corner; the bucketed widths cover steady state). A
        resume-continuation tail longer than ``max_prompt_len`` takes
        its exact width — ``prompt_bucket`` clamps and a clamped
        block would truncate the context."""
        if length - start > self.config.max_prompt_len:
            return length - start
        width = self._bucket(length - start)
        if start + width > self._model.cache_size:
            width = length - start
        return width

    def _expire(self) -> None:
        # Under _cv: expired_pending() swaps the pending deque for a
        # rebuilt one, and submit() appends under _cv — an unlocked
        # swap would silently drop a concurrently submitted request.
        with self._cv:
            expired = self.scheduler.expired_pending()
            dead = self.scheduler.pending.remove_if(
                lambda r: r.stream.cancelled)
        for req in expired:
            req.stream._fail(DeadlineExceededError(
                "deadline expired while queued for a slot"))
            _M_RETIRED.labels(self.name, "expired_queued").inc()
            tenancy.note_expired(req.tenant or tenancy.DEFAULT_TENANT)
        for req in dead:
            # Client hung up while still queued: never burn a prefill
            # or a slot on it.
            req.stream._fail(RuntimeError(
                "stream cancelled by the client"))
            _M_RETIRED.labels(self.name, "cancelled_queued").inc()
        for slot in self.scheduler.expired_slots():
            self._retire(slot, "deadline", error=DeadlineExceededError(
                f"deadline expired mid-decode after "
                f"{slot.emitted} token(s)"))
        for slot in self.scheduler.active_slots():
            if slot.request.stream.cancelled:
                self._retire(slot, "cancelled", error=RuntimeError(
                    "stream cancelled by the client"))

    def _admit(self) -> None:
        while True:
            if self.prefix is not None:
                admitted = self._admit_one_prefix()
            else:
                req = self.scheduler.next_admittable(
                    self._budget_pages)
                admitted = req is not None
                if admitted:
                    self._prefill_and_bind(req)
            if not admitted:
                return

    def _admit_one_prefix(self) -> bool:
        """One admission attempt in prefix-cache mode: in fair-
        queueing order, match each tenant head's prompt, pin the
        matched resident pages, and reserve only the private
        remainder; the first head whose reservation fits admits. A
        failed reservation UNPINS before moving on — a head never
        deadlocks the queue against its own pins (every page it waits
        for is then either free, evictable, or held by a live slot
        that will retire), and it holds the line for ITS tenant only
        (unchanged, no fair-share charge — it keeps first claim on
        freed pages) while other tenants' heads still admit."""
        sched = self.scheduler
        if not sched.pending or not sched.has_free_slot():
            return False
        for i, head in enumerate(sched.pending.heads()):
            total = self._budget_pages(head)
            match = self.prefix.match(head.prompt)
            if head.handoff is not None:
                # A handoff arrives with its whole prefill —
                # full-block sharing still saves pages, but a
                # boundary fork has nothing to copy that the carried
                # cache doesn't already hold, and a placeholder
                # prompt (no tokens in the blob) must not "match"
                # zeros.
                entries = (match.entries
                           if head.handoff.prompt_tokens is not None
                           else [])
                match = PrefixMatch(
                    entries=entries, fork=None, fork_len=0,
                    matched=len(entries) * self.kv.page_size)
            match = self.prefix.pin(match)
            if not self.kv.allocator.reserve(
                    total - len(match.entries)):
                self.prefix.unpin(match)
                if i == 0 and sched.head_blocked(head):
                    # Starvation guard (see SlotScheduler): the same
                    # fair-first head has now been skipped enough —
                    # hold the whole line so freed pages accumulate
                    # for it instead of leaking to smaller requests.
                    return False
                continue  # this tenant's line holds; try the next
            if i == 0:
                sched.head_unblocked()
            sched.pending.pop_head(head)
            if head.prefill_only or self._chunkable(head, match):
                # Slot-bound incremental prefill (ISSUE 16): long
                # tails feed one chunk per engine lap; prefill-only
                # requests ALWAYS take this path (with chunking off
                # the whole tail is one "chunk" — same program, one
                # lap) so their pages register in the prefix index.
                self._bind_chunked_prefill(head, match)
            else:
                self._prefill_and_bind_prefix(head, match)
            return True
        return False

    def _chunkable(self, req: _Request, match: "PrefixMatch") -> bool:
        return (self.config.prefill_chunk > 0
                and req.handoff is None
                and len(req.prompt) - match.matched
                > self.config.prefill_chunk)

    def _prefill_and_bind(self, req: _Request) -> None:
        t0 = time.monotonic()
        length = len(req.prompt)
        if req.handoff is not None:
            # KV handoff: the prefill ran on another replica — adopt
            # its cache pages instead of recomputing them. The carried
            # cache/step-keys make the resumed decode bitwise equal to
            # a local run (tests/test_role_routing.py pins it).
            width = req.handoff.prompt_width
            pad = width - req.handoff.prompt_len
            prefill_cache = req.handoff.cache
            first = int(req.handoff.first_token)
            done = bool(req.handoff.done)
        else:
            width = self._prompt_width(length)
            pad = width - length
        prompt = np.zeros((1, width), np.int32)
        prompt[0, pad:] = req.prompt
        cache = self._prefill_template
        try:
            if req.handoff is None:
                carry, _ = _prefill_jit(
                    self._model, self._params, jnp.asarray(prompt),
                    jnp.asarray(req.step_keys[0:1]), cache,
                    jnp.asarray([pad], jnp.int32),
                    temperature=self.config.temperature,
                    eos_id=self.config.eos_id, top_k=self.config.top_k,
                    top_p=self.config.top_p)
                prefill_cache, first, _, done = carry
                first = int(np.asarray(first)[0])
                done = bool(np.asarray(done)[0])
        except Exception as e:  # noqa: BLE001 — XLA OOM / compile
            # The request was popped WITH a reservation
            # (next_admittable); letting this propagate to _loop's
            # handler would leak that reservation forever, leave the
            # stream with no terminal event, and retire every
            # innocent in-flight slot with this error.
            logger.exception("prefill failed; shedding the request")
            self.kv.allocator.unreserve(self._budget_pages(req))
            _M_RETIRED.labels(self.name, "error").inc()
            req.stream._fail(e)
            return
        budget_pages = self._budget_pages(req)
        slot = self.scheduler.bind(
            req, prompt_width=width, pad_len=pad, first_token=first,
            done=done, budget_pages=budget_pages,
            deadline=req.deadline)
        slot.allocated_pages = self.kv.adopt(
            slot.index, prefill_cache, width, budget_pages)
        t1 = time.monotonic()
        slot.queue_s = max(0.0, t0 - req.submitted_at)
        slot.prefill_s = t1 - t0
        if req.handoff is None:
            self._note_compile("prefill", f"tokens[1,{width}]",
                               t0, t1 - t0,
                               link=self._span_args(req))
            # Only REAL prefills feed the estimator: adopt times are
            # sub-millisecond, and letting them in would collapse the
            # TTFT estimate on decode-role replicas — admission would
            # stop shedding direct requests that can't meet their
            # deadlines, and the autoscaler's engine queue pricing
            # (queue_depth × est_ttft_ms) would read a saturated
            # queue as nearly free.
            self._prefill_est.observe(t1 - t0)
        self._m_admitted.inc()
        ctx = req.stream.obs_ctx
        self._m_ttft.observe(t1 - req.submitted_at,
                             trace_id=ctx.trace_id if ctx else None)
        tenancy.observe_ttft(req.tenant or tenancy.DEFAULT_TENANT,
                             t1 - req.submitted_at)
        if TRACER.enabled:
            TRACER.record("engine_prefill", "engine", t0, t1 - t0,
                          self._span_args(req, slot=slot.index,
                                          prompt_len=length))
        self._emit_token(slot, first)
        if slot.done or slot.remaining == 0:
            self._retire(slot, "eos" if slot.done else "budget")
        else:
            self._draft_prefill(slot, req)

    def _prefill_and_bind_prefix(self, req: _Request,
                                 match: "PrefixMatch") -> None:
        """Prefix-mode admission: the caller (``_admit_one_prefix``)
        already pinned ``match``'s pages and reserved the private
        remainder. Shared full blocks enter the slot's page table
        as-is; a partially matched boundary page is forked
        copy-on-write (its common head rows ride the gathered B=1
        cache into a PRIVATE page, because the tail prefill and the
        decode will write past them); only the unmatched tail is
        prefilled. Bitwise equal to a cold prefill: same tokens at
        the same positions with the same step-key schedule, and the
        K/V at position i is a pure function of tokens [0, i]."""
        t0 = time.monotonic()
        length = len(req.prompt)
        budget_pages = self._budget_pages(req)
        shared = match.shared_pages
        m = match.matched
        fork_pinned = match.fork is not None
        try:
            if req.handoff is not None:
                prefill_cache = req.handoff.cache
                first = int(req.handoff.first_token)
                done = bool(req.handoff.done)
            else:
                if m > 0:
                    # Host-tier blocks (ISSUE 20) continue the chain
                    # past the shared HBM pages: their table rows
                    # gather as null-page zeros, then the host copies
                    # are spliced over those rows — byte-equal to
                    # having kept the pages, so the tail prefill (and
                    # the decode) is bitwise the cold run's.
                    host_blocks = list(match.host_entries)
                    page_row = list(shared) + [0] * len(host_blocks)
                    if match.fork is not None:
                        page_row.append(match.fork.page)
                    cache = self.kv.gather_prefix_cache(
                        page_row, self._prefill_template, m)
                    if host_blocks:
                        cache = splice_host_blocks(
                            cache,
                            [hb.layers for hb in host_blocks],
                            len(shared), self.kv.page_size)
                        self.host_tier.note_readopted(
                            len(host_blocks))
                    if fork_pinned:
                        # The fork copy is dispatched (device ops
                        # serialize in thread order); the donor page
                        # is not this slot's to keep.
                        self.prefix.unpin_fork(match)
                        fork_pinned = False
                else:
                    cache = self._prefill_template
                width = self._tail_width(length, m)
                block = np.zeros((1, width), np.int32)
                block[0, :length - m] = req.prompt[m:]
                cache, first_a, done_a = _prefill_ctx_jit(
                    self._model, self._params, jnp.asarray(block),
                    cache, jnp.asarray(m, jnp.int32),
                    jnp.asarray(length - m - 1, jnp.int32),
                    jnp.asarray(req.step_keys[0:1]),
                    temperature=self.config.temperature,
                    eos_id=self.config.eos_id,
                    top_k=self.config.top_k,
                    top_p=self.config.top_p)
                prefill_cache = cache
                first = int(np.asarray(first_a)[0])
                done = bool(np.asarray(done_a)[0])
        except Exception as e:  # noqa: BLE001 — XLA OOM / compile
            # Same contract as the classic path: the popped request
            # holds a reservation AND pins — leak neither, fail only
            # its own stream.
            logger.exception("prefix prefill failed; shedding the "
                             "request")
            self.kv.allocator.unreserve(budget_pages - len(shared))
            self.prefix.unpin(match, include_fork=fork_pinned)
            _M_RETIRED.labels(self.name, "error").inc()
            req.stream._fail(e)
            return
        slot = self.scheduler.bind(
            req, prompt_width=length, pad_len=0, first_token=first,
            done=done, budget_pages=budget_pages,
            deadline=req.deadline)
        slot.allocated_pages = self.kv.adopt(
            slot.index, prefill_cache, length, budget_pages,
            shared_pages=shared)
        # Index this prompt's resident pages (new private blocks, plus
        # the boundary partial). A handoff registration is the warm
        # transfer landing: the pages this replica never prefilled
        # become matchable for the next request.
        if req.handoff is None or req.handoff.prompt_tokens is not None:
            self.prefix.register(
                req.prompt,
                self.kv.tables[slot.index,
                               :slot.allocated_pages].tolist())
        t1 = time.monotonic()
        slot.queue_s = max(0.0, t0 - req.submitted_at)
        slot.prefill_s = t1 - t0
        if req.handoff is None:
            self._note_compile("prefill_ctx", f"tokens[1,{width}]",
                               t0, t1 - t0,
                               link=self._span_args(req))
            if m > 0:
                self.prefix.hits += 1
                self.prefix.saved_tokens_total += m
                self._m_prefix_hits.inc()
                self._m_prefix_saved.observe(float(m))
            else:
                self.prefix.misses += 1
                self._m_prefix_misses.inc()
                # Only full (cold) prefills feed the estimator — a
                # tail prefill's cost scales with the UNMATCHED length
                # and would read a warm cache as a fast prefill for
                # cold requests (same reasoning as the adopt-time
                # exclusion below).
                self._prefill_est.observe(t1 - t0)
        self._m_admitted.inc()
        ctx = req.stream.obs_ctx
        self._m_ttft.observe(t1 - req.submitted_at,
                             trace_id=ctx.trace_id if ctx else None)
        tenancy.observe_ttft(req.tenant or tenancy.DEFAULT_TENANT,
                             t1 - req.submitted_at)
        if TRACER.enabled:
            TRACER.record("engine_prefill", "engine", t0, t1 - t0,
                          self._span_args(req, slot=slot.index,
                                          prompt_len=length,
                                          prefix_matched=m))
        self._emit_token(slot, first)
        if slot.done or slot.remaining == 0:
            self._retire(slot, "eos" if slot.done else "budget")
        else:
            self._draft_prefill(slot, req)

    def _bind_chunked_prefill(self, req: _Request,
                              match: "PrefixMatch") -> None:
        """Admit a prompt WITHOUT running its prefill yet: the slot
        binds in the PREFILLING state holding the reservation and the
        pinned prefix match; :meth:`_advance_prefills` feeds one
        page-aligned chunk per engine lap. Like the one-shot path,
        the matched prefix (plus a boundary fork) is gathered into
        the accumulating B=1 cache up front — the fork's donor page
        is unpinned as soon as the copy is dispatched, and ``match``
        is narrowed so a mid-prefill retire can blanket-unpin the
        entries without double-freeing the fork."""
        t0 = time.monotonic()
        m = match.matched
        budget_pages = self._budget_pages(req)
        fork_pinned = match.fork is not None
        try:
            if m > 0:
                # Same gather + host-splice as the one-shot path: the
                # accumulating B=1 cache starts with every matched
                # tier's rows in place, and the chunks append past
                # them.
                host_blocks = list(match.host_entries)
                page_row = list(match.shared_pages) + \
                    [0] * len(host_blocks)
                if match.fork is not None:
                    page_row.append(match.fork.page)
                cache = self.kv.gather_prefix_cache(
                    page_row, self._prefill_template, m)
                if host_blocks:
                    cache = splice_host_blocks(
                        cache, [hb.layers for hb in host_blocks],
                        len(match.shared_pages), self.kv.page_size)
                    self.host_tier.note_readopted(len(host_blocks))
                if fork_pinned:
                    self.prefix.unpin_fork(match)
                    fork_pinned = False
                    match = dataclasses.replace(match, fork=None,
                                                fork_len=0)
            else:
                cache = self._prefill_template
        except Exception as e:  # noqa: BLE001 — XLA OOM / compile
            logger.exception("chunked-prefill admission failed; "
                             "shedding the request")
            self.kv.allocator.unreserve(
                budget_pages - len(match.entries))
            self.prefix.unpin(match, include_fork=fork_pinned)
            _M_RETIRED.labels(self.name, "error").inc()
            req.stream._fail(e)
            return
        slot = self.scheduler.bind_prefilling(
            req, prefill_pos=m, prefill_cache=cache,
            prefill_match=match, budget_pages=budget_pages,
            deadline=req.deadline)
        slot.queue_s = max(0.0, t0 - req.submitted_at)

    def _advance_prefills(self) -> None:
        """Feed ONE chunk to every prefilling slot — one per engine
        lap, so a long prompt's prefill interleaves with decode
        slices instead of stalling them (the chunk is the prefill's
        slice budget). Chunks run at a fixed [1, chunk] width (one
        compile); the final tail takes the shared bucket policy, the
        same program widths the one-shot path uses."""
        chunk = self.config.prefill_chunk
        for slot in self.scheduler.prefilling_slots():
            req = slot.request
            length = len(req.prompt)
            pos = slot.prefill_pos
            remaining = length - pos
            # chunk == 0 only for prefill-only admissions with
            # chunking disabled: the whole tail is one chunk, which
            # makes this lap bitwise the old one-shot prefill.
            step = chunk if chunk else remaining
            final = remaining <= step
            t0 = time.monotonic()
            try:
                if final:
                    width = self._tail_width(length, pos)
                    block = np.zeros((1, width), np.int32)
                    block[0, :remaining] = req.prompt[pos:]
                    last_col = remaining - 1
                else:
                    width = step
                    block = np.asarray(
                        req.prompt[pos:pos + step], np.int32
                    ).reshape(1, -1)
                    last_col = width - 1
                cache, first_a, done_a = _prefill_ctx_jit(
                    self._model, self._params, jnp.asarray(block),
                    slot.prefill_cache, jnp.asarray(pos, jnp.int32),
                    jnp.asarray(last_col, jnp.int32),
                    jnp.asarray(req.step_keys[0:1]),
                    temperature=self.config.temperature,
                    eos_id=self.config.eos_id,
                    top_k=self.config.top_k,
                    top_p=self.config.top_p)
                cache = jax.block_until_ready(cache)
            except Exception as e:  # noqa: BLE001 — fail only this
                # slot; its prefilling retire path unwinds the pins
                # and reservation.
                logger.exception("chunk prefill failed")
                self._retire(slot, "error", error=e)
                continue
            dur = time.monotonic() - t0
            slot.prefill_cache = cache
            slot.prefill_pos = pos + (remaining if final else step)
            slot.prefill_s += dur
            self._note_compile("prefill_ctx", f"tokens[1,{width}]",
                               t0, dur, link=self._span_args(req))
            if final:
                self._finish_chunked_prefill(
                    slot, first=int(np.asarray(first_a)[0]),
                    done=bool(np.asarray(done_a)[0]))

    def _finish_chunked_prefill(self, slot: Slot, *, first: int,
                                done: bool) -> None:
        """Last chunk landed: adopt the accumulated cache into pages,
        register the prompt in the prefix index, and either join the
        decode batch (:meth:`SlotScheduler.finish_prefill`) or — for
        a prefill-only request — package the handoff and retire."""
        req = slot.request
        match = slot.prefill_match
        m = match.matched
        shared = match.shared_pages
        length = len(req.prompt)
        try:
            allocated = self.kv.adopt(
                slot.index, slot.prefill_cache, length,
                slot.budget_pages, shared_pages=shared)
        except Exception as e:  # noqa: BLE001 — the prefilling
            # retire branch unpins the match and unreserves.
            logger.exception("chunked-prefill adopt failed")
            self._retire(slot, "error", error=e)
            return
        # From here the slot owns its pages like any bound slot: the
        # pins transferred into table refs, release_slot unwinds.
        SlotScheduler.finish_prefill(slot, prompt_width=length,
                                     first_token=first, done=done)
        slot.allocated_pages = allocated
        self.prefix.register(
            req.prompt,
            self.kv.tables[slot.index, :allocated].tolist())
        t1 = time.monotonic()
        if m > 0:
            self.prefix.hits += 1
            self.prefix.saved_tokens_total += m
            self._m_prefix_hits.inc()
            self._m_prefix_saved.observe(float(m))
        else:
            self.prefix.misses += 1
            self._m_prefix_misses.inc()
            # Deliberately NOT fed to the prefill estimator: a
            # chunked prefill's wall time spans several laps with
            # decode slices interleaved — it would price one-shot
            # TTFT off multi-lap wall.
        self._m_admitted.inc()
        if req.prefill_only:
            self._finish_prefill_handoff(slot, first=first, done=done)
            return
        ctx = req.stream.obs_ctx
        self._m_ttft.observe(t1 - req.submitted_at,
                             trace_id=ctx.trace_id if ctx else None)
        tenancy.observe_ttft(req.tenant or tenancy.DEFAULT_TENANT,
                             t1 - req.submitted_at)
        if TRACER.enabled:
            TRACER.record(
                "engine_prefill", "engine", t1 - slot.prefill_s,
                slot.prefill_s,
                self._span_args(req, slot=slot.index,
                                prompt_len=length, prefix_matched=m,
                                chunked=True))
        self._emit_token(slot, first)
        if slot.done or slot.remaining == 0:
            self._retire(slot, "eos" if slot.done else "budget")
        else:
            self._draft_prefill(slot, req)

    def _finish_prefill_handoff(self, slot: Slot, *, first: int,
                                done: bool) -> None:
        """Prefill-only completion: gather the slot's (now adopted
        and prefix-registered) pages back into a contiguous B=1 cache
        for the :class:`PrefillHandoff`, hand it to the waiting
        ``run_prefill`` caller, and retire the slot. Positions past
        the prompt in the tail page gather as zeros where the old
        functional path carried right-pad garbage — both are dead
        cells the adopting decode overwrites or masks, so the resumed
        decode stays bitwise."""
        req = slot.request
        length = len(req.prompt)
        page_row = self.kv.tables[
            slot.index, :slot.allocated_pages].tolist()
        cache = self.kv.gather_prefix_cache(
            page_row, self._prefill_template, length)
        req.prefill_box["handoff"] = PrefillHandoff(
            cache=jax.tree.map(np.asarray, cache),
            first_token=first, done=done,
            prompt_len=length, prompt_width=length,
            max_new_tokens=req.max_new_tokens,
            step_keys=np.asarray(req.step_keys),
            layout="right",
            prompt_tokens=np.asarray(req.prompt, np.int32).copy())
        self._retire(slot, "prefill_handoff")

    def _draft_prefill(self, slot: Slot, req: _Request) -> None:
        """Fill the slot's draft-cache row with the DRAFT model's
        prompt K/V, in the same layout the verifier's slot uses, so
        the first draft step continues from ``write_pos``. The draft
        pays its full prompt every admission (no draft-side prefix
        cache — the draft is llama-test-sized, the prefill is cheap
        relative to one saved verifier forward). A left-layout
        handoff carries no prompt ids: the row stays stale, which is
        CORRECT but useless — drafts become junk, acceptance goes to
        0, and the output is still bitwise because targets never
        depend on drafts."""
        if not self._spec_on:
            return
        if req.handoff is not None and req.handoff.prompt_tokens is \
                None:
            return
        length = len(req.prompt)
        t0 = time.monotonic()
        if self.prefix is not None:
            width = self._tail_width(length, 0)
            block = np.zeros((1, width), np.int32)
            block[0, :length] = req.prompt
            cache, _, _ = _prefill_ctx_jit(
                self._draft_model, self._draft_params,
                jnp.asarray(block), self._draft_prefill_template,
                jnp.asarray(0, jnp.int32),
                jnp.asarray(length - 1, jnp.int32),
                jnp.asarray(req.step_keys[0:1]),
                temperature=self.config.temperature,
                eos_id=self.config.eos_id, top_k=self.config.top_k,
                top_p=self.config.top_p)
        else:
            width = slot.prompt_width
            pad = slot.pad_len
            padded = np.zeros((1, width), np.int32)
            padded[0, pad:] = req.prompt
            carry, _ = _prefill_jit(
                self._draft_model, self._draft_params,
                jnp.asarray(padded),
                jnp.asarray(req.step_keys[0:1]),
                self._draft_prefill_template,
                jnp.asarray([pad], jnp.int32),
                temperature=self.config.temperature,
                eos_id=self.config.eos_id, top_k=self.config.top_k,
                top_p=self.config.top_p)
            cache = carry[0]
        self._draft_cache = _insert_cache_row(
            self._draft_cache, cache, slot.index)
        dur = time.monotonic() - t0
        slot.draft_s += dur
        self._note_compile("draft_prefill", f"tokens[1,{width}]",
                           t0, dur, link=self._span_args(req))

    def _emit_token(self, slot: Slot, token: int) -> None:
        slot.emitted += 1
        slot.request.stream._emit(
            TokenEvent(token=token, index=slot.emitted - 1))
        self._m_tokens.inc()
        # Billing-grade per-tenant usage: tokens actually DELIVERED
        # (capped label — spraying tenants can't grow /metrics).
        tenancy.note_tokens(slot.request.tenant
                            or tenancy.DEFAULT_TENANT)
        if self.config.eos_id is not None and \
                token == self.config.eos_id:
            slot.done = True

    def _run_slice(self) -> None:
        if self._spec_on:
            self._run_spec_slice()
        else:
            self._run_plain_slice()

    def _run_plain_slice(self) -> None:
        active = self.scheduler.decoding_slots()
        num_steps = min(self.config.slice_tokens,
                        max(s.remaining for s in active))
        n = self.config.num_slots
        for s in active:
            s.allocated_pages = self.kv.extend_slot(
                s.index, s.allocated_pages, s.write_pos + num_steps,
                s.budget_pages)
        tokens = np.zeros((n,), np.int32)
        wpos = np.zeros((n,), np.int32)
        pads = np.zeros((n,), np.int32)
        done = np.ones((n,), bool)  # inactive rows ride latched
        rngs = np.zeros((num_steps, n, 2), np.uint32)
        for s in active:
            tokens[s.index] = s.last_token
            wpos[s.index] = s.write_pos
            pads[s.index] = s.pad_len
            done[s.index] = s.done
            rngs[:, s.index] = SlotScheduler.slice_keys(s, num_steps)
        t0 = time.monotonic()
        physical, out, last_tok, _ = self._slice_jit(
            self._params, self.kv.physical, self.kv.device_tables(),
            jnp.asarray(wpos), jnp.asarray(pads), jnp.asarray(tokens),
            jnp.asarray(done), jnp.asarray(rngs))
        self.kv.physical = physical
        # The executor yield point (decode-slicing contract): wait for
        # THIS slice so admissions and other executors interleave
        # instead of queueing behind back-to-back dispatches.
        out = np.asarray(jax.block_until_ready(out))
        last_tok = np.asarray(last_tok)
        t_slice = time.monotonic() - t0
        self._token_est.observe(t_slice / num_steps)
        per_token = t_slice / num_steps
        self._slices += 1
        # First dispatch of a new slice length is its jit trace +
        # compile (K_eff shrinks near budget ends — each variant is
        # one program).
        self._note_compile("decode_slice",
                           f"steps={num_steps} slots={n}", t0, t_slice)
        if TRACER.enabled:
            # Per-slice structured profile record (ISSUE 15): the
            # timeline's view of engine health — occupancy collapses
            # and page pressure show up HERE, not as a throughput-dip
            # inference. Documented root span (no single request owns
            # a slice; requests join it via their own decode_ms).
            alloc = self.kv.allocator
            TRACER.record(
                "engine_slice", "engine", t0, t_slice, {
                    "model": self.name,
                    "slice": self._slices,
                    "slots": len(active),
                    "steps": num_steps,
                    "tokens": sum(min(num_steps, s.remaining)
                                  for s in active),
                    "free_pages": alloc.available(),
                    "retained_pages": alloc.retained_pages,
                    "occupancy": round(self.page_occupancy(), 4),
                    "admitted": self.scheduler.admitted,
                    "retired": self.scheduler.retired,
                    "queue_depth": self.scheduler.queue_depth(),
                    "prefix_hits": (self.prefix.hits
                                    if self.prefix is not None
                                    else 0),
                })
        for s in active:
            # Every live slot waited this whole slice — that IS its
            # decode share (per-request attribution, engine_request).
            s.decode_s += t_slice
        for s in active:
            take = min(num_steps, s.remaining)
            for k in range(take):
                if s.done:
                    break  # post-EOS steps are latched padding
                s.steps_done += 1
                self._emit_token(s, int(out[s.index, k]))
                self._m_inter.observe(per_token)
            s.write_pos += num_steps
            s.last_token = int(last_tok[s.index])
            if s.done:
                self._retire(s, "eos")
            elif s.remaining == 0:
                self._retire(s, "budget")

    def _run_spec_slice(self) -> None:
        """One speculative round over the decode batch: k draft
        steps (dense draft cache) + ONE batched verifier forward per
        slot over [t0, d1..dk], then accept the agreeing prefix.
        Emits ``min(accepts + 1, remaining)`` tokens per slot for one
        verifier weight-read — the perf claim is verifier forwards
        per emitted token < 1; the CORRECTNESS claim is that targets
        come from the verifier's own logits under the slot's own
        step keys, so the stream is bitwise the vanilla slice's
        whatever the drafts were. Speculated K/V past the accepted
        length rolls back via ``truncate_slot`` (reservation-safe),
        keeping the write_pos/steps_done alignment the vanilla path
        maintains."""
        active = self.scheduler.decoding_slots()
        k = self.config.speculate_tokens
        steps = k + 1
        n = self.config.num_slots
        for s in active:
            s.allocated_pages = self.kv.extend_slot(
                s.index, s.allocated_pages, s.write_pos + steps,
                s.budget_pages)
        tokens = np.zeros((n,), np.int32)
        wpos = np.zeros((n,), np.int32)
        pads = np.zeros((n,), np.int32)
        done = np.ones((n,), bool)  # inactive rows ride latched
        rngs = np.zeros((steps, n, 2), np.uint32)
        for s in active:
            tokens[s.index] = s.last_token
            wpos[s.index] = s.write_pos
            pads[s.index] = s.pad_len
            done[s.index] = s.done
            rngs[:, s.index] = SlotScheduler.slice_keys(s, steps)
        t0 = time.monotonic()
        # Draft proposes with keys [0, k): key j is the key target
        # j+1 will be sampled with — same key + similar logits means
        # the same categorical draw, which is what acceptance is.
        # All k+1 keys go in; the last step only writes K/V (see
        # _draft_slice) and its proposal is dropped below.
        draft_cache, drafts = self._draft_jit(
            self._draft_params, self._draft_cache,
            jnp.asarray(tokens), jnp.asarray(wpos),
            jnp.asarray(pads), jnp.asarray(done),
            jnp.asarray(rngs))
        self._draft_cache = draft_cache
        drafts = drafts[:, :k]
        # Block on the drafts (not the cache) so draft wall and
        # verify wall are separately attributable — the spec_verify
        # obs contract.
        drafts = jax.block_until_ready(drafts)
        t1 = time.monotonic()
        physical, targets, accepts = self._verify_jit(
            self._params, self.kv.physical, self.kv.device_tables(),
            jnp.asarray(wpos), jnp.asarray(pads),
            jnp.asarray(tokens), drafts, jnp.asarray(done),
            jnp.asarray(rngs))
        self.kv.physical = physical
        targets = np.asarray(jax.block_until_ready(targets))
        accepts = np.asarray(accepts)
        t2 = time.monotonic()
        t_draft, t_verify = t1 - t0, t2 - t1
        t_round = t2 - t0
        self._slices += 1
        self._spec_rounds += 1
        self._note_compile("spec_draft", f"steps={k} slots={n}",
                           t0, t_draft)
        self._note_compile("spec_verify", f"width={steps} slots={n}",
                           t1, t_verify)
        round_drafted = 0
        round_accepted = 0
        round_emitted = 0
        for s in active:
            a = int(accepts[s.index])
            take = min(a + 1, s.remaining)
            used = take - 1  # drafted tokens that saved a forward
            round_drafted += k
            round_accepted += used
            s.spec_drafted += k
            s.spec_accepted += used
            s.draft_s += t_draft
            s.verify_s += t_verify
            s.decode_s += t_round
            per_token = t_round / take
            for j in range(take):
                if s.done:
                    break  # post-EOS targets are latched padding
                s.steps_done += 1
                self._emit_token(s, int(targets[s.index, j]))
                self._m_inter.observe(per_token)
                round_emitted += 1
            s.write_pos += take
            s.allocated_pages = self.kv.truncate_slot(
                s.index, s.allocated_pages, s.write_pos)
            s.last_token = int(targets[s.index, take - 1])
            if s.done:
                self._retire(s, "eos")
            elif s.remaining == 0:
                self._retire(s, "budget")
        self._spec_drafted_total += round_drafted
        self._spec_accepted_total += round_accepted
        self._m_spec_drafted.inc(round_drafted)
        self._m_spec_accepted.inc(round_accepted)
        self._m_spec_rejected.inc(round_drafted - round_accepted)
        self._token_est.observe(
            t_round / max(1.0, round_emitted / max(1, len(active))))
        if TRACER.enabled:
            alloc = self.kv.allocator
            TRACER.record(
                "engine_slice", "engine", t0, t_round, {
                    "model": self.name,
                    "slice": self._slices,
                    "slots": len(active),
                    "steps": steps,
                    "tokens": round_emitted,
                    "spec": True,
                    "drafted": round_drafted,
                    "accepted": round_accepted,
                    "draft_ms": round(t_draft * 1e3, 3),
                    "verify_ms": round(t_verify * 1e3, 3),
                    "free_pages": alloc.available(),
                    "retained_pages": alloc.retained_pages,
                    "occupancy": round(self.page_occupancy(), 4),
                    "admitted": self.scheduler.admitted,
                    "retired": self.scheduler.retired,
                    "queue_depth": self.scheduler.queue_depth(),
                    "prefix_hits": (self.prefix.hits
                                    if self.prefix is not None
                                    else 0),
                })
            # The spec_verify leg: the verifier-forward share of the
            # round, the half the attribution report splits out.
            TRACER.record(
                "spec_verify", "engine", t1, t_verify, {
                    "model": self.name,
                    "slice": self._slices,
                    "slots": len(active),
                    "width": steps,
                })

    def _retire(self, slot: Slot, reason: str,
                error: Optional[BaseException] = None) -> None:
        req = slot.request
        if slot.prefilling:
            # Mid-chunked-prefill death (deadline / cancel / error /
            # shutdown): no pages were adopted — the slot holds only
            # its reservation and the pinned prefix match (fork
            # already unpinned and narrowed out at bind).
            match = slot.prefill_match
            shared = len(match.entries) if match is not None else 0
            if match is not None and self.prefix is not None:
                self.prefix.unpin(match, include_fork=False)
            self.kv.allocator.unreserve(slot.budget_pages - shared)
            slot.clear_prefill_state()
        else:
            self.kv.release_slot(
                slot.index, slot.allocated_pages,
                slot.budget_pages - slot.allocated_pages)
        self.scheduler.retire(slot, reason)
        _M_RETIRED.labels(self.name, reason).inc()
        if TRACER.enabled:
            extra = {}
            if slot.spec_drafted or slot.draft_s or slot.verify_s:
                # Draft vs verify split of the decode share, plus the
                # request's own acceptance economics (ISSUE 16).
                extra = dict(
                    draft_ms=round(slot.draft_s * 1e3, 3),
                    verify_ms=round(slot.verify_s * 1e3, 3),
                    spec_drafted=slot.spec_drafted,
                    spec_accepted=slot.spec_accepted)
            if req.kv_fetch_s:
                # Fleet pull-through wall (ISSUE 20): its own
                # attribution bucket, so a tier fetch is never
                # mistaken for queue wait or decode time in the r19
                # report.
                extra["kv_fetch_ms"] = round(req.kv_fetch_s * 1e3, 3)
            TRACER.record(
                "engine_request", "engine", req.submitted_at,
                time.monotonic() - req.submitted_at,
                self._span_args(
                    req, slot=slot.index, reason=reason,
                    tokens=slot.emitted,
                    # The per-request attribution triple the report
                    # generator buckets e2e latency by (queue wait →
                    # a slot, prefill, decode-slice share).
                    queue_ms=round(slot.queue_s * 1e3, 3),
                    prefill_ms=round(slot.prefill_s * 1e3, 3),
                    decode_ms=round(slot.decode_s * 1e3, 3),
                    **extra))
        if error is not None:
            req.stream._fail(error)
            return
        tokens = req.stream.tokens_so_far
        if len(tokens) < req.max_new_tokens and \
                self.config.eos_id is not None:
            # Early EOS: pad to the request budget with the latched
            # EOS id — byte-for-byte the monolithic generate() shape.
            tokens = tokens + [self.config.eos_id] * (
                req.max_new_tokens - len(tokens))
        req.stream._finish(np.asarray(tokens, np.int32))

    def _span_args(self, req: _Request, **extra) -> dict:
        # span_args adds trace linkage (trace id + parent_id = the
        # transport hop's span id + leg) so engine spans hang under
        # the right hop of the assembled fleet waterfall; the capped
        # tenant label lets waterfalls filter by tenant.
        args = span_args(req.stream.obs_ctx, model=self.name, **extra)
        if req.request_id:
            args["request_id"] = req.request_id
        if req.tenant and req.tenant != tenancy.DEFAULT_TENANT:
            args.setdefault("tenant", tenancy.tenant_label(req.tenant))
        return args
