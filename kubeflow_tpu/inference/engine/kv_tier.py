# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Tier 1 of the tiered KV memory (ISSUE 20): a byte-budgeted
host-RAM pool behind the r15 HBM prefix cache.

The r15 radix cache lives and dies inside one replica's HBM page
pool: under page pressure ``PrefixCache.reclaim`` DROPS zero-ref
retained pages, and every drop costs a full re-prefill to rebuild.
Here the drop becomes **evict-to-host**: the page's K/V rows are
snapshotted to host buffers (one ``[page_size, heads, dim]`` array
per KV leaf, the same per-page shape ``_gather_pages_to_cache``
reads), indexed under the SAME chain hash the radix index uses, and
**re-adopted** HBM-ward on a later match — a host→HBM copy is cheap
next to a re-prefill.

Custody model — deliberately simpler than the allocator's:

- A host block has no refcounts and no pin protocol. A match hands
  back the ``_HostBlock`` object itself; the admission path holds a
  Python reference until the splice lands, so LRU eviction between
  match and splice can never free the arrays out from under it
  (numpy keeps them alive) — it only makes the block unmatchable for
  the NEXT request. No pins means no new deadlock surface: the r15
  no-deadlock rule is untouched because host blocks never consume
  allocator availability.
- Only FULL blocks spill. A partial boundary block is one request's
  private tail — its chain key names a *parent*, not itself, and the
  CoW fork machinery only pays off against resident HBM pages.
- The tier is locked (``threading.RLock``) because fleet-fetch
  imports land from server request threads while the engine thread
  matches and spills. Every public method takes the lock; the
  engine-side single-mutator discipline still governs everything
  HBM-side.

Bitwise correctness: K/V at position ``i`` is a pure function of
tokens ``[0, i]`` (the prefix-cache contract), and a spill snapshot
is taken inside ``reclaim`` BEFORE the page id returns to the free
list — jax arrays are immutable, so the copy reads exactly the bytes
the retired slots wrote. Splicing those bytes back is therefore
indistinguishable from having kept the page.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.inference.engine.paged_kv import _is_kv

__all__ = ["HostKVTier", "splice_host_blocks"]


@dataclasses.dataclass
class _HostBlock:
    """One spilled (or fleet-fetched) full token block: the chain key
    it is indexed under, the block's token content (compared on match
    so a hash collision degrades to a miss), and one host array per
    KV leaf in tree-flatten order."""

    key: bytes
    tokens: Tuple[int, ...]
    layers: List[np.ndarray]  # [page_size, heads, dim] per KV leaf
    nbytes: int


class HostKVTier:
    """Byte-budgeted LRU of host-resident KV blocks, keyed by the
    prefix cache's chain hashes. ``put`` inserts at the MRU end and
    evicts LRU-first past the budget; ``get`` is a token-compared
    lookup that refreshes recency. Thread-safe (see module doc)."""

    def __init__(self, budget_bytes: int):
        if budget_bytes < 0:
            raise ValueError(
                f"host cache budget must be >= 0 bytes, got "
                f"{budget_bytes}")
        self.budget_bytes = int(budget_bytes)
        self._lock = threading.RLock()
        self._blocks: "OrderedDict[bytes, _HostBlock]" = OrderedDict()
        self._bytes = 0
        # Monotonic counters (stats()/metrics families).
        self.spilled_blocks = 0
        self.imported_blocks = 0
        self.evicted_blocks = 0
        self.readopted_blocks = 0

    # -- queries ---------------------------------------------------------

    def resident_blocks(self) -> int:
        with self._lock:
            return len(self._blocks)

    def resident_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def get(self, key: bytes, tokens: Sequence[int]):
        """Token-compared lookup: the stored block must carry exactly
        ``tokens`` (collision guard — same degrade-to-miss rule as
        the HBM index). A hit refreshes LRU recency. Returns the
        :class:`_HostBlock` or None."""
        block = tuple(int(t) for t in tokens)
        with self._lock:
            hb = self._blocks.get(key)
            if hb is None or hb.tokens != block:
                return None
            self._blocks.move_to_end(key)
            return hb

    # -- mutation --------------------------------------------------------

    def put(self, key: bytes, tokens: Sequence[int],
            layers: Sequence[np.ndarray], *,
            imported: bool = False) -> bool:
        """Insert one full block (spill path, or ``imported=True``
        for a fleet fetch landing). A key already resident just
        refreshes recency (dedupe — a re-adopted block that evicts
        again finds its host copy still here). Returns True only on a
        real insert."""
        block = tuple(int(t) for t in tokens)
        arrays = [np.asarray(a) for a in layers]
        nbytes = sum(int(a.nbytes) for a in arrays)
        with self._lock:
            if self.budget_bytes <= 0 or nbytes > self.budget_bytes:
                return False
            existing = self._blocks.get(key)
            if existing is not None:
                self._blocks.move_to_end(key)
                return False
            self._blocks[key] = _HostBlock(
                key=key, tokens=block, layers=arrays, nbytes=nbytes)
            self._bytes += nbytes
            if imported:
                self.imported_blocks += 1
            else:
                self.spilled_blocks += 1
            while self._bytes > self.budget_bytes and self._blocks:
                _, victim = self._blocks.popitem(last=False)
                self._bytes -= victim.nbytes
                self.evicted_blocks += 1
            return True

    def note_readopted(self, n: int) -> None:
        """The admission path spliced ``n`` host blocks HBM-ward."""
        with self._lock:
            self.readopted_blocks += int(n)

    def clear(self) -> int:
        """Drop every resident block (engine stop / tests). Returns
        the number of blocks released."""
        with self._lock:
            n = len(self._blocks)
            self._blocks.clear()
            self._bytes = 0
            return n

    # -- reporting -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "budget_bytes": self.budget_bytes,
                "resident_bytes": self._bytes,
                "resident_blocks": len(self._blocks),
                "spilled_blocks": self.spilled_blocks,
                "imported_blocks": self.imported_blocks,
                "evicted_blocks": self.evicted_blocks,
                "readopted_blocks": self.readopted_blocks,
            }

    def check_accounting(self) -> None:
        """Fuzz-harness half for the host pool: byte ledger vs the
        resident set, budget respected, every block well-formed."""
        with self._lock:
            total = sum(b.nbytes for b in self._blocks.values())
            assert total == self._bytes, \
                f"host byte ledger drifted: {self._bytes} != {total}"
            assert self._bytes <= max(0, self.budget_bytes), \
                f"host pool over budget: {self._bytes} > " \
                f"{self.budget_bytes}"
            for key, b in self._blocks.items():
                assert b.key == key, f"host block keyed under a " \
                    f"foreign key: {b.key!r} != {key!r}"
                assert b.nbytes == sum(int(a.nbytes)
                                       for a in b.layers), \
                    f"host block {key!r} nbytes drifted"
                assert b.tokens, f"host block {key!r} carries no " \
                    f"tokens"


@jax.jit
def _splice_block(cache: Any, layers: Any, row: jax.Array) -> Any:
    """Write one host block's K/V over the gathered B=1 cache at rows
    ``[row, row + page_size)``. ``row`` is traced, so every block
    offset (and every prefix depth) shares one compile; KV leaves
    pair with ``layers`` in tree-flatten order — the same
    deterministic order :meth:`PagedKVCache.read_page_layers`
    snapshots in. Scalar index leaves ride through untouched (the
    gather already set them to the full matched length)."""
    it = iter(layers)

    def s(leaf):
        if not _is_kv(leaf):
            return leaf
        seg = next(it)
        return jax.lax.dynamic_update_slice(
            leaf, seg[None].astype(leaf.dtype), (0, row, 0, 0))

    return jax.tree.map(s, cache)


def splice_host_blocks(cache: Any,
                       blocks_layers: Sequence[Sequence[np.ndarray]],
                       first_block: int, page_size: int) -> Any:
    """Land consecutive host blocks into a gathered B=1 prefix cache:
    block ``i`` of ``blocks_layers`` covers cache rows
    ``[(first_block + i)·P, (first_block + i + 1)·P)`` — exactly the
    null-page placeholder rows the gather left as zeros. The result
    is byte-equal to the cache a pure-HBM match of the same depth
    would have gathered (the host copies ARE the evicted pages'
    bytes), which is what keeps tier hits bitwise."""
    for i, layers in enumerate(blocks_layers):
        row = jnp.asarray((first_block + i) * page_size, jnp.int32)
        cache = _splice_block(cache, list(layers), row)
    return cache
