# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Slot bookkeeping for the continuous-batching engine.

Pure host-side state machine (no jax): N decode slots, a FIFO
admission queue, and reservation-aware admit/retire transitions. The
engine thread is the only mutator; :class:`SlotScheduler` exists
separately from the engine so the scheduling policy is unit-testable
without compiling a model.

Slot lifecycle::

    FREE --admit(prefill+adopt)--> ACTIVE --retire--> FREE
                                     |  (eos / token budget /
                                     |   deadline / cancel / error)

A slot's cache positions: ``[0, pad_len)`` left-pad garbage (masked),
``[pad_len, prompt_width)`` the prompt, ``[prompt_width, write_pos)``
decoded tokens; ``write_pos`` is where the NEXT token's K/V lands.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Deque, List, Optional

import numpy as np


@dataclasses.dataclass
class Slot:
    """One decode slot's host state."""

    index: int
    active: bool = False
    request: Any = None  # the engine's _Request
    write_pos: int = 0  # cache index the next token is written at
    pad_len: int = 0  # left-pad slots before the prompt
    prompt_width: int = 0  # prompt bucket width (pads + prompt)
    last_token: int = 0  # feeds the next decode step
    steps_done: int = 0  # step-rng indices consumed (incl. prefill's)
    emitted: int = 0  # tokens handed to the stream
    done: bool = False  # EOS latched
    allocated_pages: int = 0
    budget_pages: int = 0  # reservation ceiling (pages)
    deadline: Optional[float] = None

    @property
    def max_new_tokens(self) -> int:
        return self.request.max_new_tokens

    @property
    def remaining(self) -> int:
        """Decode steps still owed (the prefill produced token 0)."""
        return max(0, self.max_new_tokens - self.steps_done)


class SlotScheduler:
    """Owns the N slots + the admission FIFO.

    Admission is strictly FIFO (no head-of-line jumping: a large
    request that can't reserve pages yet blocks later arrivals, which
    keeps tail fairness — the alternative starves big prompts
    forever). The page-pool reservation check lives here; the actual
    prefill/adopt device work stays in the engine.
    """

    def __init__(self, num_slots: int, allocator):
        self.slots: List[Slot] = [Slot(i) for i in range(num_slots)]
        self._free: Deque[int] = deque(range(num_slots))
        self._allocator = allocator
        self.pending: Deque[Any] = deque()
        # Monotonic counters for stats()/metrics.
        self.admitted = 0
        self.retired = 0
        self.retired_by: dict = {}

    # -- queries ---------------------------------------------------------

    def active_slots(self) -> List[Slot]:
        return [s for s in self.slots if s.active]

    def occupancy(self) -> int:
        return len(self.slots) - len(self._free)

    def queue_depth(self) -> int:
        return len(self.pending)

    def has_free_slot(self) -> bool:
        return bool(self._free)

    def has_capacity_for(self, budget_pages: int) -> bool:
        return bool(self._free) and self._allocator.available() >= \
            budget_pages

    # -- transitions (engine thread only) --------------------------------

    def next_admittable(self, budget_pages_of) -> Optional[Any]:
        """Pop the FIFO head iff a slot AND its reservation fit;
        ``budget_pages_of(request)`` prices the worst case. None =
        nothing admittable right now (empty queue, no slot, or the
        head's reservation doesn't fit yet — FIFO holds the line)."""
        if not self.pending or not self._free:
            return None
        head = self.pending[0]
        if not self._allocator.reserve(budget_pages_of(head)):
            return None
        return self.pending.popleft()

    def bind(self, request: Any, *, prompt_width: int, pad_len: int,
             first_token: int, done: bool, budget_pages: int,
             deadline: Optional[float]) -> Slot:
        """Attach an admitted (already prefilled) request to a free
        slot. The caller has already reserved ``budget_pages``."""
        slot = self.slots[self._free.popleft()]
        assert not slot.active, f"slot {slot.index} double-bound"
        slot.active = True
        slot.request = request
        slot.write_pos = prompt_width
        slot.pad_len = pad_len
        slot.prompt_width = prompt_width
        slot.last_token = int(first_token)
        slot.steps_done = 1  # the prefill consumed step key 0
        slot.emitted = 0
        slot.done = bool(done)
        slot.allocated_pages = 0
        slot.budget_pages = budget_pages
        slot.deadline = deadline
        self.admitted += 1
        return slot

    def retire(self, slot: Slot, reason: str) -> None:
        """Return the slot to the free pool. Page release is the
        engine's job (it owns the PagedKVCache); this only flips the
        host state so the pages/reservation numbers the engine reads
        off the slot are still intact when it releases them."""
        assert slot.active, f"slot {slot.index} retired twice"
        slot.active = False
        slot.request = None
        self._free.append(slot.index)
        self.retired += 1
        self.retired_by[reason] = self.retired_by.get(reason, 0) + 1

    # -- expiry ----------------------------------------------------------

    def expired_slots(self, now: Optional[float] = None) -> List[Slot]:
        now = time.monotonic() if now is None else now
        return [s for s in self.active_slots()
                if s.deadline is not None and s.deadline <= now]

    def expired_pending(self, now: Optional[float] = None) -> List[Any]:
        """Drop (and return) queued requests whose deadline lapsed
        before a slot ever freed up — they must never burn a prefill.
        Caller must hold the engine's submit lock: this SWAPS the
        pending deque, and an unlocked swap would drop a concurrently
        appended request on the floor."""
        now = time.monotonic() if now is None else now
        expired = []
        keep: Deque[Any] = deque()
        while self.pending:
            req = self.pending.popleft()
            if req.deadline is not None and req.deadline <= now:
                expired.append(req)
            else:
                keep.append(req)
        self.pending = keep
        return expired

    # -- step-key helper -------------------------------------------------

    @staticmethod
    def slice_keys(slot: Slot, num_steps: int) -> np.ndarray:
        """The slot's per-step sampling keys for the next
        ``num_steps`` decode steps ([K, 2]); indices past the
        request's schedule clamp to the last key (those steps are
        overshoot — computed, discarded)."""
        keys = slot.request.step_keys
        idx = np.minimum(
            np.arange(slot.steps_done, slot.steps_done + num_steps),
            len(keys) - 1)
        return keys[idx]
