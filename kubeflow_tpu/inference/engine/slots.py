# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Slot bookkeeping for the continuous-batching engine.

Pure host-side state machine (no jax): N decode slots, a
weighted-fair admission queue (per-tenant sub-queues, ISSUE 14), and
reservation-aware admit/retire transitions. The engine thread is the
only mutator; :class:`SlotScheduler` exists separately from the
engine so the scheduling policy is unit-testable without compiling a
model.

Slot lifecycle::

    FREE --admit(prefill+adopt)--> ACTIVE --retire--> FREE
                                     |  (eos / token budget /
                                     |   deadline / cancel / error)

A slot's cache positions: ``[0, pad_len)`` left-pad garbage (masked),
``[pad_len, prompt_width)`` the prompt, ``[prompt_width, write_pos)``
decoded tokens; ``write_pos`` is where the NEXT token's K/V lands.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

import numpy as np

from kubeflow_tpu.serving.tenancy import FairQueue


@dataclasses.dataclass
class Slot:
    """One decode slot's host state."""

    index: int
    active: bool = False
    request: Any = None  # the engine's _Request
    write_pos: int = 0  # cache index the next token is written at
    pad_len: int = 0  # left-pad slots before the prompt
    prompt_width: int = 0  # prompt bucket width (pads + prompt)
    last_token: int = 0  # feeds the next decode step
    steps_done: int = 0  # step-rng indices consumed (incl. prefill's)
    emitted: int = 0  # tokens handed to the stream
    done: bool = False  # EOS latched
    allocated_pages: int = 0
    budget_pages: int = 0  # reservation ceiling (pages)
    deadline: Optional[float] = None
    # Latency attribution (ISSUE 15): where this request's wall time
    # went — queue wait before the slot, its prefill, and its share
    # of decode-slice wall (a slot waits the FULL slice whatever its
    # neighbors do). The engine_request span reports them as
    # queue_ms / prefill_ms / decode_ms.
    queue_s: float = 0.0
    prefill_s: float = 0.0
    decode_s: float = 0.0
    # Speculative-decode attribution (ISSUE 16): draft vs verify
    # share of this slot's decode wall, and its drafted/accepted
    # token counts — the engine_request span reports draft_ms /
    # verify_ms / spec acceptance alongside the r15 triple.
    draft_s: float = 0.0
    verify_s: float = 0.0
    spec_drafted: int = 0
    spec_accepted: int = 0
    # Chunked-prefill state (ISSUE 16): an admitted long prompt
    # occupies its slot while its prefill advances one page-aligned
    # chunk per engine lap, interleaved with decode slices. While
    # ``prefilling`` the slot is excluded from decode batches;
    # ``prefill_pos`` is the next prompt index to feed,
    # ``prefill_cache`` the accumulating B=1 contiguous cache, and
    # ``prefill_match`` the pinned prefix-cache match that must be
    # unpinned if the slot dies before adoption.
    prefilling: bool = False
    prefill_pos: int = 0
    prefill_cache: Any = None
    prefill_match: Any = None

    def clear_prefill_state(self) -> None:
        self.prefilling = False
        self.prefill_pos = 0
        self.prefill_cache = None
        self.prefill_match = None

    @property
    def max_new_tokens(self) -> int:
        return self.request.max_new_tokens

    @property
    def remaining(self) -> int:
        """Decode steps still owed (the prefill produced token 0)."""
        return max(0, self.max_new_tokens - self.steps_done)


class SlotScheduler:
    """Owns the N slots + the weighted-fair admission queue.

    Admission is strictly FIFO *within a tenant* (no head-of-line
    jumping inside a sub-queue: a large request that can't reserve
    pages yet blocks ITS tenant's later arrivals, which keeps tail
    fairness — the alternative starves big prompts forever) and
    weighted-fair *across* tenants (``pending`` is a
    :class:`~kubeflow_tpu.serving.tenancy.FairQueue`: one tenant's
    burst cannot park work in front of another tenant's head; with a
    single tenant the drain order is bitwise the old global FIFO's).
    The page-pool reservation check lives here; the actual
    prefill/adopt device work stays in the engine.
    """

    #: Consecutive failed reservations of the SAME fair-first head
    #: after which admission holds the WHOLE line (no other tenant's
    #: head admits) so freed pages can accumulate for it. Skipping a
    #: blocked head avoids cross-tenant head-of-line blocking, but
    #: unbounded skipping would let a stream of small requests from
    #: OTHER tenants starve a large reservation forever — the exact
    #: liveness property the old global FIFO bought by always holding.
    #: This bounds the starvation window instead: ~threshold admission
    #: attempts (one per engine lap), then the classic hold applies
    #: until the head fits, expires, or cancels.
    STARVATION_HOLD_ATTEMPTS = 32

    def __init__(self, num_slots: int, allocator, *,
                 weight_of: Optional[Callable[[str], float]] = None):
        self.slots: List[Slot] = [Slot(i) for i in range(num_slots)]
        self._free: Deque[int] = deque(range(num_slots))
        self._allocator = allocator
        self.pending: FairQueue = FairQueue(weight_of=weight_of)
        self._blocked_head: Any = None
        self._blocked_count = 0
        # Monotonic counters for stats()/metrics.
        self.admitted = 0
        self.retired = 0
        self.retired_by: dict = {}

    # -- queries ---------------------------------------------------------

    def active_slots(self) -> List[Slot]:
        return [s for s in self.slots if s.active]

    def decoding_slots(self) -> List[Slot]:
        """Active slots in the decode batch (a chunk-prefilling slot
        occupies a slot but has no first token yet)."""
        return [s for s in self.slots if s.active and not s.prefilling]

    def prefilling_slots(self) -> List[Slot]:
        return [s for s in self.slots if s.active and s.prefilling]

    def occupancy(self) -> int:
        return len(self.slots) - len(self._free)

    def queue_depth(self) -> int:
        return len(self.pending)

    def tenant_depths(self) -> Dict[str, int]:
        """Queued requests per tenant — the attribution a queue-full
        shed carries so a 503 names the tenant that caused it."""
        return self.pending.tenant_depths()

    def has_free_slot(self) -> bool:
        return bool(self._free)

    def has_capacity_for(self, budget_pages: int) -> bool:
        return bool(self._free) and self._allocator.available() >= \
            budget_pages

    # -- transitions (engine thread only) --------------------------------

    def head_blocked(self, head: Any) -> bool:
        """Record one failed reservation for the FAIR-FIRST head.
        Returns True once the same head has failed
        ``STARVATION_HOLD_ATTEMPTS`` consecutive attempts — the
        caller must then hold the whole line (admit nobody) so freed
        pages accumulate for it instead of leaking to smaller
        requests from other tenants forever."""
        if self._blocked_head is head:
            self._blocked_count += 1
        else:
            self._blocked_head = head
            self._blocked_count = 1
        return self._blocked_count >= self.STARVATION_HOLD_ATTEMPTS

    def head_unblocked(self) -> None:
        self._blocked_head = None
        self._blocked_count = 0

    def holding_for_head(self) -> bool:
        """True while the starvation guard holds the line for a
        blocked fair-first head (introspection for stats/fuzz)."""
        return (self._blocked_head is not None
                and self._blocked_count
                >= self.STARVATION_HOLD_ATTEMPTS)

    def next_admittable(self, budget_pages_of) -> Optional[Any]:
        """Pop the first admittable tenant head in fair-queueing
        order iff a slot AND its reservation fit;
        ``budget_pages_of(request)`` prices the worst case. A head
        whose reservation doesn't fit holds the line for ITS tenant
        only (and is not charged fair-share, so it keeps first claim
        on freed pages); other tenants' heads still admit — no
        cross-tenant head-of-line blocking, BOUNDED by the
        starvation guard: once the same fair-first head has been
        skipped ``STARVATION_HOLD_ATTEMPTS`` times, the whole line
        holds (classic FIFO behavior) until it fits or leaves the
        queue. None = nothing admittable right now."""
        if not self.pending or not self._free:
            return None
        for i, head in enumerate(self.pending.heads()):
            if self._allocator.reserve(budget_pages_of(head)):
                if i == 0:
                    self.head_unblocked()
                self.pending.pop_head(head)
                return head
            if i == 0 and self.head_blocked(head):
                return None  # hold the line for the starving head
        return None

    def bind(self, request: Any, *, prompt_width: int, pad_len: int,
             first_token: int, done: bool, budget_pages: int,
             deadline: Optional[float]) -> Slot:
        """Attach an admitted (already prefilled) request to a free
        slot. The caller has already reserved ``budget_pages``."""
        slot = self.slots[self._free.popleft()]
        assert not slot.active, f"slot {slot.index} double-bound"
        slot.active = True
        slot.request = request
        slot.write_pos = prompt_width
        slot.pad_len = pad_len
        slot.prompt_width = prompt_width
        slot.last_token = int(first_token)
        slot.steps_done = 1  # the prefill consumed step key 0
        slot.emitted = 0
        slot.done = bool(done)
        slot.allocated_pages = 0
        slot.budget_pages = budget_pages
        slot.deadline = deadline
        slot.queue_s = 0.0  # slots are reused: attribution resets
        slot.prefill_s = 0.0
        slot.decode_s = 0.0
        slot.draft_s = 0.0
        slot.verify_s = 0.0
        slot.spec_drafted = 0
        slot.spec_accepted = 0
        slot.clear_prefill_state()
        self.admitted += 1
        return slot

    def bind_prefilling(self, request: Any, *, prefill_pos: int,
                        prefill_cache: Any, prefill_match: Any,
                        budget_pages: int,
                        deadline: Optional[float]) -> Slot:
        """Attach an admitted request whose prompt will prefill in
        page-aligned chunks ACROSS engine laps (ISSUE 16): the slot
        is occupied (it holds the reservation and, via
        ``prefill_match``, the pinned prefix pages) but joins no
        decode batch until :meth:`finish_prefill`. The caller has
        already reserved ``budget_pages`` minus the pinned shared
        pages."""
        slot = self.slots[self._free.popleft()]
        assert not slot.active, f"slot {slot.index} double-bound"
        slot.active = True
        slot.request = request
        slot.write_pos = 0
        slot.pad_len = 0
        slot.prompt_width = 0
        slot.last_token = 0
        slot.steps_done = 0
        slot.emitted = 0
        slot.done = False
        slot.allocated_pages = 0
        slot.budget_pages = budget_pages
        slot.deadline = deadline
        slot.queue_s = 0.0
        slot.prefill_s = 0.0
        slot.decode_s = 0.0
        slot.draft_s = 0.0
        slot.verify_s = 0.0
        slot.spec_drafted = 0
        slot.spec_accepted = 0
        slot.prefilling = True
        slot.prefill_pos = prefill_pos
        slot.prefill_cache = prefill_cache
        slot.prefill_match = prefill_match
        self.admitted += 1
        return slot

    @staticmethod
    def finish_prefill(slot: Slot, *, prompt_width: int,
                       first_token: int, done: bool) -> None:
        """Chunked prefill completed: the slot joins the decode batch
        with the same state :meth:`bind` would have set (pad-0
        layout; the prefill consumed step key 0)."""
        assert slot.prefilling, f"slot {slot.index} not prefilling"
        slot.write_pos = prompt_width
        slot.prompt_width = prompt_width
        slot.last_token = int(first_token)
        slot.steps_done = 1
        slot.done = bool(done)
        slot.clear_prefill_state()

    def retire(self, slot: Slot, reason: str) -> None:
        """Return the slot to the free pool. Page release is the
        engine's job (it owns the PagedKVCache); this only flips the
        host state so the pages/reservation numbers the engine reads
        off the slot are still intact when it releases them."""
        assert slot.active, f"slot {slot.index} retired twice"
        slot.active = False
        slot.request = None
        self._free.append(slot.index)
        self.retired += 1
        self.retired_by[reason] = self.retired_by.get(reason, 0) + 1

    # -- expiry ----------------------------------------------------------

    def expired_slots(self, now: Optional[float] = None) -> List[Slot]:
        now = time.monotonic() if now is None else now
        return [s for s in self.active_slots()
                if s.deadline is not None and s.deadline <= now]

    def expired_pending(self, now: Optional[float] = None) -> List[Any]:
        """Drop (and return) queued requests whose deadline lapsed
        before a slot ever freed up — they must never burn a prefill.
        ``FairQueue.remove_if`` rebuilds each sub-queue atomically
        under its own lock (fairness state survives the sweep), so a
        concurrently appended request can never be dropped — the r11
        locked-swap contract, now per sub-queue."""
        now = time.monotonic() if now is None else now
        return self.pending.remove_if(
            lambda req: req.deadline is not None
            and req.deadline <= now)

    # -- step-key helper -------------------------------------------------

    @staticmethod
    def slice_keys(slot: Slot, num_steps: int) -> np.ndarray:
        """The slot's per-step sampling keys for the next
        ``num_steps`` decode steps ([K, 2]); indices past the
        request's schedule clamp to the last key (those steps are
        overshoot — computed, discarded)."""
        keys = slot.request.step_keys
        idx = np.minimum(
            np.arange(slot.steps_done, slot.steps_done + num_steps),
            len(keys) - 1)
        return keys[idx]
