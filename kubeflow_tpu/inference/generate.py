# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Autoregressive generation: prefill + KV-cache decode loop.

Beyond-parity feature (the reference served classify-style models
only); TPU-first shape discipline throughout:

- The KV cache is a **static-size** buffer (``cache_size`` on the
  Llama family, models/llama.py) written with
  ``lax.dynamic_update_slice`` at a running index — no growing arrays,
  one compile for the whole decode.
- Prefill runs the full prompt once (batched matmuls, MXU-bound) and
  fills the cache; decode steps run inside one ``lax.scan`` (single
  dispatch for the whole generation — on remote-tunneled backends this
  is also the difference between one round-trip and max_new_tokens of
  them).
- Greedy (``temperature=0``) or temperature sampling.
- Batched decode is first-class: mixed-length prompts ride one decode
  dispatch via LEFT-padding + ``prompt_lengths`` (per-row position
  offsets and a cache-slot mask keep each row identical to its B=1
  run), and ``rng`` accepts per-row keys ``[B, 2]`` so sampled rows
  reproduce their single-request streams inside any batch. Decode is
  HBM-bound (every step streams the full weight set), so the batch
  rows are near-free throughput — the serving micro-batcher
  (serving/manager.py) exists to exploit exactly this.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def prompt_bucket(n: int, max_len: int,
                  buckets: Optional[Sequence[int]] = None) -> int:
    """THE prompt-length bucketing policy, shared by the serving
    prepare path (LoadedModel) and the decode engine so the widths
    they prefill-compile can never drift apart: the export's explicit
    ``buckets`` list when present, else the smallest power of two
    ≥ ``n`` — either way capped at ``max_len``."""
    if buckets:
        for b in sorted(int(v) for v in buckets):
            if b >= n:
                return min(b, max_len)
        return max_len
    b = 1
    while b < n and b < max_len:
        b *= 2
    return min(b, max_len)


def init_cache(model: Any, params: Any, batch: int) -> Any:
    """Zero cache variables matching ``model`` (which must be built
    with a ``cache_size``). Cheap: shapes come from eval_shape."""
    dummy = jnp.zeros((batch, 1), jnp.int32)
    shapes = jax.eval_shape(
        lambda p: model.apply({"params": p}, dummy,
                              jnp.zeros((batch, 1), jnp.int32),
                              mutable=["cache"])[1],
        params)
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        shapes["cache"])


def _truncate_logits(logits: jax.Array, top_k: Optional[int],
                     top_p: Optional[float]) -> jax.Array:
    """Mask logits outside the top-k set and/or the top-p (nucleus)
    set to -inf. Static shapes throughout: top-p uses a full
    descending sort (one ``lax.top_k`` over vocab — cheap on TPU next
    to the decode matmuls) and converts the kept set into a value
    threshold, avoiding any scatter back to token order."""
    neg_inf = jnp.asarray(-jnp.inf, logits.dtype)
    # top_k in (None, 0) and top_p in (None, >=1.0) mean "disabled"
    # (the conventional sentinels); top_k >= vocab is a no-op.
    if top_k is not None and 0 < top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg_inf, logits)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jax.lax.top_k(logits, logits.shape[-1])[0]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep the smallest prefix with mass ≥ top_p; the top token is
        # force-kept so top_p ≤ 0 degrades to greedy rather than to an
        # all--inf row (categorical over which would emit token 0).
        keep = (cum - probs) < top_p
        keep = keep.at[..., 0].set(True)
        threshold = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
            keepdims=True)
        logits = jnp.where(logits < threshold, neg_inf, logits)
    return logits


def _split_step_rngs(rng: jax.Array, n: int) -> jax.Array:
    """Per-step rngs from either one shared key (``[2]`` → ``[N, 2]``,
    one stream for the whole batch — the classic path) or per-row keys
    (``[B, 2]`` → ``[N, B, 2]``, one independent stream per row, so a
    row sampled inside a coalesced batch is bitwise identical to the
    same request run at B=1 with its own key)."""
    if rng.ndim == 2:
        return jnp.swapaxes(
            jax.vmap(lambda k: jax.random.split(k, n))(rng), 0, 1)
    return jax.random.split(rng, n)


def _prompt_positions(b, prompt_len, pad_lengths):
    """RoPE positions for a (possibly left-padded) prompt: row i's
    real tokens get positions 0..len_i-1 whatever slot they occupy;
    pad slots clamp to 0 (their K/V are masked out of attention)."""
    if pad_lengths is None:
        return jnp.broadcast_to(
            jnp.arange(prompt_len)[None, :], (b, prompt_len))
    return jnp.maximum(
        jnp.arange(prompt_len)[None, :] - pad_lengths[:, None], 0)


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "temperature", "eos_id",
                     "top_k", "top_p"))
def _generate_jit(model, params, prompt_ids, rng, cache, pad_lengths, *,
                  max_new_tokens: int, temperature: float,
                  eos_id: Optional[int], top_k: Optional[int] = None,
                  top_p: Optional[float] = None):
    """Module-level jit: repeat calls with the same (model, shapes,
    config) hit the trace cache instead of recompiling per call."""
    b, prompt_len = prompt_ids.shape

    def sample(logits, step_rng):
        return _sample_logits(logits, step_rng, temperature, top_k, top_p)

    decode_step = _make_decode_step(model, params, b, temperature,
                                    eos_id, top_k, top_p, pad_lengths)

    positions = _prompt_positions(b, prompt_len, pad_lengths)
    mkw = {} if pad_lengths is None else {"pad_lengths": pad_lengths}
    prefill_logits, mutated = model.apply(
        {"params": params, "cache": cache}, prompt_ids, positions,
        mutable=["cache"], **mkw)
    last_logits = prefill_logits[:, -1]
    step_rngs = _split_step_rngs(rng, max_new_tokens)
    first = sample(last_logits, step_rngs[0])
    done = jnp.zeros((b,), bool)
    if eos_id is not None:
        done = first == eos_id
    if pad_lengths is None:
        position = jnp.full((b,), prompt_len, jnp.int32)
    else:
        position = (prompt_len - pad_lengths).astype(jnp.int32)
    carry = (mutated["cache"], first, position, done)
    # Steps 2..N inside one scan: single dispatch for the decode.
    _, (tokens, logits) = jax.lax.scan(decode_step, carry, step_rngs[1:])
    tokens = jnp.concatenate([first[None], tokens], axis=0)
    logits = jnp.concatenate([last_logits[None], logits], axis=0)
    # scan stacks on the step axis; callers want [B, N, ...].
    return tokens.swapaxes(0, 1), logits.swapaxes(0, 1)


@functools.partial(
    jax.jit,
    static_argnames=("model", "temperature", "eos_id", "top_k", "top_p"))
def _prefill_jit(model, params, prompt_ids, first_rng, cache,
                 pad_lengths, *,
                 temperature: float, eos_id: Optional[int],
                 top_k: Optional[int], top_p: Optional[float]):
    """Prompt pass + first sampled token (the chunked path's head)."""
    b, prompt_len = prompt_ids.shape
    positions = _prompt_positions(b, prompt_len, pad_lengths)
    mkw = {} if pad_lengths is None else {"pad_lengths": pad_lengths}
    prefill_logits, mutated = model.apply(
        {"params": params, "cache": cache}, prompt_ids, positions,
        mutable=["cache"], **mkw)
    last_logits = prefill_logits[:, -1]
    first = _sample_logits(last_logits, first_rng, temperature,
                           top_k, top_p)
    done = (first == eos_id) if eos_id is not None else \
        jnp.zeros((b,), bool)
    if pad_lengths is None:
        position = jnp.full((b,), prompt_len, jnp.int32)
    else:
        position = (prompt_len - pad_lengths).astype(jnp.int32)
    return (mutated["cache"], first, position, done), last_logits


def _sample_logits(logits, step_rng, temperature, top_k, top_p):
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    logits = _truncate_logits(logits, top_k, top_p)
    if step_rng.ndim == 2:
        # Per-row keys: each row consumes its own stream, so the same
        # (prompt, key) samples the same tokens at any batch position.
        return jax.vmap(jax.random.categorical)(
            step_rng, logits).astype(jnp.int32)
    return jax.random.categorical(
        step_rng, logits, axis=-1).astype(jnp.int32)


def _make_decode_step(model, params, b, temperature, eos_id, top_k,
                      top_p, pad_lengths=None):
    """THE one-token decode step (cache write + sample + EOS latch),
    shared by the monolithic scan and the chunked slices — the
    bitwise equivalence between those paths rests on this being one
    function."""
    mkw = {} if pad_lengths is None else {"pad_lengths": pad_lengths}

    def decode_step(carry, step_rng):
        cache, token, position, done = carry
        positions = jnp.broadcast_to(position[:, None], (b, 1))
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, token[:, None], positions,
            mutable=["cache"], **mkw)
        logits = logits[:, 0]
        next_token = _sample_logits(logits, step_rng, temperature,
                                    top_k, top_p)
        if eos_id is not None:
            next_token = jnp.where(done, eos_id, next_token)
            done = done | (next_token == eos_id)
        return ((mutated["cache"], next_token, position + 1, done),
                (next_token, logits))

    return decode_step


@functools.partial(
    jax.jit,
    static_argnames=("model", "temperature", "eos_id", "top_k", "top_p"))
def _decode_chunk_jit(model, params, carry, step_rngs, pad_lengths, *,
                      temperature: float, eos_id: Optional[int],
                      top_k: Optional[int], top_p: Optional[float]):
    """One K-token decode slice (K = step_rngs length, static by
    shape). The SAME decode_step as the monolithic scan
    (_make_decode_step); the carry round-trips between slices."""
    decode_step = _make_decode_step(model, params, carry[1].shape[0],
                                    temperature, eos_id, top_k, top_p,
                                    pad_lengths)
    carry, (tokens, logits) = jax.lax.scan(decode_step, carry, step_rngs)
    return carry, tokens.swapaxes(0, 1), logits.swapaxes(0, 1)


def generate(
    model: Any,
    params: Any,
    prompt_ids: jax.Array,
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_id: Optional[int] = None,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    chunk_tokens: Optional[int] = None,
    prompt_lengths: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Generate ``max_new_tokens`` continuations of ``prompt_ids``.

    ``model`` must be constructed with
    ``cache_size >= prompt_len + max_new_tokens``. Returns
    ``(tokens [B, max_new_tokens], logits [B, max_new_tokens, V])``.
    With ``eos_id``, tokens after the first EOS are replaced by EOS
    (shapes stay static; callers trim). ``top_k``/``top_p`` truncate
    the sampling distribution (nucleus sampling); both only apply when
    ``temperature > 0``.

    ``prompt_lengths`` — batched mixed-length decode: ``[B]`` true
    per-row token counts, with ``prompt_ids`` LEFT-padded (each row's
    real tokens right-aligned; pad ids are arbitrary). Per-row
    position offsets + a cache-slot mask make every row's computation
    attend over exactly its own tokens at its own positions, so row i
    of a batch equals the same prompt run alone at B=1. None = every
    row is full-width (the classic path).

    ``rng`` — one PRNG key (``[2]``: the whole batch shares one
    per-step stream, the classic behavior), or per-row keys
    (``[B, 2]``: row i samples from ``rng[i]``'s stream, so a request
    coalesced into a batch reproduces its B=1 tokens bitwise — the
    serving batcher's contract).

    ``chunk_tokens`` — decode-slicing for SHARED executors (the
    serving head-of-line fix, PERF.md r5): instead of one monolithic
    dispatch whose multi-second decode monopolizes the device, decode
    runs in K-token slices with a host sync between them, creating
    yield points where concurrently-queued work (classify batches)
    can interleave. Token output is identical to the monolithic path
    (same per-step rng stream); cost is one dispatch per slice. None/
    ``>= max_new_tokens`` = monolithic (the single-stream optimum,
    and the only sensible choice over high-latency tunnels).
    """
    if model.cache_size < prompt_ids.shape[1] + max_new_tokens:
        raise ValueError(
            f"cache_size {model.cache_size} < prompt "
            f"{prompt_ids.shape[1]} + max_new_tokens {max_new_tokens}")
    if chunk_tokens is not None and chunk_tokens < 1:
        # A negative K would make the chunk count negative and
        # silently truncate the output to the prefill token.
        raise ValueError(f"chunk_tokens must be >= 1, got {chunk_tokens}")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    pad_lengths = None
    if prompt_lengths is not None:
        prompt_lengths = jnp.asarray(prompt_lengths, jnp.int32)
        if prompt_lengths.shape != (prompt_ids.shape[0],):
            raise ValueError(
                f"prompt_lengths shape {prompt_lengths.shape} != "
                f"(batch,) = ({prompt_ids.shape[0]},)")
        # Host-side range check (values are concrete here — generate
        # is an eager wrapper): an out-of-range length would silently
        # shift every RoPE position / unmask garbage cache slots
        # instead of erroring.
        lo, hi = int(jnp.min(prompt_lengths)), int(jnp.max(prompt_lengths))
        if lo < 1 or hi > prompt_ids.shape[1]:
            raise ValueError(
                f"prompt_lengths must be in [1, {prompt_ids.shape[1]}] "
                f"(the padded prompt width); got range [{lo}, {hi}]")
        pad_lengths = prompt_ids.shape[1] - prompt_lengths
    cache = init_cache(model, params, prompt_ids.shape[0])
    if not chunk_tokens or chunk_tokens >= max_new_tokens:
        return _generate_jit(model, params, prompt_ids, rng, cache,
                             pad_lengths,
                             max_new_tokens=max_new_tokens,
                             temperature=temperature, eos_id=eos_id,
                             top_k=top_k, top_p=top_p)

    # The SAME rng stream as the monolithic path (split once over
    # max_new_tokens), padded to whole slices — padding steps produce
    # trimmed tokens only, so outputs match bitwise.
    step_rngs = _split_step_rngs(rng, max_new_tokens)
    n_decode = max_new_tokens - 1
    n_chunks = -(-n_decode // chunk_tokens)
    pad = n_chunks * chunk_tokens - n_decode
    decode_rngs = jnp.concatenate(
        [step_rngs[1:]] + [step_rngs[-1:]] * pad) if pad else step_rngs[1:]
    sample_kw = dict(temperature=temperature, eos_id=eos_id,
                     top_k=top_k, top_p=top_p)
    carry, last_logits = _prefill_jit(
        model, params, prompt_ids, step_rngs[0], cache, pad_lengths,
        **sample_kw)
    tokens_out = [carry[1][:, None]]
    logits_out = [last_logits[:, None]]
    for c in range(n_chunks):
        rngs = decode_rngs[c * chunk_tokens:(c + 1) * chunk_tokens]
        carry, toks, logs = _decode_chunk_jit(
            model, params, carry, rngs, pad_lengths, **sample_kw)
        tokens_out.append(toks)
        logits_out.append(logs)
        # The yield point: wait for THIS slice before dispatching the
        # next, so the device queue drains and other requests' batches
        # get a slot. (Without it, async dispatch would enqueue every
        # slice back-to-back and re-monopolize the device.)
        jax.block_until_ready(toks)
    tokens = jnp.concatenate(tokens_out, axis=1)[:, :max_new_tokens]
    logits = jnp.concatenate(logits_out, axis=1)[:, :max_new_tokens]
    return tokens, logits
