from kubeflow_tpu.params.spec import Param, ParamSet, REQUIRED  # noqa: F401
from kubeflow_tpu.params.registry import Prototype, register, get_prototype, list_prototypes  # noqa: F401
