# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Typed parameter system — the replacement for ksonnet prototype params.

The reference declared parameters as ``@param``/``@optionalParam``
comment annotations on jsonnet prototypes (e.g.
``kubeflow/core/prototypes/all.jsonnet:5-17``), received every value as
a string, and coerced ad hoc with ``util.toBool/toArray``. Here the same
surface is a declarative :class:`Param` list per prototype; coercion
happens once, at :meth:`ParamSet.resolve`, and everything downstream is
typed. Environment overlays (ksonnet's per-env ``params.libsonnet``)
are plain dict overlays applied in order.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional

from kubeflow_tpu.utils.coerce import to_array, to_bool, to_int


class _Required:
    def __repr__(self) -> str:  # pragma: no cover
        return "<REQUIRED>"


REQUIRED = _Required()

_COERCERS: Dict[str, Callable[[Any], Any]] = {
    "string": lambda v: str(v),
    "int": to_int,
    "bool": to_bool,
    "array": to_array,
    # Structured values (dicts/lists) pass through by deep copy so a
    # builder mutating its resolved value can't corrupt the Param
    # default or a shared overlay across builds.
    "raw": copy.deepcopy,
}


@dataclasses.dataclass(frozen=True)
class Param:
    """One declared parameter of a prototype.

    ``kind`` selects the string-boundary coercion; ``default`` of
    :data:`REQUIRED` makes the param mandatory (ksonnet ``@param`` vs
    ``@optionalParam``).
    """

    name: str
    default: Any = REQUIRED
    kind: str = "string"
    doc: str = ""

    def __post_init__(self) -> None:
        if self.kind not in _COERCERS:
            raise ValueError(f"unknown param kind {self.kind!r} for {self.name!r}")

    @property
    def required(self) -> bool:
        return self.default is REQUIRED

    def coerce(self, value: Any) -> Any:
        try:
            return _COERCERS[self.kind](value)
        except (TypeError, ValueError) as e:
            raise ValueError(f"param {self.name!r}: {e}") from e


class ParamSet:
    """A prototype's declared params plus any number of overlays.

    Overlays are applied left-to-right (defaults < app params < env
    params < CLI ``--param`` flags), mirroring ksonnet's
    component-params/env-params/`ks param set` precedence.
    """

    def __init__(self, params: Iterable[Param]):
        self._specs: Dict[str, Param] = {}
        for p in params:
            if p.name in self._specs:
                raise ValueError(f"duplicate param {p.name!r}")
            self._specs[p.name] = p
        self._overlays: List[Mapping[str, Any]] = []

    @property
    def specs(self) -> Dict[str, Param]:
        return dict(self._specs)

    def overlay(self, values: Optional[Mapping[str, Any]]) -> "ParamSet":
        """Return a new ParamSet with ``values`` layered on top."""
        clone = ParamSet(self._specs.values())
        clone._overlays = list(self._overlays)
        if values:
            unknown = set(values) - set(self._specs)
            if unknown:
                raise KeyError(
                    f"unknown params {sorted(unknown)}; declared: {sorted(self._specs)}"
                )
            clone._overlays.append(dict(values))
        return clone

    def resolve(self) -> Dict[str, Any]:
        """Collapse overlays over defaults into a typed dict."""
        out: Dict[str, Any] = {}
        for name, spec in self._specs.items():
            value = spec.default
            for layer in self._overlays:
                if name in layer:
                    value = layer[name]
            if value is REQUIRED:
                raise ValueError(f"missing required param {name!r}")
            if value is None:
                # None is only a legal resolved value for params whose
                # declared default is None (nullable params); it must
                # not bypass REQUIRED or coercion via an overlay.
                if spec.default is None:
                    out[name] = None
                    continue
                raise ValueError(f"param {name!r} may not be None")
            out[name] = spec.coerce(value)
        return out
