# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Prototype registry — the replacement for ksonnet's prototype index.

A :class:`Prototype` is a named, documented manifest generator: the
typed equivalent of one ``*.jsonnet`` prototype file (reference
``kubeflow/*/prototypes/``). A builder takes one argument — the
resolved (typed) params dict — and returns a list of Kubernetes
objects (plain dicts); the target namespace is, by convention, a
``namespace`` param (the reference threaded namespace as a param
everywhere too, e.g. ``kubeflow/core/tf-job.libsonnet:2-3``). The
CLI's ``generate``/``show``/``apply`` drive this registry the way
``ks generate``/``ks show``/``ks apply`` drove ksonnet's.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Sequence

from kubeflow_tpu.params.spec import Param, ParamSet

Builder = Callable[[Dict[str, Any]], List[dict]]

_REGISTRY: Dict[str, "Prototype"] = {}


@dataclasses.dataclass(frozen=True)
class Prototype:
    name: str
    description: str
    params: Sequence[Param]
    builder: Builder
    package: str = "core"

    def param_set(self) -> ParamSet:
        return ParamSet(self.params)

    def build(self, overrides: Dict[str, Any] | None = None) -> List[dict]:
        resolved = self.param_set().overlay(overrides or {}).resolve()
        objects = self.builder(resolved)
        return [o for o in objects if o]


def register(
    name: str,
    description: str,
    params: Sequence[Param],
    package: str = "core",
) -> Callable[[Builder], Builder]:
    """Decorator registering a builder function as a prototype."""

    def wrap(fn: Builder) -> Builder:
        if name in _REGISTRY:
            raise ValueError(f"prototype {name!r} already registered")
        _REGISTRY[name] = Prototype(
            name=name, description=description, params=tuple(params), builder=fn,
            package=package,
        )
        return fn

    return wrap


def get_prototype(name: str) -> Prototype:
    _ensure_loaded()
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown prototype {name!r}; available: {sorted(_REGISTRY)}"
        ) from None


def list_prototypes() -> List[Prototype]:
    _ensure_loaded()
    return sorted(_REGISTRY.values(), key=lambda p: (p.package, p.name))


def _ensure_loaded() -> None:
    """Import all manifest component modules so their prototypes register."""
    import kubeflow_tpu.manifests  # noqa: F401  (side-effect imports)
