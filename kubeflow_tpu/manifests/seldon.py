"""Seldon-core alternative serving graph platform.

Replaces reference ``kubeflow/seldon``: core deployments (apife,
cluster-manager, redis) patched-over-JSON ``core.libsonnet:19-96``,
SeldonDeployment CRD ``crd.libsonnet``, and the ``serve-simple``
single-model prototype ``serve-simple.libsonnet:3-52``. Kept at the
reference's scope (optional component); the CRD schema is the v1
preserve-unknown-fields form rather than the reference's 3,336-line
inline openAPIV3 schema.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.manifests import k8s
from kubeflow_tpu.params import Param, REQUIRED, register

APIFE_IMAGE = "seldonio/apife:0.1.5"
OPERATOR_IMAGE = "seldonio/cluster-manager:0.1.5"
ENGINE_IMAGE = "seldonio/engine:0.1.5"
REDIS_IMAGE = "redis:4.0.1"


def crd() -> Dict[str, Any]:
    return k8s.crd("seldondeployments.machinelearning.seldon.io",
                   "machinelearning.seldon.io", "v1alpha1",
                   "SeldonDeployment", "seldondeployments",
                   short_names=["sdep"])


def core(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    ns = p["namespace"]
    name = p["name"]
    objs: List[Dict[str, Any]] = [crd()]
    if p["with_rbac"]:
        objs += [
            k8s.service_account("seldon", ns),
            k8s.cluster_role_binding(
                f"seldon-{ns}", "cluster-admin",
                [k8s.subject("ServiceAccount", "seldon", ns)]),
        ]
    if p["with_apife"]:
        apife = k8s.container(
            "seldon-apiserver-container", p["apife_image"],
            ports=[k8s.port(8080), k8s.port(5000)],
            env=[k8s.env_var("SELDON_ENGINE_KAFKA_SERVER", "kafka:9092"),
                 k8s.env_var("SELDON_CLUSTER_MANAGER_REDIS_HOST", "redis")],
        )
        objs += [
            k8s.deployment("seldon-apiserver", ns,
                           k8s.pod_spec([apife], service_account="seldon"),
                           labels={"app": "seldon-apiserver"}),
            k8s.service("seldon-apiserver", ns, {"app": "seldon-apiserver"},
                        [k8s.service_port(8080, name="http"),
                         k8s.service_port(5000, name="grpc")],
                        service_type=p["apife_service_type"]),
        ]
    manager_env = [
        k8s.env_var("SELDON_CLUSTER_MANAGER_REDIS_HOST", "redis"),
        k8s.env_var("SELDON_CLUSTER_MANAGER_POD_NAMESPACE",
                    field_path="metadata.namespace"),
        k8s.env_var("SELDON_ENGINE_IMAGE", p["engine_image"]),
    ]
    if p["operator_java_opts"]:
        manager_env.append(k8s.env_var("JAVA_OPTS", p["operator_java_opts"]))
    if p["operator_spring_opts"]:
        manager_env.append(k8s.env_var("SPRING_OPTS", p["operator_spring_opts"]))
    manager = k8s.container(
        "seldon-cluster-manager-container", p["operator_image"],
        ports=[k8s.port(8080)], env=manager_env)
    redis = k8s.container("redis", REDIS_IMAGE, ports=[k8s.port(6379)])
    objs += [
        k8s.deployment("seldon-cluster-manager", ns,
                       k8s.pod_spec([manager], service_account="seldon"),
                       labels={"app": "seldon-cluster-manager"}),
        k8s.deployment("redis", ns, k8s.pod_spec([redis]),
                       labels={"app": "redis"}),
        k8s.service("redis", ns, {"app": "redis"},
                    [k8s.service_port(6379)]),
    ]
    del name
    return objs


register("seldon", "Seldon-core serving graph platform", [
    Param("name", "seldon", "string"),
    Param("namespace", "default", "string"),
    Param("with_rbac", "true", "bool"),
    Param("with_apife", "false", "bool"),
    Param("apife_image", APIFE_IMAGE, "string"),
    Param("apife_service_type", "NodePort", "string"),
    Param("operator_image", OPERATOR_IMAGE, "string"),
    Param("operator_java_opts", "", "string"),
    Param("operator_spring_opts", "", "string"),
    Param("engine_image", ENGINE_IMAGE, "string"),
], package="seldon")(core)


def serve_simple(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Single-model SeldonDeployment graph (parity
    ``serve-simple.libsonnet:3-52``)."""
    name = p["name"]
    return [{
        "apiVersion": "machinelearning.seldon.io/v1alpha1",
        "kind": "SeldonDeployment",
        "metadata": k8s.metadata(name, p["namespace"],
                                 labels={"app": "seldon"}),
        "spec": {
            "name": name,
            "oauth_key": "oauth-key",
            "oauth_secret": "oauth-secret",
            "predictors": [{
                "name": name,
                "replicas": p["replicas"],
                "annotations": {"predictor_version": "v1"},
                "componentSpec": {
                    "spec": k8s.pod_spec([
                        k8s.container(
                            "classifier", p["image"],
                            image_pull_policy="IfNotPresent")
                    ])
                },
                "graph": {
                    "name": "classifier",
                    "type": "MODEL",
                    "endpoint": {"type": p["endpoint"]},
                    "children": [],
                },
            }],
        },
    }]


register("seldon-serve-simple", "Single-model Seldon serving graph", [
    Param("name", REQUIRED, "string", "Name to give this deployment."),
    Param("namespace", "default", "string"),
    Param("image", REQUIRED, "string",
          "Docker image which contains this model."),
    Param("replicas", 1, "int"),
    Param("endpoint", "REST", "string", "REST or GRPC."),
], package="seldon")(serve_simple)
