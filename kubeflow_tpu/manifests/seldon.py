# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Seldon-core alternative serving graph platform.

Replaces reference ``kubeflow/seldon``: core deployments (apife,
cluster-manager, redis) patched-over-JSON ``core.libsonnet:19-96``,
SeldonDeployment CRD with openAPIV3 admission validation
``crd.libsonnet:1-254`` (+ the embedded pod-template schema,
``json/pod-template-spec-validation.json``), and the ``serve-simple``
single-model prototype ``serve-simple.libsonnet:3-52``.

The validation schema is *generated*, not vendored: the reference
unrolled its inference-graph recursion by hand three levels deep and
pasted a 3,336-line swagger-derived PodTemplateSpec JSON; here a
recursive builder emits the graph levels and a typed subset of
PodTemplateSpec covers the fields Seldon graphs actually set (with the
same hard requirement the reference enforced: ``spec.containers``).
Enum vocabularies (PredictiveUnit type/implementation/methods,
endpoint type) are Seldon's public v1alpha1 API constants.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.manifests import k8s
from kubeflow_tpu.params import Param, REQUIRED, register

APIFE_IMAGE = "seldonio/apife:0.1.5"
OPERATOR_IMAGE = "seldonio/cluster-manager:0.1.5"
ENGINE_IMAGE = "seldonio/engine:0.1.5"
REDIS_IMAGE = "redis:4.0.1"

#: Seldon v1alpha1 PredictiveUnit enums (public API constants; the
#: reference repeats them at every unrolled graph level,
#: crd.libsonnet:85-130).
PREDICTIVE_UNIT_TYPES = [
    "UNKNOWN_TYPE", "ROUTER", "COMBINER", "MODEL", "TRANSFORMER",
    "OUTPUT_TRANSFORMER",
]
PREDICTIVE_UNIT_IMPLEMENTATIONS = [
    "UNKNOWN_IMPLEMENTATION", "SIMPLE_MODEL", "SIMPLE_ROUTER",
    "RANDOM_ABTEST", "AVERAGE_COMBINER",
]
PREDICTIVE_UNIT_METHODS = [
    "TRANSFORM_INPUT", "TRANSFORM_OUTPUT", "ROUTE", "AGGREGATE",
    "SEND_FEEDBACK",
]


def _endpoint_schema() -> Dict[str, Any]:
    return {"type": "object",
            "x-kubernetes-preserve-unknown-fields": True,
            "properties": {
        "service_host": {"type": "string"},
        "service_port": {"type": "integer"},
        "type": {"type": "string", "enum": ["REST", "GRPC"]},
    }}


def graph_node_schema(depth: int) -> Dict[str, Any]:
    """Inference-graph node. The reference validated three nested
    levels of ``children`` then left deeper levels free-form
    (``crd.libsonnet:50-58`` bottoms out at ``items: {}``); ``depth``
    counts the validated child levels below this node."""
    node: Dict[str, Any] = {
        "type": "object",
        "x-kubernetes-preserve-unknown-fields": True,  # e.g. parameters
        "properties": {
        "name": {"type": "string"},
        "type": {"type": "string", "enum": PREDICTIVE_UNIT_TYPES},
        "implementation": {"type": "string",
                           "enum": PREDICTIVE_UNIT_IMPLEMENTATIONS},
        "methods": {"type": "array",
                    "items": {"type": "string",
                              "enum": PREDICTIVE_UNIT_METHODS}},
        "endpoint": _endpoint_schema(),
        # Below the validated levels the graph is free-form (v1
        # structural schemas still need typed items, hence the
        # preserve-unknown-fields object instead of the reference's
        # v1beta1 bare ``items: {}``).
        "children": ({"type": "array", "items": graph_node_schema(depth - 1)}
                     if depth > 0 else
                     {"type": "array",
                      "items": {"type": "object",
                                "x-kubernetes-preserve-unknown-fields": True}}),
    }}
    return node


def _container_schema() -> Dict[str, Any]:
    # preserve-unknown-fields on the subset nodes: v1 CRDs *prune*
    # unknown fields (the reference's v1beta1 schema never did), so a
    # typed-subset schema without it would silently strip valid k8s
    # fields outside the subset (probes, valueFrom, emptyDir, ...).
    # Typed fields below are still validated; unknown siblings pass
    # through — the reference's admission behavior.
    return {"type": "object", "required": ["name"],
            "x-kubernetes-preserve-unknown-fields": True,
            "properties": {
        "name": {"type": "string"},
        "image": {"type": "string"},
        "imagePullPolicy": {"type": "string",
                            "enum": ["Always", "IfNotPresent", "Never"]},
        "command": {"type": "array", "items": {"type": "string"}},
        "args": {"type": "array", "items": {"type": "string"}},
        "workingDir": {"type": "string"},
        "ports": {"type": "array", "items": {
            "type": "object",
            "x-kubernetes-preserve-unknown-fields": True,
            "properties": {
                "containerPort": {"type": "integer"},
                "name": {"type": "string"},
                "protocol": {"type": "string", "enum": ["TCP", "UDP"]},
            }}},
        "env": {"type": "array", "items": {
            "type": "object", "required": ["name"],
            "x-kubernetes-preserve-unknown-fields": True,  # valueFrom
            "properties": {
                "name": {"type": "string"},
                "value": {"type": "string"},
            }}},
        "resources": {"type": "object", "properties": {
            "limits": {"type": "object", "additionalProperties": {
                "x-kubernetes-int-or-string": True}},
            "requests": {"type": "object", "additionalProperties": {
                "x-kubernetes-int-or-string": True}},
        }},
        "volumeMounts": {"type": "array", "items": {
            "type": "object", "required": ["name", "mountPath"],
            "properties": {
                "name": {"type": "string"},
                "mountPath": {"type": "string"},
                "readOnly": {"type": "boolean"},
            }}},
    }}


def pod_template_schema() -> Dict[str, Any]:
    """PodTemplateSpec subset (the reference pasted the full
    swagger-derived JSON; same load-bearing constraint —
    ``spec.containers`` required — plus types for the fields serving
    graphs actually set)."""
    return {"type": "object", "properties": {
        "metadata": {"type": "object",
                     "x-kubernetes-preserve-unknown-fields": True},
        "spec": {"type": "object", "required": ["containers"],
                 "x-kubernetes-preserve-unknown-fields": True,
                 "properties": {
            "containers": {"type": "array", "items": _container_schema()},
            "initContainers": {"type": "array",
                               "items": _container_schema()},
            "restartPolicy": {"type": "string",
                              "enum": ["Always", "OnFailure", "Never"]},
            "dnsPolicy": {"type": "string"},
            "hostNetwork": {"type": "boolean"},
            "serviceAccountName": {"type": "string"},
            "terminationGracePeriodSeconds": {"type": "integer"},
            "nodeSelector": {"type": "object",
                             "additionalProperties": {"type": "string"}},
            "volumes": {"type": "array", "items": {
                "type": "object", "required": ["name"],
                "x-kubernetes-preserve-unknown-fields": True,
                "properties": {"name": {"type": "string"}}}},
            "securityContext": {"type": "object",
                                "x-kubernetes-preserve-unknown-fields": True,
                                "properties": {
                "runAsUser": {"type": "integer"},
                "runAsNonRoot": {"type": "boolean"},
                "fsGroup": {"type": "integer"},
            }},
        }},
    }}


def seldon_deployment_schema() -> Dict[str, Any]:
    """The CRD's openAPIV3 admission schema (reference
    ``crd.libsonnet:23-247``: spec.{annotations,name,oauth_key,
    oauth_secret,predictors[...]} with graph + componentSpec
    validation)."""
    predictor = {"type": "object",
                 "x-kubernetes-preserve-unknown-fields": True,
                 "properties": {
        "annotations": {"type": "object",
                        "additionalProperties": {"type": "string"}},
        "name": {"type": "string"},
        "replicas": {"type": "integer"},
        "graph": graph_node_schema(2),
        "componentSpec": pod_template_schema(),
    }}
    return {"type": "object",
            "x-kubernetes-preserve-unknown-fields": True,
            "properties": {
        "spec": {"type": "object",
                 "x-kubernetes-preserve-unknown-fields": True,
                 "properties": {
            "annotations": {"type": "object",
                            "additionalProperties": {"type": "string"}},
            "name": {"type": "string"},
            "oauth_key": {"type": "string"},
            "oauth_secret": {"type": "string"},
            "predictors": {"type": "array", "items": predictor},
        }},
    }}


def crd() -> Dict[str, Any]:
    return k8s.crd("seldondeployments.machinelearning.seldon.io",
                   "machinelearning.seldon.io", "v1alpha1",
                   "SeldonDeployment", "seldondeployments",
                   short_names=["sdep"],
                   schema=seldon_deployment_schema())


def core(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    ns = p["namespace"]
    name = p["name"]
    objs: List[Dict[str, Any]] = [crd()]
    if p["with_rbac"]:
        objs += [
            k8s.service_account("seldon", ns),
            k8s.cluster_role_binding(
                f"seldon-{ns}", "cluster-admin",
                [k8s.subject("ServiceAccount", "seldon", ns)]),
        ]
    if p["with_apife"]:
        apife = k8s.container(
            "seldon-apiserver-container", p["apife_image"],
            ports=[k8s.port(8080), k8s.port(5000)],
            env=[k8s.env_var("SELDON_ENGINE_KAFKA_SERVER", "kafka:9092"),
                 k8s.env_var("SELDON_CLUSTER_MANAGER_REDIS_HOST", "redis")],
        )
        objs += [
            k8s.deployment("seldon-apiserver", ns,
                           k8s.pod_spec([apife], service_account="seldon"),
                           labels={"app": "seldon-apiserver"}),
            k8s.service("seldon-apiserver", ns, {"app": "seldon-apiserver"},
                        [k8s.service_port(8080, name="http"),
                         k8s.service_port(5000, name="grpc")],
                        service_type=p["apife_service_type"]),
        ]
    manager_env = [
        k8s.env_var("SELDON_CLUSTER_MANAGER_REDIS_HOST", "redis"),
        k8s.env_var("SELDON_CLUSTER_MANAGER_POD_NAMESPACE",
                    field_path="metadata.namespace"),
        k8s.env_var("SELDON_ENGINE_IMAGE", p["engine_image"]),
    ]
    if p["operator_java_opts"]:
        manager_env.append(k8s.env_var("JAVA_OPTS", p["operator_java_opts"]))
    if p["operator_spring_opts"]:
        manager_env.append(k8s.env_var("SPRING_OPTS", p["operator_spring_opts"]))
    manager = k8s.container(
        "seldon-cluster-manager-container", p["operator_image"],
        ports=[k8s.port(8080)], env=manager_env)
    redis = k8s.container("redis", REDIS_IMAGE, ports=[k8s.port(6379)])
    objs += [
        k8s.deployment("seldon-cluster-manager", ns,
                       k8s.pod_spec([manager], service_account="seldon"),
                       labels={"app": "seldon-cluster-manager"}),
        k8s.deployment("redis", ns, k8s.pod_spec([redis]),
                       labels={"app": "redis"}),
        k8s.service("redis", ns, {"app": "redis"},
                    [k8s.service_port(6379)]),
    ]
    del name
    return objs


register("seldon", "Seldon-core serving graph platform", [
    Param("name", "seldon", "string"),
    Param("namespace", "default", "string"),
    Param("with_rbac", "true", "bool"),
    Param("with_apife", "false", "bool"),
    Param("apife_image", APIFE_IMAGE, "string"),
    Param("apife_service_type", "NodePort", "string"),
    Param("operator_image", OPERATOR_IMAGE, "string"),
    Param("operator_java_opts", "", "string"),
    Param("operator_spring_opts", "", "string"),
    Param("engine_image", ENGINE_IMAGE, "string"),
], package="seldon")(core)


def serve_simple(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Single-model SeldonDeployment graph (parity
    ``serve-simple.libsonnet:3-52``)."""
    name = p["name"]
    return [{
        "apiVersion": "machinelearning.seldon.io/v1alpha1",
        "kind": "SeldonDeployment",
        "metadata": k8s.metadata(name, p["namespace"],
                                 labels={"app": "seldon"}),
        "spec": {
            "name": name,
            "oauth_key": "oauth-key",
            "oauth_secret": "oauth-secret",
            "predictors": [{
                "name": name,
                "replicas": p["replicas"],
                "annotations": {"predictor_version": "v1"},
                "componentSpec": {
                    "spec": k8s.pod_spec([
                        k8s.container(
                            "classifier", p["image"],
                            image_pull_policy="IfNotPresent")
                    ])
                },
                "graph": {
                    "name": "classifier",
                    "type": "MODEL",
                    "endpoint": {"type": p["endpoint"]},
                    "children": [],
                },
            }],
        },
    }]


register("seldon-serve-simple", "Single-model Seldon serving graph", [
    Param("name", REQUIRED, "string", "Name to give this deployment."),
    Param("namespace", "default", "string"),
    Param("image", REQUIRED, "string",
          "Docker image which contains this model."),
    Param("replicas", 1, "int"),
    Param("endpoint", "REST", "string", "REST or GRPC."),
], package="seldon")(serve_simple)
