# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""JupyterHub notebook hub with a TPU-aware spawner.

Replaces reference ``kubeflow/core/jupyterhub.libsonnet`` (ConfigMap
assembly ``:17-89``, services ``:91-140``, StatefulSet ``:143-202``,
RBAC ``:204-258``) and ``kubeflow/core/jupyterhub_spawner.py``.

TPU-native deltas: the spawner form requests ``google.com/tpu`` chips
(+ node selectors) instead of free-text ``nvidia.com/gpu`` JSON; the
default notebook image carries a jax[tpu] kernel; everything else
(per-user PVC, culling off, LB + headless services) keeps the
reference's semantics.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict, List

from kubeflow_tpu.manifests import k8s
from kubeflow_tpu.params import Param, register

DEFAULT_HUB_IMAGE = "ghcr.io/kubeflow-tpu/jupyterhub-k8s:v0.1.0"
DEFAULT_NOTEBOOK_IMAGE = "ghcr.io/kubeflow-tpu/jax-notebook:v0.1.0"

_SPAWNER_PATH = Path(__file__).resolve().parent.parent / "hub" / "spawner_config.py"


def hub_config_map(namespace: str, *, authenticator: str,
                   notebook_image: str) -> Dict[str, Any]:
    """Assemble jupyterhub_config.py from the spawner module + the
    chosen authenticator block (parity with the importstr+concat
    pattern at reference ``jupyterhub.libsonnet:17-89``)."""
    spawner = _SPAWNER_PATH.read_text()
    if authenticator == "iap":
        auth_block = (
            "c.JupyterHub.authenticator_class = "
            "'jhub_remote_user_authenticator.remote_user_auth."
            "RemoteUserAuthenticator'\n"
            "c.RemoteUserAuthenticator.header_name = 'x-goog-authenticated-"
            "user-email'\n"
        )
    else:
        auth_block = (
            "c.JupyterHub.authenticator_class = 'dummyauthenticator."
            "DummyAuthenticator'\n"
        )
    config = "\n".join([
        spawner,
        auth_block,
        f"c.KubeSpawner.image = '{notebook_image}'",
        "",
    ])
    return k8s.config_map("tpu-hub-config", namespace,
                          {"jupyterhub_config.py": config})


def hub_services(namespace: str, service_type: str) -> List[Dict[str, Any]]:
    labels = {"app": "tpu-hub"}
    return [
        # Headless service for the StatefulSet (parity :91-113).
        k8s.service("tpu-hub-0", namespace, labels,
                    [k8s.service_port(8000, name="hub")],
                    cluster_ip="None", labels=labels),
        # User-facing LB/ClusterIP service (parity :115-140) routed via
        # Ambassador annotation.
        k8s.service(
            "tpu-hub-lb", namespace, labels,
            [k8s.service_port(80, target_port=8000, name="hub")],
            service_type=service_type,
            annotations={
                "getambassador.io/config": k8s.ambassador_mapping(
                    "tpu-hub-lb-hub-mapping", "/hub/",
                    f"tpu-hub-lb.{namespace}", rewrite="/hub/",
                    use_websocket=True,
                ) + "\n" + k8s.ambassador_mapping(
                    "tpu-hub-lb-user-mapping", "/user/",
                    f"tpu-hub-lb.{namespace}", rewrite="/user/",
                    use_websocket=True,
                )
            },
        ),
    ]


def hub_statefulset(namespace: str, image: str) -> Dict[str, Any]:
    labels = {"app": "tpu-hub"}
    container = k8s.container(
        "tpu-hub", image,
        command=["jupyterhub", "-f", "/etc/config/jupyterhub_config.py"],
        ports=[k8s.port(8000, "hub"), k8s.port(8081, "api")],
        volume_mounts=[k8s.volume_mount("config-volume", "/etc/config")],
        env=[
            k8s.env_var("NOTEBOOK_PVC_SIZE", "10Gi"),
            k8s.env_var("KFT_NAMESPACE", field_path="metadata.namespace"),
        ],
    )
    return k8s.stateful_set(
        "tpu-hub", namespace,
        k8s.pod_spec(
            [container],
            volumes=[k8s.volume("config-volume", config_map_name="tpu-hub-config")],
            service_account="tpu-hub",
        ),
        service_name="tpu-hub-0", labels=labels,
    )


def hub_rbac(namespace: str) -> List[Dict[str, Any]]:
    """Parity: reference ``jupyterhub.libsonnet:204-258`` — the hub
    spawns/culls user pods + PVCs in its namespace."""
    return [
        k8s.service_account("tpu-hub", namespace, labels={"app": "tpu-hub"}),
        k8s.role("tpu-hub", namespace, [
            k8s.policy_rule([""], ["pods", "persistentvolumeclaims"],
                            ["get", "watch", "list", "create", "delete"]),
            k8s.policy_rule([""], ["events"], ["get", "watch", "list"]),
        ]),
        k8s.role_binding("tpu-hub", namespace, "tpu-hub",
                         [k8s.subject("ServiceAccount", "tpu-hub", namespace)]),
    ]


def all_objects(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    ns = p["namespace"]
    return [
        hub_config_map(ns, authenticator=p["jupyter_hub_authenticator"],
                       notebook_image=p["notebook_image"]),
        *hub_services(ns, p["jupyter_hub_service_type"]),
        hub_statefulset(ns, p["jupyter_hub_image"]),
        *hub_rbac(ns),
    ]


HUB_PARAMS = [
    Param("namespace", "default", "string"),
    Param("jupyter_hub_image", DEFAULT_HUB_IMAGE, "string",
          "The image to use for JupyterHub."),
    Param("notebook_image", DEFAULT_NOTEBOOK_IMAGE, "string",
          "Default single-user notebook image (jax[tpu] kernel)."),
    Param("jupyter_hub_authenticator", "dummy", "string",
          "The authenticator to use: dummy or iap."),
    Param("jupyter_hub_service_type", "ClusterIP", "string",
          "The service type for JupyterHub."),
]

register("jupyterhub", "JupyterHub with TPU-aware KubeSpawner",
         HUB_PARAMS, package="core")(all_objects)
