# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""TPU model serving: model-server Deployment + Service (+ mixins).

Replaces reference ``kubeflow/tf-serving/tf-serving.libsonnet``:
late-bound params + CPU/GPU image selection ``:22-27``, model-server
container ``:102-128``, HTTP proxy sidecar ``:143-170``, non-root
Deployment ``:173-202``, Service with Ambassador mappings ``:204-249``,
S3 mixin ``:253-283``, GCP mixin ``:285-327``.

TPU-native redesign: ONE server image — the kubeflow_tpu model server
(kubeflow_tpu.serving) hosting XLA-compiled models on TPU via jax —
so the numGpus/image-pair selection logic disappears; instead a
``tpu_chips`` param adds ``google.com/tpu`` limits + node selectors
(zero-CUDA invariant). The REST proxy keeps the reference's route
grammar (``/model/<name>[:predict|:classify]``).
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.manifests import k8s
from kubeflow_tpu.params import Param, REQUIRED, register
from kubeflow_tpu.manifests.tpujob import (
    TPU_ACCEL_SELECTOR,
    TPU_RESOURCE,
    TPU_TOPO_SELECTOR,
)

DEFAULT_SERVER_IMAGE = "ghcr.io/kubeflow-tpu/model-server:v0.1.0"
DEFAULT_PROXY_IMAGE = "ghcr.io/kubeflow-tpu/model-server-http-proxy:v0.1.0"


def server_container(p: Dict[str, Any]) -> Dict[str, Any]:
    """Model-server container (parity ``tf-serving.libsonnet:102-128``:
    ``tensorflow_model_server --port=9000 --model_name=...
    --model_base_path=...``)."""
    args = [
        "--port=9000",        # native gRPC PredictionService
        "--rest_port=8500",   # REST + gRPC-Web
        f"--model_name={p['model_name']}",
        f"--model_base_path={p['model_path']}",
        f"--version_policy={p['version_policy']}",
    ]
    if p.get("role") and p["role"] != "any":
        # Prefill/decode pool splitting (docs/scaling.md): the role
        # rides /healthz so the router and autoscaler see it even
        # before the endpoints file carries it.
        args.append(f"--role={p['role']}")
    if p.get("continuous_batching"):
        args.append("--continuous_batching")
    mounts = []
    if p.get("tenant_policy"):
        # Multi-tenant quotas/weights (docs/tenancy.md): the policy
        # file rides a ConfigMap mount; the server hot-reloads it
        # with last-good-on-malformed semantics, so editing the
        # ConfigMap retunes quotas without a rollout.
        args.append("--tenant_policy=/etc/kft-tenancy/policy.json")
        mounts.append(k8s.volume_mount("tenant-policy",
                                       "/etc/kft-tenancy",
                                       read_only=True))
    container = k8s.container(
        p["name"], p["model_server_image"],
        command=["python", "-m", "kubeflow_tpu.serving.server"],
        args=args,
        volume_mounts=mounts or None,
        ports=[k8s.port(9000, "grpc"), k8s.port(8500, "rest")],
        # Model load + first XLA compile takes tens of seconds to
        # minutes. The server opens its ports immediately and /healthz
        # answers 503 until every model has a loaded version, so:
        # readiness (/healthz) gates traffic on actual model
        # availability; liveness (/livez) only checks the process;
        # the startup probe gives slow gs:// loads a 10-minute budget
        # before liveness can kill anything. (The reference set no
        # probes at all — observed warmup 502s motivated these.)
        readiness_probe=k8s.http_get_probe("/healthz", 8500,
                                           initial_delay=5, period=5),
        liveness_probe=k8s.http_get_probe("/livez", 8500,
                                          initial_delay=0, period=30),
        startup_probe=k8s.http_get_probe("/livez", 8500, initial_delay=0,
                                         period=10, failure_threshold=60),
        resources=k8s.resources(
            cpu_request="1", memory_request="1Gi",
            cpu_limit="4", memory_limit="4Gi",
            extra_limits=({TPU_RESOURCE: p["tpu_chips"]}
                          if p["tpu_chips"] else None),
        ),
        image_pull_policy="IfNotPresent",
    )
    return container


def proxy_container(p: Dict[str, Any]) -> Dict[str, Any]:
    """REST→server proxy sidecar (parity ``:143-170``)."""
    return k8s.container(
        f"{p['name']}-http-proxy", p["http_proxy_image"],
        command=["python", "-m", "kubeflow_tpu.serving.http_proxy"],
        args=["--port=8000", "--rpc_port=8500", "--grpc_port=9000",
              "--rpc_timeout=10.0"],
        ports=[k8s.port(8000, "http")],
        resources=k8s.resources(cpu_request="500m", memory_request="500Mi",
                                cpu_limit="1", memory_limit="1Gi"),
    )


def deployment(p: Dict[str, Any]) -> Dict[str, Any]:
    containers = [server_container(p)]
    if p["http_proxy"]:
        containers.append(proxy_container(p))
    node_selector = None
    if p["tpu_chips"]:
        node_selector = {TPU_ACCEL_SELECTOR: p["tpu_accelerator"]}
        if p["tpu_topology"]:
            node_selector[TPU_TOPO_SELECTOR] = p["tpu_topology"]
    spec = k8s.pod_spec(
        containers,
        node_selector=node_selector,
    )
    if p.get("tenant_policy"):
        spec.setdefault("volumes", []).append(k8s.volume(
            "tenant-policy", config_map_name=p["tenant_policy"]))
    # Non-root (parity ``:173-202`` runAsUser/fsGroup 1000).
    spec["securityContext"] = {"runAsUser": 1000, "fsGroup": 1000}
    # With the router (autoscaler) enabled the scale subresource owns
    # spec.replicas; pinning it here would make every manifest
    # re-apply stomp the autoscaler's writes back to the static param
    # (the documented HPA-vs-manifest conflict — omit replicas so the
    # field stays with whoever scaled it last; the apiserver defaults
    # a brand-new Deployment to 1).
    labels = {"app": p["name"]}
    if p.get("role") and p["role"] != "any":
        labels["kft-role"] = p["role"]
    return k8s.deployment(p["name"], p["namespace"], spec,
                          replicas=(None if p["router"]
                                    else int(p["replicas"])),
                          labels=labels)


def router_proxy_container(p: Dict[str, Any]) -> Dict[str, Any]:
    """The fleet-level pooled proxy: routes requests across the
    serving Deployment's replicas (balancer + per-replica breakers +
    failover, serving/http_proxy.py) from the endpoints file the
    autoscaler sidecar maintains in the shared volume."""
    return k8s.container(
        f"{p['name']}-router", p["http_proxy_image"],
        command=["python", "-m", "kubeflow_tpu.serving.http_proxy"],
        args=["--port=8000",
              "--endpoints_file=/fleet/endpoints.json",
              f"--balancer={p['balancer']}",
              "--probe_interval=1.0",
              "--rpc_timeout=10.0"],
        ports=[k8s.port(8000, "http")],
        readiness_probe=k8s.http_get_probe("/healthz", 8000,
                                           initial_delay=2, period=5),
        volume_mounts=[k8s.volume_mount("fleet", "/fleet",
                                        read_only=True)],
        resources=k8s.resources(cpu_request="500m",
                                memory_request="500Mi",
                                cpu_limit="1", memory_limit="1Gi"),
    )


def autoscaler_container(p: Dict[str, Any]) -> Dict[str, Any]:
    """Autoscaler sidecar (scaling/autoscaler.py): discovers replica
    pods by the serving Deployment's app label, scrapes their
    /healthz saturation, actuates spec.replicas through the scale
    subresource, publishes the fleet ConfigMap for the dashboard, and
    rewrites the router's endpoints file (atomic rename; the proxy
    hot-reloads it)."""
    if p.get("role_deployments"):
        # Role-split fleet: one Deployment per role pool, each scaled
        # on its own signal; membership merges into ONE role-carrying
        # endpoints file (scaling/autoscaler.py RoleSplitAutoscalerLoop).
        target = [f"--role_deployments={p['role_deployments']}"]
    else:
        target = [f"--deployment={p['name']}",
                  f"--selector=app={p['name']}"]
    return k8s.container(
        f"{p['name']}-autoscaler", p["http_proxy_image"],
        command=["python", "-m", "kubeflow_tpu.scaling.autoscaler"],
        args=target +
             [f"--namespace={p['namespace']}",
              f"--min_replicas={p['min_replicas']}",
              f"--max_replicas={p['max_replicas']}",
              f"--target_queue_wait_ms={p['target_queue_wait_ms']}",
              f"--scale_up_cooldown={p['scale_up_cooldown_s']}",
              f"--scale_down_cooldown={p['scale_down_cooldown_s']}",
              "--write_endpoints=/fleet/endpoints.json",
              "--metrics_port=9401"],
        ports=[k8s.port(9401, "metrics")],
        volume_mounts=[k8s.volume_mount("fleet", "/fleet")],
        resources=k8s.resources(cpu_request="100m",
                                memory_request="128Mi",
                                cpu_limit="500m",
                                memory_limit="256Mi"),
    )


def collector_container(p: Dict[str, Any]) -> Dict[str, Any]:
    """Fleet telemetry collector sidecar (obs/collector.py): scrapes
    every replica's /metrics via the shared endpoints file plus the
    router's own exposition, aggregates cross-replica rates, and —
    with --alerts — evaluates the default SLO set, publishing burn-
    rate alerts as Events + the kft-alerts ConfigMap the dashboard's
    Fleet health page reads."""
    return k8s.container(
        f"{p['name']}-collector", p["http_proxy_image"],
        command=["python", "-m", "kubeflow_tpu.obs.collector"],
        args=["--endpoints_file=/fleet/endpoints.json",
              "--static=localhost:8000=router",
              f"--interval={p['collector_interval_s']}",
              f"--namespace={p['namespace']}",
              "--alerts",
              "--metrics_port=9402"],
        ports=[k8s.port(9402, "collector")],
        volume_mounts=[k8s.volume_mount("fleet", "/fleet",
                                        read_only=True)],
        resources=k8s.resources(cpu_request="100m",
                                memory_request="128Mi",
                                cpu_limit="500m",
                                memory_limit="512Mi"),
    )


def router_deployment(p: Dict[str, Any]) -> Dict[str, Any]:
    """One-replica router pod in front of the serving fleet: the
    pooled proxy + the autoscaler sidecar (+ the telemetry collector
    with ``collector true``), wired through a shared emptyDir
    endpoints file (the reference fronted its fleet with Ambassador
    and never closed the loop; this pod does both halves)."""
    name = f"{p['name']}-router"
    containers = [router_proxy_container(p), autoscaler_container(p)]
    if p.get("collector"):
        containers.append(collector_container(p))
    spec = k8s.pod_spec(containers)
    spec["securityContext"] = {"runAsUser": 1000, "fsGroup": 1000}
    spec["volumes"] = [{"name": "fleet", "emptyDir": {}}]
    spec["serviceAccountName"] = f"{p['name']}-autoscaler"
    dep = k8s.deployment(name, p["namespace"], spec,
                         labels={"app": name})
    dep["spec"]["template"]["metadata"].setdefault(
        "annotations", {}).update({
            "prometheus.io/scrape": "true",
            "prometheus.io/port": "9401",
        })
    return dep


def router_service(p: Dict[str, Any]) -> Dict[str, Any]:
    name = f"{p['name']}-router"
    return k8s.service(
        name, p["namespace"], {"app": name},
        [k8s.service_port(8000, name="http")],
        service_type=p["service_type"])


def autoscaler_rbac(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    """SA + namespaced Role + Binding for the autoscaler sidecar —
    exactly the verbs its loop uses and nothing wider: replica-pod
    discovery (list), the serving Deployment's scale subresource
    (get/update — NOT the Deployment itself: no pod-template access),
    and the fleet-metrics ConfigMap publish (the operator_rbac
    pattern, tpujob.py, scoped to a Role since everything is
    namespace-local)."""
    name = f"{p['name']}-autoscaler"
    namespace = p["namespace"]
    labels = {"app": f"{p['name']}-router"}
    rules = [
        k8s.policy_rule([""], ["pods"], ["get", "list", "watch"]),
        k8s.policy_rule(["apps"], ["deployments/scale"],
                        ["get", "update", "patch"]),
        k8s.policy_rule([""], ["configmaps"],
                        ["get", "create", "update", "patch"]),
    ]
    if p.get("collector"):
        # The collector sidecar shares the pod's ServiceAccount and
        # additionally publishes alert Events (kft-alerts ConfigMap
        # writes are covered by the configmaps rule above).
        rules.append(k8s.policy_rule(
            [""], ["events"], ["get", "create", "patch"]))
    return [
        k8s.service_account(name, namespace, labels=labels),
        k8s.role(name, namespace, rules, labels=labels),
        k8s.role_binding(
            name, namespace, name,
            [k8s.subject("ServiceAccount", name, namespace)],
            labels=labels),
    ]


def service(p: Dict[str, Any]) -> Dict[str, Any]:
    """Native gRPC :9000 (reference contract) + REST proxy :8000 +
    server REST :8500, with Ambassador GET/POST mappings at
    ``/models/<name>/`` (parity ``:204-249``)."""
    name, ns = p["name"], p["namespace"]
    mapping = "\n".join([
        k8s.ambassador_mapping(
            f"{name}-get", f"/models/{name}/", f"{name}.{ns}:8000",
            method="GET", rewrite=f"/model/{name}"),
        k8s.ambassador_mapping(
            f"{name}-post", f"/models/{name}/", f"{name}.{ns}:8000",
            method="POST", rewrite=f"/model/{name}:predict",
            timeout_ms=10000),
        # gRPC-Web PredictionService surface (serving/wire.py); the
        # IAP Envoy's grpc_web filter bridges browser gRPC-Web
        # clients down to this path. Native gRPC clients dial :9000.
        k8s.ambassador_mapping(
            f"{name}-grpc-web",
            "/tensorflow.serving.PredictionService/",
            f"{name}.{ns}:8500", method="POST", rewrite="",
            timeout_ms=30000),
    ])
    return k8s.service(
        name, ns, {"app": name},
        [k8s.service_port(9000, name="grpc"),
         k8s.service_port(8500, name="rest"),
         k8s.service_port(8000, name="http")],
        service_type=p["service_type"],
        annotations={"getambassador.io/config": mapping},
    )


def s3_env(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    """S3 credential env (parity s3parts ``:253-283``)."""
    secret = p["s3_secret_name"]
    return [
        k8s.env_var("AWS_ACCESS_KEY_ID", secret=secret,
                    secret_key=p["s3_secret_accesskeyid_key_name"]),
        k8s.env_var("AWS_SECRET_ACCESS_KEY", secret=secret,
                    secret_key=p["s3_secret_secretaccesskey_key_name"]),
        k8s.env_var("AWS_REGION", p["s3_aws_region"]),
        k8s.env_var("S3_USE_HTTPS", p["s3_use_https"]),
        k8s.env_var("S3_VERIFY_SSL", p["s3_verify_ssl"]),
        k8s.env_var("S3_ENDPOINT", p["s3_endpoint"]),
    ]


def gcp_env_and_volume(p: Dict[str, Any]) -> Dict[str, Any]:
    """GCP credential secret mount (parity gcpParts ``:285-327``)."""
    secret = p["gcp_credential_secret_name"]
    return {
        "env": [k8s.env_var(
            "GOOGLE_APPLICATION_CREDENTIALS",
            "/secret/gcp-credentials/key.json")],
        "volume": k8s.volume("gcp-credentials", secret_name=secret),
        "mount": k8s.volume_mount("gcp-credentials", "/secret/gcp-credentials",
                                  read_only=True),
    }


def all_objects(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    from kubeflow_tpu.serving.version_policy import parse_version_policy

    p = dict(p)
    p.setdefault("model_name", None)
    p.setdefault("version_policy", "latest")
    p["model_name"] = p["model_name"] or p["name"]
    parse_version_policy(p["version_policy"])  # fail at generate time
    dep = deployment(p)
    containers = dep["spec"]["template"]["spec"]["containers"]
    if p["s3_enable"]:
        containers[0].setdefault("env", []).extend(s3_env(p))
    if p["cloud"] == "gcp" and p["gcp_credential_secret_name"]:
        gcp = gcp_env_and_volume(p)
        containers[0].setdefault("env", []).extend(gcp["env"])
        containers[0].setdefault("volumeMounts", []).append(gcp["mount"])
        dep["spec"]["template"]["spec"].setdefault("volumes", []).append(
            gcp["volume"])
    objects = [dep, service(p)]
    if p["router"]:
        objects += [router_deployment(p), router_service(p)]
        objects += autoscaler_rbac(p)
    return objects


SERVING_PARAMS = [
    Param("name", REQUIRED, "string", "Name to give to each of the components."),
    Param("namespace", "default", "string"),
    Param("model_name", "", "string", "Defaults to name."),
    Param("model_path", REQUIRED, "string",
          "Versioned model base path (gs://... or s3://... or local)."),
    Param("model_server_image", DEFAULT_SERVER_IMAGE, "string"),
    Param("version_policy", "latest", "string",
          "latest | all | specific:<v>[,<v>...] — which version dirs "
          "to serve (rollback = specific:<old-version>)."),
    Param("http_proxy", "true", "bool", "Deploy the REST proxy sidecar."),
    Param("http_proxy_image", DEFAULT_PROXY_IMAGE, "string"),
    Param("service_type", "ClusterIP", "string"),
    Param("replicas", 1, "int", "Model-server replica count. Ignored "
          "with `router true`: the autoscaler then owns spec.replicas "
          "via the scale subresource (the manifest omits the field so "
          "re-applies don't stomp it) — size the fleet with "
          "min_replicas/max_replicas instead."),
    # Fleet router + autoscaler (kubeflow_tpu/scaling/; docs/scaling.md).
    Param("router", "false", "bool",
          "Deploy the fleet router pod: pooled proxy + autoscaler "
          "sidecar in front of the serving replicas."),
    Param("collector", "false", "bool",
          "Add the fleet telemetry collector sidecar to the router "
          "pod (scrapes replica /metrics, aggregates fleet rates, "
          "publishes SLO burn-rate alerts; needs `router true`)."),
    Param("collector_interval_s", 5, "int",
          "Collector scrape interval (seconds)."),
    Param("balancer", "least_saturation", "string",
          "Router policy: round_robin | least_saturation | affinity "
          "| role (prefill/decode pool splitting) | prefix "
          "(prompt-prefix affinity for prefix-cache fleets)."),
    Param("role", "any", "string",
          "Replica role for prefill/decode pool splitting: prefill | "
          "decode | any. Apply the prototype once per pool (e.g. "
          "name llm-prefill role prefill, name llm-decode role "
          "decode) and point role_deployments at both."),
    Param("tenant_policy", "", "string",
          "Name of a ConfigMap whose policy.json key holds the "
          "tenant quota/weight policy (multi-tenant isolation: "
          "per-tenant token buckets -> 429s, weighted-fair "
          "queueing; hot-reloaded with last-good-on-malformed "
          "semantics — docs/tenancy.md). Empty disables tenancy."),
    Param("continuous_batching", "false", "bool",
          "Serve generate models through the slot-based decode "
          "engine (required for KV handoff / role-split serving)."),
    Param("role_deployments", "", "string",
          "Role-split autoscaling: 'prefill=<dep>,decode=<dep>' — "
          "the router's autoscaler then scales each pool on its own "
          "signal and merges membership into one role-carrying "
          "endpoints file. Empty = single-pool autoscaling of this "
          "Deployment."),
    Param("min_replicas", 1, "int"),
    Param("max_replicas", 5, "int"),
    Param("target_queue_wait_ms", 100, "int",
          "Autoscaler saturation target: mean per-replica estimated "
          "queue wait (ms)."),
    Param("scale_up_cooldown_s", 15, "int"),
    Param("scale_down_cooldown_s", 60, "int"),
    Param("tpu_chips", 0, "int", "TPU chips per server pod (0 = CPU)."),
    Param("tpu_accelerator", "tpu-v5-lite-device", "string"),
    Param("tpu_topology", "", "string"),
    Param("cloud", "", "string", "gcp | aws | ''"),
    # S3 mixin params (parity :253-283).
    Param("s3_enable", "false", "bool"),
    Param("s3_secret_name", "", "string"),
    Param("s3_secret_accesskeyid_key_name", "AWS_ACCESS_KEY_ID", "string"),
    Param("s3_secret_secretaccesskey_key_name", "AWS_SECRET_ACCESS_KEY",
          "string"),
    Param("s3_aws_region", "us-west-1", "string"),
    Param("s3_use_https", "true", "string"),
    Param("s3_verify_ssl", "true", "string"),
    Param("s3_endpoint", "s3.us-west-1.amazonaws.com", "string"),
    # GCP mixin params (parity :285-327).
    Param("gcp_credential_secret_name", "", "string"),
]

register("tpu-serving",
         "TPU model server + REST proxy (tf-serving replacement)",
         SERVING_PARAMS, package="tpu-serving")(all_objects)
