# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Manifest components — each module registers its prototypes on import.

This package is the typed replacement for the reference's
``kubeflow/{core,tf-job,tf-serving,argo,seldon}`` jsonnet packages.
"""

# Side-effect imports: each module registers prototypes with
# kubeflow_tpu.params.registry at import time.
from kubeflow_tpu.manifests import k8s  # noqa: F401

_COMPONENT_MODULES = [
    "kubeflow_tpu.manifests.core",
    "kubeflow_tpu.manifests.tpujob",
    "kubeflow_tpu.manifests.jupyterhub",
    "kubeflow_tpu.manifests.ambassador",
    "kubeflow_tpu.manifests.iap",
    "kubeflow_tpu.manifests.cert_manager",
    "kubeflow_tpu.manifests.nfs",
    "kubeflow_tpu.manifests.spartakus",
    "kubeflow_tpu.manifests.argo",
    "kubeflow_tpu.manifests.serving",
    "kubeflow_tpu.manifests.seldon",
    "kubeflow_tpu.manifests.ci",
]

import importlib as _importlib

for _mod in _COMPONENT_MODULES:
    try:
        _importlib.import_module(_mod)
    except ModuleNotFoundError as _e:
        # Allow partial builds during bootstrap; only swallow missing
        # component modules themselves, not their broken imports.
        if _e.name != _mod:
            raise
