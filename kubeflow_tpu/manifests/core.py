# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""kubeflow-core aggregator prototype.

Replaces reference ``kubeflow/core/all.libsonnet:1-15`` +
``kubeflow/core/prototypes/all.jsonnet``: one component deploying
JupyterHub + TPUJob operator + Ambassador + NFS + telemetry.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.manifests import ambassador, jupyterhub, nfs, spartakus, tpujob
from kubeflow_tpu.params import Param, register


def all_objects(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    return (
        jupyterhub.all_objects({
            "namespace": p["namespace"],
            "jupyter_hub_image": p["jupyter_hub_image"],
            "notebook_image": p["notebook_image"],
            "jupyter_hub_authenticator": p["jupyter_hub_authenticator"],
            "jupyter_hub_service_type": p["jupyter_hub_service_type"],
        })
        + tpujob.all_objects({
            "namespace": p["namespace"],
            "tpujob_image": p["tpujob_image"],
            "tpujob_ui_image": p["tpujob_ui_image"],
            "tpujob_ui_service_type": p["tpujob_ui_service_type"],
            "cloud": p["cloud"],
        })
        + ambassador.all_objects({
            "namespace": p["namespace"],
            "ambassador_service_type": p["ambassador_service_type"],
            "replicas": 3,
        })
        + nfs.all_objects({
            "namespace": p["namespace"],
            "disks": p["disks"],
        })
        + spartakus.all_objects({
            "namespace": p["namespace"],
            "report_usage": p["report_usage"],
            "usage_id": p["usage_id"],
        })
    )


CORE_PARAMS = (
    [Param("namespace", "default", "string",
           "Namespace to use for the components.")]
    + [p for p in jupyterhub.HUB_PARAMS if p.name != "namespace"]
    + [p for p in tpujob.OPERATOR_PARAMS if p.name != "namespace"]
    + [
        Param("ambassador_service_type", "ClusterIP", "string"),
        Param("disks", "", "array"),
        Param("report_usage", "false", "bool"),
        Param("usage_id", "unknown_cluster", "string"),
    ]
)

register("kubeflow-core",
         "JupyterHub + TPUJob operator + API gateway + storage + telemetry",
         CORE_PARAMS, package="core")(all_objects)
