# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Ambassador API gateway (annotation-driven routing).

Replaces reference ``kubeflow/core/ambassador.libsonnet``: Service
``:14-37``, admin Service ``:39-62``, RBAC ``:64-145``, 3-replica
Deployment + statsd sidecar ``:147-219``, k8s-dashboard route
``:222-259``. No TPU delta — the gateway pattern carries over; other
services self-register routes via the ``getambassador.io/config``
annotation (see k8s.ambassador_mapping).
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.manifests import k8s
from kubeflow_tpu.params import Param, register

AMBASSADOR_IMAGE = "quay.io/datawire/ambassador:0.30.1"
STATSD_IMAGE = "quay.io/datawire/statsd:0.30.1"


def services(namespace: str, service_type: str) -> List[Dict[str, Any]]:
    labels = {"service": "ambassador"}
    return [
        k8s.service("ambassador", namespace, labels,
                    [k8s.service_port(80, target_port=80, name="ambassador")],
                    service_type=service_type, labels=labels),
        k8s.service("ambassador-admin", namespace, labels,
                    [k8s.service_port(8877, target_port=8877,
                                      name="ambassador-admin")],
                    labels={"service": "ambassador-admin"}),
    ]


def rbac(namespace: str) -> List[Dict[str, Any]]:
    return [
        k8s.service_account("ambassador", namespace),
        k8s.cluster_role("ambassador", [
            k8s.policy_rule([""], ["services", "endpoints", "namespaces",
                                   "secrets"], ["get", "list", "watch"]),
        ]),
        k8s.cluster_role_binding(
            "ambassador", "ambassador",
            [k8s.subject("ServiceAccount", "ambassador", namespace)],
        ),
    ]


def deployment(namespace: str, replicas: int = 3) -> Dict[str, Any]:
    ambassador = k8s.container(
        "ambassador", AMBASSADOR_IMAGE,
        env=[
            k8s.env_var("AMBASSADOR_NAMESPACE", field_path="metadata.namespace"),
            k8s.env_var("AMBASSADOR_SINGLE_NAMESPACE", "true"),
        ],
        ports=[k8s.port(80), k8s.port(8877, "admin")],
        resources=k8s.resources(cpu_request="200m", memory_request="100Mi",
                                cpu_limit="1", memory_limit="400Mi"),
        liveness_probe=k8s.http_get_probe("/ambassador/v0/check_alive", 8877),
        readiness_probe=k8s.http_get_probe("/ambassador/v0/check_ready", 8877),
    )
    statsd = k8s.container("statsd", STATSD_IMAGE, ports=[k8s.port(8125, "metrics")])
    return k8s.deployment(
        "ambassador", namespace,
        k8s.pod_spec([ambassador, statsd], service_account="ambassador"),
        replicas=replicas, labels={"service": "ambassador"},
    )


def k8s_dashboard_route(namespace: str) -> Dict[str, Any]:
    """Route to the cluster's kubernetes-dashboard (parity :222-259)."""
    return k8s.service(
        "k8s-dashboard", namespace, {"k8s-app": "kubernetes-dashboard"},
        [k8s.service_port(443, target_port=8443)],
        annotations={
            "getambassador.io/config": k8s.ambassador_mapping(
                "k8s-dashboard-ui-mapping", "/k8s/ui/",
                "kubernetes-dashboard.kube-system", rewrite="/",
                # tls: the upstream dashboard serves https
            ) + "\ntls: true"
        },
    )


def all_objects(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    ns = p["namespace"]
    return [
        *services(ns, p["ambassador_service_type"]),
        *rbac(ns),
        deployment(ns, p["replicas"]),
        k8s_dashboard_route(ns),
    ]


register("ambassador", "Ambassador API gateway", [
    Param("namespace", "default", "string"),
    Param("ambassador_service_type", "ClusterIP", "string",
          "The service type for the API Gateway."),
    Param("replicas", 3, "int"),
], package="core")(all_objects)
