# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Typed Kubernetes object builders.

The reference generated raw K8s objects from Jsonnet (every
``*.libsonnet`` under ``kubeflow/``). Here the same objects are built by
small typed constructors returning plain dicts — plain dicts because the
output boundary is the apiserver's JSON, and golden tests diff them
directly. Keyword-only arguments + explicit apiVersion/kind per builder
replace Jsonnet's untyped object literals.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

Obj = Dict[str, Any]


def _prune(obj: Any) -> Any:
    """Drop None values recursively.

    Plays the role of the reference's ``std.prune`` over the final
    object list (``kubeflow/core/prototypes/all.jsonnet:22``), but only
    removes ``None`` — legitimately-empty objects like a volume's
    ``emptyDir: {}`` or a ConfigMap's ``data: {}`` must survive, so
    builders signal "absent" with None, never with an empty container.
    """
    if isinstance(obj, dict):
        return {k: _prune(v) for k, v in obj.items() if v is not None}
    if isinstance(obj, (list, tuple)):
        return [_prune(v) for v in obj if v is not None]
    return obj


def prune(objects: Sequence[Obj]) -> List[Obj]:
    return [_prune(o) for o in objects if o]


def metadata(
    name: str,
    namespace: Optional[str] = None,
    labels: Optional[Dict[str, str]] = None,
    annotations: Optional[Dict[str, str]] = None,
) -> Obj:
    return _prune(
        {
            "name": name,
            "namespace": namespace,
            "labels": labels,
            "annotations": annotations,
        }
    )


def env_var(name: str, value: Any = None, *, field_path: Optional[str] = None,
            secret: Optional[str] = None, secret_key: Optional[str] = None) -> Obj:
    if field_path is not None:
        return {"name": name, "valueFrom": {"fieldRef": {"fieldPath": field_path}}}
    if secret is not None:
        return {
            "name": name,
            "valueFrom": {"secretKeyRef": {"name": secret, "key": secret_key or name}},
        }
    if value is None:
        raise ValueError(
            f"env var {name!r} needs a value, field_path, or secret "
            "(pass value='' explicitly for an empty string)"
        )
    return {"name": name, "value": str(value)}


def container(
    name: str,
    image: str,
    *,
    command: Optional[Sequence[str]] = None,
    args: Optional[Sequence[str]] = None,
    env: Optional[Sequence[Obj]] = None,
    ports: Optional[Sequence[Obj]] = None,
    resources: Optional[Obj] = None,
    volume_mounts: Optional[Sequence[Obj]] = None,
    working_dir: Optional[str] = None,
    security_context: Optional[Obj] = None,
    liveness_probe: Optional[Obj] = None,
    readiness_probe: Optional[Obj] = None,
    startup_probe: Optional[Obj] = None,
    image_pull_policy: Optional[str] = None,
) -> Obj:
    return _prune(
        {
            "name": name,
            "image": image,
            "command": list(command) if command else None,
            "args": list(args) if args else None,
            "env": list(env) if env else None,
            "ports": list(ports) if ports else None,
            "resources": resources,
            "volumeMounts": list(volume_mounts) if volume_mounts else None,
            "workingDir": working_dir,
            "securityContext": security_context,
            "livenessProbe": liveness_probe,
            "readinessProbe": readiness_probe,
            "startupProbe": startup_probe,
            "imagePullPolicy": image_pull_policy,
        }
    )


def port(container_port: int, name: Optional[str] = None) -> Obj:
    return _prune({"containerPort": container_port, "name": name})


def resources(
    *,
    cpu_request: Optional[str] = None,
    memory_request: Optional[str] = None,
    cpu_limit: Optional[str] = None,
    memory_limit: Optional[str] = None,
    extra_limits: Optional[Dict[str, Any]] = None,
    extra_requests: Optional[Dict[str, Any]] = None,
) -> Obj:
    req: Obj = {}
    lim: Obj = {}
    if cpu_request:
        req["cpu"] = cpu_request
    if memory_request:
        req["memory"] = memory_request
    if cpu_limit:
        lim["cpu"] = cpu_limit
    if memory_limit:
        lim["memory"] = memory_limit
    if extra_requests:
        req.update({k: str(v) for k, v in extra_requests.items()})
    if extra_limits:
        lim.update({k: str(v) for k, v in extra_limits.items()})
    return _prune({"requests": req or None, "limits": lim or None})


def pod_spec(
    containers: Sequence[Obj],
    *,
    volumes: Optional[Sequence[Obj]] = None,
    service_account: Optional[str] = None,
    restart_policy: Optional[str] = None,
    node_selector: Optional[Dict[str, str]] = None,
    init_containers: Optional[Sequence[Obj]] = None,
    host_network: Optional[bool] = None,
    dns_policy: Optional[str] = None,
    scheduler_name: Optional[str] = None,
    tolerations: Optional[Sequence[Obj]] = None,
    subdomain: Optional[str] = None,
    hostname: Optional[str] = None,
) -> Obj:
    return _prune(
        {
            "containers": list(containers),
            "volumes": list(volumes) if volumes else None,
            "serviceAccountName": service_account,
            "restartPolicy": restart_policy,
            "nodeSelector": node_selector,
            "initContainers": list(init_containers) if init_containers else None,
            "hostNetwork": host_network,
            "dnsPolicy": dns_policy,
            "schedulerName": scheduler_name,
            "tolerations": list(tolerations) if tolerations else None,
            "subdomain": subdomain,
            "hostname": hostname,
        }
    )


def deployment(
    name: str,
    namespace: str,
    spec: Obj,
    *,
    replicas: Optional[int] = 1,
    labels: Optional[Dict[str, str]] = None,
    pod_labels: Optional[Dict[str, str]] = None,
    pod_annotations: Optional[Dict[str, str]] = None,
) -> Obj:
    pod_labels = pod_labels or labels or {"app": name}
    return _prune(
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": metadata(name, namespace, labels=labels or {"app": name}),
            "spec": {
                "replicas": replicas,
                "selector": {"matchLabels": pod_labels},
                "template": {
                    "metadata": _prune(
                        {"labels": pod_labels, "annotations": pod_annotations}
                    ),
                    "spec": spec,
                },
            },
        }
    )


def stateful_set(
    name: str,
    namespace: str,
    spec: Obj,
    *,
    service_name: str,
    replicas: int = 1,
    labels: Optional[Dict[str, str]] = None,
) -> Obj:
    labels = labels or {"app": name}
    return _prune(
        {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": metadata(name, namespace, labels=labels),
            "spec": {
                "serviceName": service_name,
                "replicas": replicas,
                "selector": {"matchLabels": labels},
                "template": {"metadata": {"labels": labels}, "spec": spec},
            },
        }
    )


def service(
    name: str,
    namespace: str,
    selector: Dict[str, str],
    ports: Sequence[Obj],
    *,
    service_type: Optional[str] = None,
    labels: Optional[Dict[str, str]] = None,
    annotations: Optional[Dict[str, str]] = None,
    cluster_ip: Optional[str] = None,
) -> Obj:
    return _prune(
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": metadata(
                name, namespace, labels=labels or {"app": name},
                annotations=annotations,
            ),
            "spec": {
                "selector": selector,
                "ports": list(ports),
                "type": service_type,
                "clusterIP": cluster_ip,
            },
        }
    )


def service_port(port_: int, *, target_port: Optional[Any] = None,
                 name: Optional[str] = None, node_port: Optional[int] = None,
                 protocol: Optional[str] = None) -> Obj:
    return _prune(
        {
            "port": port_,
            "targetPort": target_port,
            "name": name,
            "nodePort": node_port,
            "protocol": protocol,
        }
    )


def config_map(name: str, namespace: str, data: Dict[str, str],
               labels: Optional[Dict[str, str]] = None) -> Obj:
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": metadata(name, namespace, labels=labels),
        "data": data,
    }


def secret(name: str, namespace: str, string_data: Dict[str, str],
           secret_type: str = "Opaque") -> Obj:
    return {
        "apiVersion": "v1",
        "kind": "Secret",
        "metadata": metadata(name, namespace),
        "type": secret_type,
        "stringData": string_data,
    }


def namespace_obj(name: str) -> Obj:
    return {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": name}}


def service_account(name: str, namespace: str,
                    labels: Optional[Dict[str, str]] = None) -> Obj:
    return {
        "apiVersion": "v1",
        "kind": "ServiceAccount",
        "metadata": metadata(name, namespace, labels=labels),
    }


def policy_rule(api_groups: Sequence[str], resources_: Sequence[str],
                verbs: Sequence[str]) -> Obj:
    return {
        "apiGroups": list(api_groups),
        "resources": list(resources_),
        "verbs": list(verbs),
    }


def cluster_role(name: str, rules: Sequence[Obj],
                 labels: Optional[Dict[str, str]] = None) -> Obj:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRole",
        "metadata": metadata(name, labels=labels),
        "rules": list(rules),
    }


def cluster_role_binding(name: str, role_name: str, subjects: Sequence[Obj],
                         labels: Optional[Dict[str, str]] = None) -> Obj:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRoleBinding",
        "metadata": metadata(name, labels=labels),
        "roleRef": {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "ClusterRole",
            "name": role_name,
        },
        "subjects": list(subjects),
    }


def role(name: str, namespace: str, rules: Sequence[Obj],
         labels: Optional[Dict[str, str]] = None) -> Obj:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "Role",
        "metadata": metadata(name, namespace, labels=labels),
        "rules": list(rules),
    }


def role_binding(name: str, namespace: str, role_name: str,
                 subjects: Sequence[Obj],
                 labels: Optional[Dict[str, str]] = None) -> Obj:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "RoleBinding",
        "metadata": metadata(name, namespace, labels=labels),
        "roleRef": {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "Role",
            "name": role_name,
        },
        "subjects": list(subjects),
    }


def subject(kind: str, name: str, namespace: Optional[str] = None) -> Obj:
    return _prune({"kind": kind, "name": name, "namespace": namespace})


def crd(
    name: str,
    group: str,
    version: str,
    kind: str,
    plural: str,
    *,
    scope: str = "Namespaced",
    singular: Optional[str] = None,
    short_names: Optional[Sequence[str]] = None,
    schema: Optional[Obj] = None,
    status_subresource: bool = False,
) -> Obj:
    """CustomResourceDefinition (apiextensions v1, vs the reference's
    v1beta1 at ``kubeflow/core/tf-job.libsonnet:14-29``).

    ``status_subresource`` declares ``subresources.status`` — REQUIRED
    for any controller writing status through the ``/status``
    endpoint (the apiserver 404s the endpoint when undeclared)."""
    version_obj: Obj = {
        "name": version,
        "served": True,
        "storage": True,
        "schema": {
            "openAPIV3Schema": schema
            or {"type": "object", "x-kubernetes-preserve-unknown-fields": True}
        },
    }
    if status_subresource:
        version_obj["subresources"] = {"status": {}}
    return _prune(
        {
            "apiVersion": "apiextensions.k8s.io/v1",
            "kind": "CustomResourceDefinition",
            "metadata": {"name": name},
            "spec": {
                "group": group,
                "scope": scope,
                "names": _prune(
                    {
                        "kind": kind,
                        "plural": plural,
                        "singular": singular or kind.lower(),
                        "shortNames": list(short_names) if short_names else None,
                    }
                ),
                "versions": [version_obj],
            },
        }
    )


def pvc(name: str, namespace: str, storage: str,
        *, access_modes: Sequence[str] = ("ReadWriteOnce",),
        storage_class: Optional[str] = None) -> Obj:
    return _prune(
        {
            "apiVersion": "v1",
            "kind": "PersistentVolumeClaim",
            "metadata": metadata(name, namespace),
            "spec": {
                "accessModes": list(access_modes),
                "storageClassName": storage_class,
                "resources": {"requests": {"storage": storage}},
            },
        }
    )


def storage_class(name: str, provisioner: str) -> Obj:
    return {
        "apiVersion": "storage.k8s.io/v1",
        "kind": "StorageClass",
        "metadata": {"name": name},
        "provisioner": provisioner,
    }


def ingress(name: str, namespace: str, *, backend_service: str,
            backend_port: int, annotations: Optional[Dict[str, str]] = None,
            tls_secret: Optional[str] = None, host: Optional[str] = None) -> Obj:
    rule: Obj = {
        "http": {
            "paths": [
                {
                    "path": "/*",
                    "pathType": "ImplementationSpecific",
                    "backend": {
                        "service": {
                            "name": backend_service,
                            "port": {"number": backend_port},
                        }
                    },
                }
            ]
        }
    }
    if host:
        rule["host"] = host
    return _prune(
        {
            "apiVersion": "networking.k8s.io/v1",
            "kind": "Ingress",
            "metadata": metadata(name, namespace, annotations=annotations),
            "spec": {
                "rules": [rule],
                "tls": [{"secretName": tls_secret, "hosts": [host] if host else None}]
                if tls_secret
                else None,
            },
        }
    )


def http_get_probe(path: str, port_: Any, *, initial_delay: int = 30,
                   period: int = 30, timeout: Optional[int] = None,
                   failure_threshold: Optional[int] = None) -> Obj:
    return _prune(
        {
            "httpGet": {"path": path, "port": port_},
            "initialDelaySeconds": initial_delay,
            "periodSeconds": period,
            "timeoutSeconds": timeout,
            "failureThreshold": failure_threshold,
        }
    )


def volume(name: str, *, config_map_name: Optional[str] = None,
           pvc_name: Optional[str] = None, secret_name: Optional[str] = None,
           empty_dir: bool = False, host_path: Optional[str] = None) -> Obj:
    v: Obj = {"name": name}
    if config_map_name:
        v["configMap"] = {"name": config_map_name}
    elif pvc_name:
        v["persistentVolumeClaim"] = {"claimName": pvc_name}
    elif secret_name:
        v["secret"] = {"secretName": secret_name}
    elif host_path:
        v["hostPath"] = {"path": host_path}
    elif empty_dir:
        v["emptyDir"] = {}
    return v


def volume_mount(name: str, mount_path: str, *, read_only: Optional[bool] = None,
                 sub_path: Optional[str] = None) -> Obj:
    return _prune(
        {"name": name, "mountPath": mount_path, "readOnly": read_only,
         "subPath": sub_path}
    )


def ambassador_mapping(name: str, prefix: str, service_addr: str, *,
                       method: Optional[str] = None, rewrite: Optional[str] = None,
                       timeout_ms: Optional[int] = None,
                       use_websocket: Optional[bool] = None) -> str:
    """One Ambassador route mapping, rendered as the YAML annotation
    payload the reference attached to Services (annotation-driven
    routing, e.g. ``kubeflow/tf-serving/tf-serving.libsonnet:211-231``).
    """
    lines = [
        "---",
        "apiVersion: ambassador/v0",
        "kind: Mapping",
        f"name: {name}",
        f"prefix: {prefix}",
    ]
    if rewrite is not None:
        # Empty = explicit no-rewrite; must be quoted or YAML reads
        # the bare value as null (and a trailing space forces ugly
        # escaped quoting on the whole annotation).
        lines.append(f'rewrite: "{rewrite}"' if rewrite == ""
                     else f"rewrite: {rewrite}")
    if method is not None:
        lines.append(f"method: {method}")
    if timeout_ms is not None:
        lines.append(f"timeout_ms: {timeout_ms}")
    if use_websocket:
        lines.append("use_websocket: true")
    lines.append(f"service: {service_addr}")
    return "\n".join(lines)


def k8s_list(objects: Sequence[Obj]) -> Obj:
    """Wrap objects as one v1 List, the reference's apply unit
    (``k.core.v1.list.new`` in every prototype)."""
    return {"apiVersion": "v1", "kind": "List", "items": prune(objects)}
