# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""TPUJob CRD, operator, dashboard — and the TPUJob CR builders.

Replaces the reference's tf-job package and core/tf-job component:

- CRD + operator Deployment + ConfigMap + RBAC + dashboard UI:
  reference ``kubeflow/core/tf-job.libsonnet`` (CRD ``:14-29``,
  operator ``:31-95``, ConfigMap ``:98-148``, RBAC ``:150-269``,
  UI ``:271-458``).
- TFJob CR builder → TPUJob CR builder: reference
  ``kubeflow/tf-job/tf-job.libsonnet:5-56`` and prototypes
  ``tf-job.jsonnet`` / ``tf-cnn-benchmarks.jsonnet``.

TPU-native redesign (not a port):

- Replica types are {COORDINATOR, TPU_WORKER, CPU} instead of
  {MASTER, WORKER, PS}. A TPU_WORKER replica describes a *whole pod
  slice* (accelerator type + topology), gang-scheduled atomically —
  there is no parameter server; gradients ride ICI all-reduce inside
  the jitted program.
- Instead of injecting ``TF_CONFIG`` (cluster JSON), the operator
  injects the ``jax.distributed`` bootstrap env:
  ``KFT_COORDINATOR_ADDRESS``, ``KFT_NUM_PROCESSES``,
  ``KFT_PROCESS_ID``, plus ``TPU_WORKER_ID``/``TPU_WORKER_HOSTNAMES``
  for the TPU runtime (see kubeflow_tpu.operator and
  kubeflow_tpu.training.launcher).
- GPU resource limits (``nvidia.com/gpu``, reference
  ``tf-job.libsonnet:18``) become ``google.com/tpu`` limits plus
  GKE TPU node selectors (topology + accelerator).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Sequence

from kubeflow_tpu.manifests import k8s
from kubeflow_tpu.params import Param, REQUIRED, register

GROUP = "kubeflow.org"
VERSION = "v1alpha1"
KIND = "TPUJob"
PLURAL = "tpujobs"
CRD_NAME = f"{PLURAL}.{GROUP}"

REPLICA_TYPES = ("COORDINATOR", "TPU_WORKER", "CPU")

DEFAULT_OPERATOR_IMAGE = "ghcr.io/kubeflow-tpu/tpujob-operator:v0.1.0"
DEFAULT_UI_IMAGE = "ghcr.io/kubeflow-tpu/tpujob-dashboard:v0.1.0"

# GKE TPU scheduling contract (replaces nvidia.com/gpu limits).
TPU_RESOURCE = "google.com/tpu"
TPU_ACCEL_SELECTOR = "cloud.google.com/gke-tpu-accelerator"
TPU_TOPO_SELECTOR = "cloud.google.com/gke-tpu-topology"


def replica_spec(
    replica_type: str,
    replicas: int,
    *,
    image: str,
    args: Optional[Sequence[str]] = None,
    command: Optional[Sequence[str]] = None,
    tpu_accelerator: Optional[str] = None,  # e.g. "tpu-v5-lite-podslice"
    tpu_topology: Optional[str] = None,  # e.g. "2x4"
    chips_per_worker: int = 4,
    env: Optional[Sequence[Dict[str, Any]]] = None,
    resources: Optional[Dict[str, Any]] = None,
    volumes: Optional[Sequence[Dict[str, Any]]] = None,
    volume_mounts: Optional[Sequence[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """One replicaSpec of a TPUJob (parity: ``tfJobReplica``,
    reference ``kubeflow/tf-job/tf-job.libsonnet:5-35``)."""
    if replica_type not in REPLICA_TYPES:
        raise ValueError(
            f"replica_type must be one of {REPLICA_TYPES}, got {replica_type!r}"
        )
    if replica_type == "TPU_WORKER" and not (tpu_accelerator and tpu_topology):
        raise ValueError(
            "TPU_WORKER replicas need tpu_accelerator and tpu_topology "
            "(whole-slice gang scheduling contract)"
        )
    container: Dict[str, Any] = {
        "name": "kubeflow-tpu",
        "image": image,
    }
    # Deep-copy so TPU limit injection below can't leak into a resources
    # dict the caller shares across replica specs.
    resources = copy.deepcopy(resources) if resources else None
    if command:
        container["command"] = list(command)
    if args:
        container["args"] = list(args)
    if env:
        container["env"] = list(env)
    if resources:
        container["resources"] = dict(resources)
    if volume_mounts:
        container["volumeMounts"] = list(volume_mounts)
    node_selector: Optional[Dict[str, str]] = None
    if replica_type == "TPU_WORKER":
        limits = container.setdefault("resources", {}).setdefault("limits", {})
        limits[TPU_RESOURCE] = str(chips_per_worker)
        node_selector = {
            TPU_ACCEL_SELECTOR: tpu_accelerator,
            TPU_TOPO_SELECTOR: tpu_topology,
        }
    template: Dict[str, Any] = {
        "spec": k8s.pod_spec(
            [container],
            # Never, not the reference's OnFailure (tf-job.libsonnet:30):
            # recovery is slice-granular here — the operator restarts
            # the whole gang (operator/reconciler.py forces Never too),
            # so per-pod kubelet restarts would only desync the gang.
            restart_policy="Never",
            node_selector=node_selector,
            volumes=volumes,
        )
    }
    return k8s._prune(
        {
            "replicas": replicas,
            "tpuReplicaType": replica_type,
            "template": template,
        }
    )


def termination_policy(chief_name: str = "COORDINATOR",
                       chief_index: int = 0) -> Dict[str, Any]:
    """Job success is defined by one chief replica finishing (parity:
    ``tfJobTerminationPolicy``, reference ``tf-job.libsonnet:37-42``;
    chief = WORKER 0 in ``tf-cnn-benchmarks.jsonnet:100``)."""
    return {"chief": {"replicaName": chief_name, "replicaIndex": chief_index}}


def tpu_job(
    name: str,
    namespace: str,
    replica_specs: Sequence[Dict[str, Any]],
    *,
    termination: Optional[Dict[str, Any]] = None,
    recovery: str = "restart-slice",
    num_slices: int = 1,
    scheduling_deadline_seconds: Optional[int] = None,
    priority: int = 0,
    min_replicas: Optional[int] = None,
    max_replicas: Optional[int] = None,
) -> Dict[str, Any]:
    """A TPUJob CR (parity: ``tfJob``, reference
    ``tf-job.libsonnet:44-56``). ``recovery`` is new: TPU slices fail
    as a unit, so the operator restarts the whole gang from the last
    checkpoint ('restart-slice') or fails the job ('none').

    ``num_slices`` > 1 makes this a multi-slice (megascale) job: the
    operator provisions the replicaSpecs once PER SLICE — one gang per
    slice, all-or-nothing across the union — and injects
    ``MEGASCALE_COORDINATOR_ADDRESS`` / ``MEGASCALE_NUM_SLICES`` /
    ``MEGASCALE_SLICE_ID`` so the trainer's hybrid ``dcn_data`` mesh
    axis comes from the deployment. The TPU translation of the
    reference operator's cluster-spec assembly
    (``kubeflow/core/tf-job.libsonnet:31-95``, consumed as TF_CONFIG)."""
    if recovery not in ("restart-slice", "none"):
        raise ValueError(f"unknown recovery policy {recovery!r}")
    if num_slices < 1:
        raise ValueError(f"num_slices must be >= 1, got {num_slices}")
    if scheduling_deadline_seconds is not None \
            and scheduling_deadline_seconds < 1:
        raise ValueError(
            f"scheduling_deadline_seconds must be >= 1 (omit for no "
            f"deadline), got {scheduling_deadline_seconds}")
    if priority < 0:
        raise ValueError(
            f"priority must be >= 0 (0 = the default, preemptible "
            f"class), got {priority}")
    # Elastic gangs (r16): minReplicas makes the job resize through
    # worker loss instead of riding the restart budget — the operator
    # keeps the gang Running in [minReplicas, maxReplicas] and the
    # training loop reshards from its continuous checkpoint. Validated
    # at generate time: an incoherent bound silently degrades to rigid
    # inside the operator, which would surprise at the worst moment
    # (mid-preemption).
    if min_replicas is not None:
        workers = [s for s in replica_specs
                   if s.get("tpuReplicaType") == "TPU_WORKER"]
        if len(workers) != 1:
            raise ValueError(
                "elastic jobs (min_replicas) need exactly one "
                "TPU_WORKER replicaSpec")
        if num_slices > 1:
            raise ValueError(
                "elastic jobs are single-slice (a megascale SPMD "
                "program spanning slices recovers all-or-nothing)")
        desired = int(workers[0].get("replicas", 1))
        effective_max = desired if max_replicas is None else max_replicas
        if not 1 <= min_replicas <= desired <= effective_max:
            raise ValueError(
                f"need 1 <= min_replicas ({min_replicas}) <= replicas "
                f"({desired}) <= max_replicas ({effective_max})")
    elif max_replicas is not None:
        raise ValueError("max_replicas needs min_replicas (the "
                         "elastic bounds travel together)")
    return {
        "apiVersion": f"{GROUP}/{VERSION}",
        "kind": KIND,
        "metadata": k8s.metadata(name, namespace),
        "spec": k8s._prune(
            {
                "replicaSpecs": list(replica_specs),
                "terminationPolicy": termination or termination_policy(),
                "recoveryPolicy": recovery,
                # Single-slice jobs stay schema-identical to pre-r5
                # manifests (goldens, kubectl diffs): the field only
                # materializes when it means something.
                "numSlices": num_slices if num_slices > 1 else None,
                # Gang scheduling deadline: a job still Pending this
                # many seconds after submission Fails with a
                # DeadlineExceeded condition and its gang is torn
                # down, releasing the TPU slices (operator/reconciler
                # enforces it). Absent = wait forever.
                "schedulingDeadlineSeconds": scheduling_deadline_seconds,
                # Priority class (r12): a Pending gang with priority
                # > 0 approaching its scheduling deadline may preempt
                # the lowest-priority RUNNING gang (strictly lower
                # class only, globally rate-limited — see
                # docs/operator.md). 0 (the default) never preempts
                # and stays schema-identical to pre-r12 manifests.
                "priority": priority if priority else None,
                # Elastic bounds (r16): absent = rigid, schema-
                # identical to pre-r16 manifests.
                "minReplicas": min_replicas,
                "maxReplicas": (max_replicas
                                if min_replicas is not None else None),
            }
        ),
    }


def crd() -> Dict[str, Any]:
    schema = {
        "type": "object",
        "properties": {
            "spec": {
                "type": "object",
                "properties": {
                    "replicaSpecs": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "x-kubernetes-preserve-unknown-fields": True,
                            "properties": {
                                "tpuReplicaType": {
                                    "type": "string",
                                    "enum": list(REPLICA_TYPES),
                                },
                                "replicas": {"type": "integer", "minimum": 1},
                            },
                        },
                    },
                    "terminationPolicy": {
                        "type": "object",
                        "x-kubernetes-preserve-unknown-fields": True,
                    },
                    "recoveryPolicy": {
                        "type": "string",
                        "enum": ["restart-slice", "none"],
                    },
                    "numSlices": {"type": "integer", "minimum": 1},
                    "schedulingDeadlineSeconds": {
                        "type": "integer", "minimum": 1,
                    },
                    "priority": {"type": "integer", "minimum": 0},
                    # Elastic gang bounds (r16): with minReplicas set,
                    # the operator resizes the TPU_WORKER gang through
                    # member loss / preemption inside [min, max]
                    # instead of restarting or dying.
                    "minReplicas": {"type": "integer", "minimum": 1},
                    "maxReplicas": {"type": "integer", "minimum": 1},
                },
            },
            "status": {
                "type": "object",
                "x-kubernetes-preserve-unknown-fields": True,
            },
        },
    }
    return k8s.crd(CRD_NAME, GROUP, VERSION, KIND, PLURAL,
                   short_names=["tpj"], schema=schema,
                   # The operator writes status through /status (both
                   # the kubectl shim's --subresource=status and the
                   # HTTP client's PUT); without this declaration the
                   # apiserver 404s that endpoint and every status
                   # update would be silently dropped.
                   status_subresource=True)


def operator_config(namespace: str, cloud: str = "") -> Dict[str, Any]:
    """Operator ConfigMap (parity: reference ``tf-job.libsonnet:98-148``
    whose config carried ``grpcServerFilePath`` — the stock PS/worker
    gRPC server — and per-cloud accelerator mounts ``:108-136``. The
    TPU equivalent default entrypoint is the JAX coordinator bootstrap
    in kubeflow_tpu.training.launcher; the per-cloud block selects the
    TPU scheduling contract)."""
    import json

    config = {
        "defaultEntrypoint": "python -m kubeflow_tpu.training.launcher",
        "coordinatorPort": 8476,
        "cloud": cloud or "gke",
        "accelerators": {
            # name → chips per host; used to validate topology/gang size.
            "tpu-v5-lite-podslice": {"chipsPerHost": 4},
            "tpu-v5p-slice": {"chipsPerHost": 4},
            "tpu-v4-podslice": {"chipsPerHost": 4},
        },
    }
    if (cloud or "gke") != "gke":
        # Non-GKE clusters (e.g. minikube CI) have no TPU nodepools:
        # the operator schedules TPU_WORKER replicas as CPU pods with
        # the simulated-mesh env so e2e tests can run anywhere.
        config["simulateTpu"] = True
    return k8s.config_map(
        "tpujob-operator-config", namespace,
        {"controller_config_file.yaml": json.dumps(config, indent=2)},
    )


def operator_deployment(namespace: str, image: str) -> Dict[str, Any]:
    container = k8s.container(
        "tpujob-operator",
        image,
        command=["/opt/kubeflow-tpu/tpujob-operator"],
        args=["--controller-config-file=/etc/config/controller_config_file.yaml"],
        env=[
            k8s.env_var("KFT_NAMESPACE", field_path="metadata.namespace"),
        ],
        ports=[k8s.port(9400, "metrics")],
        volume_mounts=[k8s.volume_mount("config-volume", "/etc/config")],
    )
    return k8s.deployment(
        "tpujob-operator", namespace,
        k8s.pod_spec(
            [container],
            volumes=[k8s.volume("config-volume",
                                config_map_name="tpujob-operator-config")],
            service_account="tpujob-operator",
        ),
        # Annotation-driven discovery (the classic prometheus.io
        # contract): the operator's stdlib exposition thread serves
        # /metrics on :9400 (docs/observability.md).
        pod_annotations={"prometheus.io/scrape": "true",
                         "prometheus.io/port": "9400",
                         "prometheus.io/path": "/metrics"},
    )


def operator_rbac(namespace: str) -> List[Dict[str, Any]]:
    """Parity: reference ``tf-job.libsonnet:150-269`` (SA + ClusterRole
    + Binding), with the rule set narrowed to what the reconciler
    actually touches."""
    labels = {"app": "tpujob-operator"}
    rules = [
        k8s.policy_rule([GROUP], [PLURAL, f"{PLURAL}/status"], ["*"]),
        k8s.policy_rule(["apiextensions.k8s.io"], ["customresourcedefinitions"],
                        ["get", "list", "watch", "create"]),
        k8s.policy_rule([""], ["pods", "services", "endpoints", "events",
                               "configmaps"], ["*"]),
        # Whole-gang disruption budgets (reconciler._gang_pdb).
        k8s.policy_rule(["policy"], ["poddisruptionbudgets"], ["*"]),
        # Leader-election leases (operator/leader.py).
        k8s.policy_rule(["coordination.k8s.io"], ["leases"], ["*"]),
        k8s.policy_rule(["apps"], ["deployments"], ["get", "list", "watch"]),
    ]
    return [
        k8s.service_account("tpujob-operator", namespace, labels=labels),
        k8s.cluster_role("tpujob-operator", rules, labels=labels),
        k8s.cluster_role_binding(
            "tpujob-operator", "tpujob-operator",
            [k8s.subject("ServiceAccount", "tpujob-operator", namespace)],
            labels=labels,
        ),
    ]


def ui(namespace: str, image: str, service_type: str) -> List[Dict[str, Any]]:
    """TPUJob dashboard (parity: reference ``tf-job.libsonnet:271-458``,
    served behind Ambassador at ``/tpujobs/ui/``)."""
    labels = {"name": "tpujob-dashboard"}
    container = k8s.container(
        "tpujob-dashboard", image,
        command=["/opt/kubeflow-tpu/dashboard", "--port=8080"],
        ports=[k8s.port(8080)],
    )
    svc = k8s.service(
        "tpujob-dashboard", namespace, labels,
        [k8s.service_port(80, target_port=8080)],
        service_type=service_type,
        annotations={
            "getambassador.io/config": k8s.ambassador_mapping(
                "tpujobs-ui-mapping", "/tpujobs/ui/",
                f"tpujob-dashboard.{namespace}:80", rewrite="/tpujobs/ui/",
            )
        },
    )
    deploy = k8s.deployment(
        "tpujob-dashboard", namespace,
        k8s.pod_spec([container], service_account="tpujob-dashboard"),
        labels=labels, pod_labels=labels,
    )
    rbac = [
        k8s.service_account("tpujob-dashboard", namespace),
        k8s.cluster_role("tpujob-dashboard", [
            k8s.policy_rule([GROUP], [PLURAL], ["*"]),
            k8s.policy_rule([""], ["pods", "pods/log", "events"],
                            ["get", "list", "watch"]),
        ]),
        k8s.cluster_role_binding(
            "tpujob-dashboard", "tpujob-dashboard",
            [k8s.subject("ServiceAccount", "tpujob-dashboard", namespace)],
        ),
    ]
    return [svc, deploy] + rbac


def all_objects(params: Dict[str, Any]) -> List[Dict[str, Any]]:
    ns = params["namespace"]
    return [
        crd(),
        operator_config(ns, params.get("cloud", "")),
        operator_deployment(ns, params["tpujob_image"]),
        *operator_rbac(ns),
        *ui(ns, params["tpujob_ui_image"], params["tpujob_ui_service_type"]),
    ]


OPERATOR_PARAMS = [
    Param("namespace", "default", "string", "Namespace to use for the components."),
    Param("tpujob_image", DEFAULT_OPERATOR_IMAGE, "string",
          "The image for the TPUJob controller."),
    Param("tpujob_ui_image", DEFAULT_UI_IMAGE, "string",
          "The image for the TPUJob dashboard."),
    Param("tpujob_ui_service_type", "ClusterIP", "string",
          "The service type for the UI."),
    Param("cloud", "", "string",
          "Cloud to customize for: gke (default) | minikube."),
]

register(
    "tpujob-operator",
    "TPUJob CRD, operator, and dashboard (tf-operator replacement)",
    OPERATOR_PARAMS,
    package="core",
)(all_objects)


def _generic_job_builder(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Generic TPUJob prototype (parity: reference
    ``kubeflow/tf-job/prototypes/tf-job.jsonnet:5-57``: num_masters/
    num_ps/num_workers/num_gpus → coordinator + TPU workers)."""
    args = p["args"]
    specs = []
    if p["num_coordinators"] > 0:
        specs.append(replica_spec(
            "COORDINATOR", p["num_coordinators"], image=p["image"], args=args))
    if p["num_tpu_workers"] > 0:
        specs.append(replica_spec(
            "TPU_WORKER", p["num_tpu_workers"], image=p["image"], args=args,
            tpu_accelerator=p["tpu_accelerator"], tpu_topology=p["tpu_topology"],
            chips_per_worker=p["chips_per_worker"]))
    if p["num_cpu_workers"] > 0:
        specs.append(replica_spec(
            "CPU", p["num_cpu_workers"], image=p["image"], args=args))
    if not specs:
        raise ValueError("job needs at least one replica")
    # Chief: the coordinator if present, else TPU_WORKER 0 (parity with
    # tf-job.jsonnet:41-44 MASTER-else-WORKER chief selection).
    chief = "COORDINATOR" if p["num_coordinators"] > 0 else "TPU_WORKER"
    return [tpu_job(p["name"], p["namespace"], specs,
                    termination=termination_policy(chief),
                    num_slices=p["num_slices"],
                    scheduling_deadline_seconds=(
                        p["scheduling_deadline_seconds"] or None),
                    priority=p["priority"],
                    min_replicas=p["min_replicas"] or None,
                    max_replicas=p["max_replicas"] or None)]


register(
    "tpu-job",
    "A generic TPUJob (tf-job prototype replacement)",
    [
        Param("name", REQUIRED, "string", "Name for the job."),
        Param("namespace", "default", "string"),
        Param("image", "ghcr.io/kubeflow-tpu/trainer:v0.1.0", "string",
              "The docker image to use for the job."),
        Param("args", "", "array", "Comma separated args to pass to the job."),
        Param("num_coordinators", 1, "int"),
        Param("num_tpu_workers", 1, "int"),
        Param("num_cpu_workers", 0, "int"),
        Param("tpu_accelerator", "tpu-v5-lite-podslice", "string"),
        Param("tpu_topology", "2x4", "string"),
        Param("chips_per_worker", 4, "int"),
        Param("num_slices", 1, "int",
              ">1 = multi-slice (megascale) job: the replicaSpecs are "
              "provisioned once per slice and MEGASCALE_* env is "
              "injected."),
        Param("scheduling_deadline_seconds", 0, "int",
              "Fail the job (DeadlineExceeded) and release its gang "
              "if it is still Pending after this many seconds; 0 = "
              "wait forever. See docs/operator.md for picking a "
              "value on spot-heavy pools."),
        Param("priority", 0, "int",
              "Priority class: a Pending job with priority > 0 "
              "approaching its scheduling deadline may preempt the "
              "lowest-priority running gang (strictly lower class "
              "only, rate-limited; needs "
              "scheduling_deadline_seconds). 0 = default, "
              "preemptible."),
        Param("min_replicas", 0, "int",
              "Elastic gang floor: > 0 lets the operator RESIZE the "
              "TPU_WORKER gang through worker loss / preemption "
              "(down to this many workers) instead of restarting or "
              "killing it; the trainer reshards from its continuous "
              "checkpoint. 0 = rigid (the default). See "
              "docs/operator.md."),
        Param("max_replicas", 0, "int",
              "Elastic gang ceiling (needs min_replicas; 0 = the "
              "declared num_tpu_workers)."),
    ],
    package="tpu-job",
)(_generic_job_builder)


def _cnn_benchmark_builder(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The tpu-cnn benchmark prototype (parity: reference
    ``kubeflow/tf-job/prototypes/tf-cnn-benchmarks.jsonnet``: arg
    assembly ``:36-43``, worker/ps validation ``:92-97``, chief =
    worker 0 ``:100``). PS count is gone — validation is now that the
    slice geometry is coherent."""
    if p["num_tpu_workers"] < 1:
        # Parity with the reference's jsonnet `error` on workers < 1.
        raise ValueError("num_tpu_workers must be >= 1")
    args = [
        "python", "-m", "kubeflow_tpu.training.benchmark",
        f"--model={p['model']}",
        f"--batch_size={p['batch_size']}",
    ]
    if p["profile_dir"]:
        args.append(f"--profile_dir={p['profile_dir']}")
    spec = replica_spec(
        "TPU_WORKER", p["num_tpu_workers"], image=p["image"],
        command=args[:1], args=args[1:],
        tpu_accelerator=p["tpu_accelerator"], tpu_topology=p["tpu_topology"],
        chips_per_worker=p["chips_per_worker"],
    )
    return [tpu_job(
        p["name"], p["namespace"], [spec],
        termination=termination_policy("TPU_WORKER", 0),
        num_slices=p["num_slices"],
    )]


register(
    "tpu-cnn",
    "ResNet/Inception training benchmark as a TPUJob (tf-cnn replacement)",
    [
        Param("name", REQUIRED, "string", "Name for the job."),
        Param("namespace", "default", "string"),
        Param("image", "ghcr.io/kubeflow-tpu/trainer:v0.1.0", "string"),
        Param("model", "resnet50", "string", "Which model to use."),
        Param("batch_size", 128, "int", "Global batch size."),
        Param("num_tpu_workers", 1, "int"),
        Param("tpu_accelerator", "tpu-v5-lite-podslice", "string"),
        Param("tpu_topology", "2x4", "string"),
        Param("chips_per_worker", 4, "int"),
        Param("num_slices", 1, "int",
              ">1 = multi-slice (megascale) job: workers are "
              "provisioned once per slice; the trainer's dcn_data "
              "mesh axis follows from the injected MEGASCALE env."),
        Param("profile_dir", "", "string",
              "Capture the timed steps as an XPlane trace under this "
              "dir (mount a shared volume; the dashboard lists it)."),
    ],
    package="tpu-job",
)(_cnn_benchmark_builder)


def _finetune_builder(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    """LoRA fine-tune prototype: a TPUJob whose workers run the LoRA
    trainer (training/finetune.py via the benchmark CLI). Greenfield —
    the reference has no fine-tuning prototype; shape mirrors tpu-cnn
    so `kft generate tpu-finetune` slots into the same workflow."""
    if p["num_tpu_workers"] < 1:
        raise ValueError("num_tpu_workers must be >= 1")
    if p["lora_rank"] < 1:
        raise ValueError("lora_rank must be >= 1 for a LoRA fine-tune")
    total_chips = p["num_tpu_workers"] * p["chips_per_worker"]
    if p["batch_size"] % total_chips:
        # The trainer shards the batch over the (data, fsdp) mesh of
        # all slice chips; an indivisible batch fails at runtime with
        # a sharding error — fail at generate time instead.
        raise ValueError(
            f"batch_size {p['batch_size']} must be divisible by "
            f"num_tpu_workers*chips_per_worker = {total_chips}")
    args = [
        "python", "-m", "kubeflow_tpu.training.benchmark",
        f"--model={p['model']}",
        f"--lora_rank={p['lora_rank']}",
        f"--batch_size={p['batch_size']}",
        f"--seq_len={p['seq_len']}",
    ]
    if p["data"]:
        args.append(f"--data={p['data']}")
    if p["profile_dir"]:
        args.append(f"--profile_dir={p['profile_dir']}")
    spec = replica_spec(
        "TPU_WORKER", p["num_tpu_workers"], image=p["image"],
        command=args[:1], args=args[1:],
        tpu_accelerator=p["tpu_accelerator"], tpu_topology=p["tpu_topology"],
        chips_per_worker=p["chips_per_worker"],
    )
    return [tpu_job(
        p["name"], p["namespace"], [spec],
        termination=termination_policy("TPU_WORKER", 0),
    )]


register(
    "tpu-finetune",
    "LoRA fine-tune of a language model as a TPUJob",
    [
        Param("name", REQUIRED, "string", "Name for the job."),
        Param("namespace", "default", "string"),
        Param("image", "ghcr.io/kubeflow-tpu/trainer:v0.1.0", "string"),
        Param("model", "llama2-7b", "string", "Which language model."),
        Param("lora_rank", 16, "int", "Adapter rank (r)."),
        Param("batch_size", 1, "int",
              "Global batch size (the slice's chip count must divide "
              "it)."),
        Param("seq_len", 1024, "int", "Sequence length."),
        Param("data", "", "string",
              "Glob of token shards (.npy / raw .bin) mounted in the "
              "pod; empty = synthetic data."),
        Param("num_tpu_workers", 1, "int"),
        Param("tpu_accelerator", "tpu-v5-lite-podslice", "string"),
        # Default = the measured one-chip config (PERF.md: 7B LoRA on
        # a single v5e chip) — batch 1 cannot shard over a 2x4 slice.
        Param("tpu_topology", "1x1", "string"),
        Param("chips_per_worker", 1, "int"),
        Param("profile_dir", "", "string",
              "Capture the timed steps as an XPlane trace under this "
              "dir (mount a shared volume; the dashboard lists it)."),
    ],
    package="tpu-job",
)(_finetune_builder)


def _lm_pretrain_builder(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    """LM pretraining prototype: a TPUJob whose workers run the tpu-lm
    trainer (training/pretrain.py) — mlm/causal objectives, any mesh
    preset incl. pipeline parallelism. Greenfield (the reference's only
    training prototype was the CNN benchmark); shape mirrors tpu-cnn."""
    if p["num_tpu_workers"] < 1:
        raise ValueError("num_tpu_workers must be >= 1")
    num_slices = p["num_slices"]
    if num_slices < 1:
        raise ValueError("num_slices must be >= 1")
    # Multi-slice: the replicaSpecs are per-slice, so the job's chip
    # and host counts scale by num_slices.
    total_chips = num_slices * p["num_tpu_workers"] * p["chips_per_worker"]
    total_hosts = num_slices * p["num_tpu_workers"]
    # Validate the mesh against the slice geometry at GENERATE time: a
    # mesh whose axis product mismatches the chip count fails in-pod
    # minutes later. The arithmetic mirrors parallel/mesh.py MeshSpec
    # .resolve (one -1 wildcard, product == chip count) AND build_mesh's
    # megascale-env rule (dcn_data defaults to the slice count, a
    # conflicting explicit value is an error) but stays jax-free — the
    # manifest compiler must import only pyyaml (pyproject: the engine
    # lives behind the "engine" extra).
    batch_axes_product = total_chips  # flat all-data default mesh
    if p["mesh"]:
        axes = ("dcn_data", "data", "fsdp", "pipeline", "seq",
                "expert", "tensor")
        sizes = {}
        for part in p["mesh"].split(","):
            axis, _, value = part.partition("=")
            axis = axis.strip()
            if axis not in axes or not value:
                raise ValueError(
                    f"bad mesh entry {part!r} (want <axis>=N with "
                    f"axis in {axes})")
            size = int(value)
            if size < 1 and size != -1:
                # 0 / negative sizes crash or silently resolve to
                # garbage meshes; only the single -1 wildcard is
                # meaningful.
                raise ValueError(
                    f"bad mesh entry {part!r} (axis size must be "
                    f">= 1, or -1 as the wildcard)")
            sizes[axis] = size
        if num_slices > 1:
            # Mirror build_mesh: the injected MEGASCALE_NUM_SLICES
            # sets dcn_data when the spec leaves it unset (or
            # wildcarded); an explicit conflicting value fails in-pod,
            # so fail here first.
            if sizes.get("dcn_data", 1) in (1, -1):
                sizes["dcn_data"] = num_slices
            elif sizes["dcn_data"] != num_slices:
                raise ValueError(
                    f"mesh {p['mesh']!r} sets dcn_data="
                    f"{sizes['dcn_data']} but the job provisions "
                    f"num_slices = {num_slices}")
        wildcards = [a for a, v in sizes.items() if v == -1]
        fixed = 1
        for v in sizes.values():
            if v != -1:
                fixed *= v
        if len(wildcards) > 1 or (not wildcards and fixed != total_chips) \
                or (wildcards and total_chips % fixed):
            raise ValueError(
                f"mesh {p['mesh']!r} does not fit "
                f"num_slices*num_tpu_workers*chips_per_worker = "
                f"{total_chips}")
        if wildcards:
            sizes[wildcards[0]] = total_chips // fixed
        # Batch rows shard over the data-parallel axes only
        # (parallel/mesh.py batch_sharding: dcn_data × data × fsdp).
        batch_axes_product = (sizes.get("dcn_data", 1)
                              * sizes.get("data", 1)
                              * sizes.get("fsdp", 1))
    if p["global_batch"] % batch_axes_product:
        raise ValueError(
            f"global_batch {p['global_batch']} must be divisible by "
            f"the mesh's data axes (dcn_data*data*fsdp = "
            f"{batch_axes_product})")
    if p["global_batch"] % total_hosts:
        # Each host feeds its own 1/num_hosts rows (host_shard_range);
        # a tensor- or pipeline-only mesh passes the data-axes check
        # with product 1 yet still fails in-pod on this split.
        raise ValueError(
            f"global_batch {p['global_batch']} must be divisible by "
            f"the host count (num_slices*num_tpu_workers = "
            f"{total_hosts})")
    if p["mesh"] and "pipeline=" in p["mesh"]:
        # The pipeline schedule additionally splits each step's batch
        # into microbatches whose rows shard over the data axis.
        if p["microbatches"] < 1:
            raise ValueError("microbatches must be >= 1")
        if p["global_batch"] % (p["microbatches"] * batch_axes_product):
            raise ValueError(
                f"global_batch {p['global_batch']} must be divisible "
                f"by microbatches*data axes = "
                f"{p['microbatches'] * batch_axes_product}")
    if p["objective"] not in ("", "mlm", "causal"):
        # Mirrors pretrain's argparse choices — a typo'd objective
        # would otherwise burn the whole restart budget on instant
        # arg-parse crashes.
        raise ValueError(
            f"objective must be mlm or causal (or empty for the "
            f"model default); got {p['objective']!r}")
    args = [
        "python", "-m", "kubeflow_tpu.training.pretrain",
        f"--model={p['model']}",
        f"--global_batch={p['global_batch']}",
        f"--seq_len={p['seq_len']}",
        f"--steps={p['steps']}",
    ]
    if p["objective"]:
        args.append(f"--objective={p['objective']}")
    if p["mesh"]:
        args.append(f"--mesh={p['mesh']}")
        if "pipeline=" in p["mesh"]:
            args.append(f"--microbatches={p['microbatches']}")
            if p["virtual_stages"] > 1:
                args.append(f"--virtual_stages={p['virtual_stages']}")
    if p["remat"]:
        args.append("--remat")
    if p["data"]:
        args.append(f"--data={p['data']}")
        if p["bin_dtype"] != "uint16":
            args.append(f"--bin_dtype={p['bin_dtype']}")
    if p["min_replicas"]:
        if num_slices > 1:
            raise ValueError("elastic jobs (min_replicas) are "
                             "single-slice")
        if not p["checkpoint_dir"]:
            # An elastic resize resumes from the continuous sharded
            # checkpoint; without a checkpoint dir the resized gang
            # would restart the run from step 0 — elasticity without
            # the recovery half is a silent-data-loss trap.
            raise ValueError("elastic jobs (min_replicas) need "
                             "checkpoint_dir (the resize resumes "
                             "from the continuous checkpoint)")
        if p["mesh"] and any(f"{axis}=" in p["mesh"]
                             for axis in ("tensor", "pipeline", "seq",
                                          "expert")):
            # Model-parallel axes are sized to the gang; a resize
            # would need a different parameter factorization, which
            # the restore path does not re-plan. Elastic = dp/fsdp.
            raise ValueError("elastic jobs support data/fsdp meshes "
                             "only (model-parallel axes cannot "
                             "resize)")
    if p["continuous_every"]:
        if not p["checkpoint_dir"]:
            raise ValueError("continuous_every needs checkpoint_dir")
        args.append(f"--continuous_every={p['continuous_every']}")
    volumes = volume_mounts = None
    if p["checkpoint_dir"]:
        args.append(f"--checkpoint_dir={p['checkpoint_dir']}")
        if p["checkpoint_pvc"]:
            # Without a durable mount, restart-slice recovery would
            # resume from an empty ephemeral dir — i.e. from step 0.
            volumes = [k8s.volume("ckpt", pvc_name=p["checkpoint_pvc"])]
            volume_mounts = [k8s.volume_mount("ckpt",
                                              p["checkpoint_dir"])]
    spec = replica_spec(
        "TPU_WORKER", p["num_tpu_workers"], image=p["image"],
        command=args[:1], args=args[1:],
        tpu_accelerator=p["tpu_accelerator"], tpu_topology=p["tpu_topology"],
        chips_per_worker=p["chips_per_worker"],
        volumes=volumes, volume_mounts=volume_mounts,
    )
    return [tpu_job(
        p["name"], p["namespace"], [spec],
        termination=termination_policy("TPU_WORKER", 0),
        num_slices=num_slices,
        min_replicas=p["min_replicas"] or None,
        max_replicas=p["max_replicas"] or None,
    )]


register(
    "tpu-lm",
    "LM pretraining (BERT mlm / Llama causal) as a TPUJob",
    [
        Param("name", REQUIRED, "string", "Name for the job."),
        Param("namespace", "default", "string"),
        Param("image", "ghcr.io/kubeflow-tpu/trainer:v0.1.0", "string"),
        Param("model", "bert-base", "string", "Which language model."),
        Param("objective", "", "string",
              "mlm | causal (empty = the model family's default)."),
        Param("global_batch", 256, "int", "Global batch size."),
        Param("seq_len", 128, "int", "Sequence length."),
        Param("steps", 1000, "int", "Training steps."),
        Param("mesh", "", "string",
              "Mesh spec, e.g. data=-1 or data=4,pipeline=2 "
              "(validated against the slice geometry at generate "
              "time)."),
        Param("microbatches", 4, "int",
              "Pipeline schedule microbatch count (pipeline meshes)."),
        Param("virtual_stages", 1, "int",
              ">1 = interleaved pipeline schedule (~v× smaller "
              "bubble)."),
        Param("data", "", "string",
              "Token shards (.npy / raw .bin): files, dirs, or globs "
              "mounted in the pod, or gs://-style remote paths; "
              "empty = synthetic data. mlm gets dynamic masking."),
        Param("bin_dtype", "uint16", "string",
              "dtype of raw .bin token dumps (headerless — a wrong "
              "value reads garbage tokens; .npy self-describes)."),
        Param("checkpoint_dir", "", "string",
              "Orbax checkpoint dir (enables slice-restart resume; "
              "pair with checkpoint_pvc for a durable mount)."),
        Param("checkpoint_pvc", "", "string",
              "ReadWriteMany PVC (e.g. from the nfs prototype) "
              "mounted at checkpoint_dir — without it checkpoints "
              "land on ephemeral storage and a slice restart starts "
              "from step 0."),
        Param("remat", False, "bool",
              "Rematerialize decoder blocks (trade FLOPs for "
              "activation memory; llama only)."),
        Param("num_tpu_workers", 1, "int",
              "TPU hosts PER SLICE (multiply by num_slices for the "
              "job's host count)."),
        Param("tpu_accelerator", "tpu-v5-lite-podslice", "string"),
        Param("tpu_topology", "2x4", "string"),
        Param("chips_per_worker", 4, "int"),
        Param("num_slices", 1, "int",
              ">1 = multi-slice (megascale) job: one gang per slice, "
              "all-or-nothing recovery across the union; the mesh's "
              "dcn_data axis defaults to this count in-pod."),
        Param("min_replicas", 0, "int",
              "Elastic gang floor: > 0 keeps the job Running through "
              "worker loss — the operator resizes the gang (never "
              "below this) and the trainer reshards from the "
              "continuous checkpoint. Needs checkpoint_dir; "
              "data/fsdp meshes only. 0 = rigid."),
        Param("max_replicas", 0, "int",
              "Elastic gang ceiling (0 = num_tpu_workers)."),
        Param("continuous_every", 0, "int",
              "Continuous sharded checkpointing: per-host async "
              "shard writes every N steps under "
              "checkpoint_dir/continuous (manifest-last atomic "
              "commit — a mid-write crash never yields a torn "
              "restore). 0 = off. Elastic resizes restore from "
              "these shards."),
    ],
    package="tpu-job",
)(_lm_pretrain_builder)
