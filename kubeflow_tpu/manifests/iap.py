# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""GKE Identity-Aware-Proxy ingress: Ingress + Envoy JWT filter.

Replaces reference ``kubeflow/core/iap.libsonnet``: the ingress
prototype (Ingress + Certificate + NodePort Service, ``:12-16,490-560``)
and the Envoy prototype (3-replica deployment ``:104-144``, config
generated in-code ``:164-415`` with a JWT-auth filter on
``x-goog-iap-jwt-assertion`` ``:297-323``, routes /hub,/user →
JupyterHub, fallthrough → Ambassador ``:228-292``), plus the whoami
debug app ``:417-488``. The config generation moves from Jsonnet to
Python and targets Envoy v3 APIs; the route/JWT semantics are parity.
"""

from __future__ import annotations

from typing import Any, Dict, List

import yaml

from kubeflow_tpu.manifests import k8s
from kubeflow_tpu.params import Param, REQUIRED, register

ENVOY_IMAGE = "envoyproxy/envoy:v1.22.0"
IAP_ISSUER = "https://cloud.google.com/iap"
IAP_JWKS = "https://www.gstatic.com/iap/verify/public_key-jwk"


def envoy_config(namespace: str, audiences: List[str],
                 disable_jwt: bool) -> str:
    """Render the Envoy v3 bootstrap YAML (reference's in-Jsonnet v1
    JSON, ``iap.libsonnet:164-415``). Routes: /hub, /user → hub;
    /whoami → debug app; everything else → Ambassador."""
    jwt_filter: Dict[str, Any] = {
        "name": "envoy.filters.http.jwt_authn",
        "typed_config": {
            "@type": "type.googleapis.com/envoy.extensions.filters.http."
                     "jwt_authn.v3.JwtAuthentication",
            "providers": {
                "iap": {
                    "issuer": IAP_ISSUER,
                    "audiences": audiences,
                    "from_headers": [{"name": "x-goog-iap-jwt-assertion"}],
                    "remote_jwks": {
                        "http_uri": {
                            "uri": IAP_JWKS,
                            "cluster": "iap_jwks",
                            "timeout": "5s",
                        },
                        "cache_duration": "300s",
                    },
                }
            },
            "rules": [
                # Health checks bypass JWT (parity: the reference's
                # /healthz route skipped the filter, :255-262).
                {"match": {"prefix": "/healthz"}},
                {"match": {"prefix": "/"}, "requires": {"provider_name": "iap"}},
            ],
        },
    }
    http_filters: List[Dict[str, Any]] = []
    if not disable_jwt:
        http_filters.append(jwt_filter)
    # Bridge native gRPC clients to the model server's gRPC-Web
    # PredictionService surface (serving/wire.py): the filter
    # translates HTTP/2 gRPC ⇄ gRPC-Web over HTTP/1.1 upstream.
    http_filters.append({
        "name": "envoy.filters.http.grpc_web",
        "typed_config": {
            "@type": "type.googleapis.com/envoy.extensions.filters."
                     "http.grpc_web.v3.GrpcWeb"
        },
    })
    http_filters.append({
        "name": "envoy.filters.http.router",
        "typed_config": {
            "@type": "type.googleapis.com/envoy.extensions.filters."
                     "http.router.v3.Router"
        },
    })

    def cluster(name: str, host: str, port: int) -> Dict[str, Any]:
        return {
            "name": name,
            "connect_timeout": "1.0s",
            "type": "STRICT_DNS",
            "lb_policy": "ROUND_ROBIN",
            "load_assignment": {
                "cluster_name": name,
                "endpoints": [{
                    "lb_endpoints": [{
                        "endpoint": {
                            "address": {
                                "socket_address": {
                                    "address": host, "port_value": port,
                                }
                            }
                        }
                    }]
                }],
            },
        }

    routes = [
        {"match": {"prefix": "/healthz"},
         "route": {"cluster": "whoami"}},
        {"match": {"prefix": "/hub"},
         "route": {"cluster": "jupyterhub", "timeout": "600s",
                   "upgrade_configs": [{"upgrade_type": "websocket"}]}},
        {"match": {"prefix": "/user"},
         "route": {"cluster": "jupyterhub", "timeout": "600s",
                   "upgrade_configs": [{"upgrade_type": "websocket"}]}},
        {"match": {"prefix": "/whoami"},
         "route": {"cluster": "whoami"}},
        {"match": {"prefix": "/"},
         "route": {"cluster": "ambassador", "timeout": "600s"}},
    ]
    config = {
        "admin": {
            "address": {"socket_address": {"address": "0.0.0.0",
                                           "port_value": 8001}},
        },
        "static_resources": {
            "listeners": [{
                "name": "main",
                "address": {"socket_address": {"address": "0.0.0.0",
                                               "port_value": 8080}},
                "filter_chains": [{
                    "filters": [{
                        "name": "envoy.filters.network.http_connection_manager",
                        "typed_config": {
                            "@type": "type.googleapis.com/envoy.extensions."
                                     "filters.network.http_connection_manager"
                                     ".v3.HttpConnectionManager",
                            "stat_prefix": "ingress_http",
                            "route_config": {
                                "name": "local_route",
                                "virtual_hosts": [{
                                    "name": "backend",
                                    "domains": ["*"],
                                    "routes": routes,
                                }],
                            },
                            "http_filters": http_filters,
                        },
                    }]
                }],
            }],
            "clusters": [
                cluster("jupyterhub", f"tpu-hub-lb.{namespace}.svc.cluster.local", 80),
                cluster("ambassador", f"ambassador.{namespace}.svc.cluster.local", 80),
                cluster("whoami", f"whoami-app.{namespace}.svc.cluster.local", 80),
                {**cluster("iap_jwks", "www.gstatic.com", 443),
                 "transport_socket": {
                     "name": "envoy.transport_sockets.tls",
                     "typed_config": {
                         "@type": "type.googleapis.com/envoy.extensions."
                                  "transport_sockets.tls.v3.UpstreamTlsContext",
                         "sni": "www.gstatic.com",
                     },
                 }},
            ],
        },
    }
    return yaml.safe_dump(config, sort_keys=False)


def envoy_objects(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    ns = p["namespace"]
    labels = {"service": "envoy"}
    config = envoy_config(ns, p["audiences"], p["disable_jwt_checking"])
    cm = k8s.config_map("envoy-config", ns, {"envoy.yaml": config})
    container = k8s.container(
        "envoy", p["envoy_image"],
        command=["envoy", "-c", "/etc/envoy/envoy.yaml"],
        ports=[k8s.port(8080), k8s.port(8001, "admin")],
        volume_mounts=[k8s.volume_mount("envoy-config", "/etc/envoy")],
        resources=k8s.resources(cpu_request="100m", memory_request="128Mi",
                                cpu_limit="1", memory_limit="400Mi"),
        liveness_probe=k8s.http_get_probe("/healthz", 8080),
        readiness_probe=k8s.http_get_probe("/healthz", 8080),
    )
    deploy = k8s.deployment(
        "envoy", ns,
        k8s.pod_spec([container],
                     volumes=[k8s.volume("envoy-config",
                                         config_map_name="envoy-config")]),
        replicas=3, labels=labels)
    svc = k8s.service(
        "envoy", ns, labels,
        [k8s.service_port(80, target_port=8080, name="envoy")],
        service_type="NodePort", labels=labels)
    return [cm, deploy, svc, *whoami_app(ns)]


def whoami_app(namespace: str) -> List[Dict[str, Any]]:
    """Debug echo app (parity ``iap.libsonnet:417-488``)."""
    labels = {"app": "whoami"}
    container = k8s.container(
        "app", "gcr.io/cloud-solutions-group/esp-sample-app:1.0.0",
        ports=[k8s.port(8081)])
    return [
        k8s.service("whoami-app", namespace, labels,
                    [k8s.service_port(80, target_port=8081)]),
        k8s.deployment("whoami-app", namespace, k8s.pod_spec([container]),
                       labels=labels),
    ]


def ingress_objects(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Ingress + managed Certificate (parity ``iap.libsonnet:490-560``)."""
    ns = p["namespace"]
    objs = [
        k8s.ingress(
            "envoy-ingress", ns, backend_service="envoy", backend_port=80,
            annotations={
                "kubernetes.io/ingress.global-static-ip-name": p["ip_name"],
                "kubernetes.io/ingress.class": "gce",
            },
            tls_secret=p["secret_name"],
            host=p["hostname"] or None,
        ),
    ]
    if p["hostname"]:
        objs.append({
            "apiVersion": "cert-manager.io/v1",
            "kind": "Certificate",
            "metadata": k8s.metadata("envoy-ingress-tls", ns),
            "spec": {
                "secretName": p["secret_name"],
                "issuerRef": {"name": p["issuer"], "kind": "Issuer"},
                "commonName": p["hostname"],
                "dnsNames": [p["hostname"]],
            },
        })
    return objs


register("iap-envoy", "Envoy deployment verifying IAP JWTs", [
    Param("namespace", "default", "string"),
    Param("audiences", REQUIRED, "array",
          "Comma separated list of JWT audiences to accept."),
    Param("disable_jwt_checking", "false", "bool"),
    Param("envoy_image", ENVOY_IMAGE, "string"),
], package="core")(envoy_objects)

register("iap-ingress", "GCE Ingress + TLS certificate for IAP", [
    Param("namespace", "default", "string"),
    Param("ip_name", REQUIRED, "string", "Name of the global static IP."),
    Param("hostname", "", "string", "Hostname e.g. kubeflow.example.com."),
    Param("secret_name", "envoy-ingress-tls", "string"),
    Param("issuer", "letsencrypt-prod", "string"),
], package="core")(ingress_objects)
