# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""cert-manager + Let's Encrypt ACME issuer.

Replaces reference ``kubeflow/core/cert-manager.libsonnet``: CRDs
``:19-69``, RBAC ``:71-123``, controller Deployment ``:125-160``,
ACME prod Issuer ``:162-180``. No TPU delta; pinned to a
v1-API-era cert-manager rather than the reference's v0.2.3.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.manifests import k8s
from kubeflow_tpu.params import Param, REQUIRED, register

CONTROLLER_IMAGE = "quay.io/jetstack/cert-manager-controller:v1.5.3"


def crds() -> List[Dict[str, Any]]:
    group = "cert-manager.io"
    return [
        k8s.crd(f"{plural}.{group}", group, "v1", kind, plural)
        for kind, plural in (
            ("Certificate", "certificates"),
            ("Issuer", "issuers"),
            ("ClusterIssuer", "clusterissuers"),
        )
    ]


def rbac(namespace: str) -> List[Dict[str, Any]]:
    return [
        k8s.service_account("cert-manager", namespace),
        k8s.cluster_role("cert-manager", [
            k8s.policy_rule(["cert-manager.io"], ["*"], ["*"]),
            k8s.policy_rule([""], ["secrets", "events", "services", "pods"],
                            ["*"]),
            k8s.policy_rule(["networking.k8s.io"], ["ingresses"], ["*"]),
        ]),
        k8s.cluster_role_binding(
            "cert-manager", "cert-manager",
            [k8s.subject("ServiceAccount", "cert-manager", namespace)]),
    ]


def deployment(namespace: str) -> Dict[str, Any]:
    container = k8s.container(
        "cert-manager", CONTROLLER_IMAGE,
        args=["--cluster-resource-namespace=$(POD_NAMESPACE)",
              "--leader-election-namespace=$(POD_NAMESPACE)"],
        env=[k8s.env_var("POD_NAMESPACE", field_path="metadata.namespace")],
    )
    return k8s.deployment(
        "cert-manager", namespace,
        k8s.pod_spec([container], service_account="cert-manager"),
        labels={"app": "cert-manager"})


def issuer(namespace: str, acme_email: str, acme_url: str) -> Dict[str, Any]:
    return {
        "apiVersion": "cert-manager.io/v1",
        "kind": "Issuer",
        "metadata": k8s.metadata("letsencrypt-prod", namespace),
        "spec": {
            "acme": {
                "server": acme_url,
                "email": acme_email,
                "privateKeySecretRef": {"name": "letsencrypt-prod-secret"},
                "solvers": [{"http01": {"ingress": {}}}],
            }
        },
    }


def all_objects(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    ns = p["namespace"]
    return [
        *crds(),
        *rbac(ns),
        deployment(ns),
        issuer(ns, p["acme_email"], p["acme_url"]),
    ]


register("cert-manager", "cert-manager with Let's Encrypt ACME issuer", [
    Param("namespace", "default", "string"),
    Param("acme_email", REQUIRED, "string",
          "The Lets Encrypt account email address."),
    Param("acme_url", "https://acme-v02.api.letsencrypt.org/directory",
          "string", "The ACME server URL."),
], package="core")(all_objects)
