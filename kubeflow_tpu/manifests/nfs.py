# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""NFS provisioning on GCE persistent disks + GCS-FUSE option.

Replaces reference ``kubeflow/core/nfs.libsonnet``: per-disk
StorageClass/PVC/Service/Deployment of nfs-provisioner ``:49-221``,
RBAC incl. volume-provisioner role ``:223-299``, comma-string disk
list ``:22``. TPU delta: an optional GCS-FUSE flavor — TPU VM pods
usually stream checkpoints/models from GCS rather than NFS.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.manifests import k8s
from kubeflow_tpu.params import Param, register

PROVISIONER_IMAGE = "quay.io/kubernetes_incubator/nfs-provisioner:v1.0.8"


def disk_objects(namespace: str, disk: str) -> List[Dict[str, Any]]:
    name = f"nfs-{disk}"
    labels = {"app": name}
    provisioner = f"github.com/kubernetes-incubator/nfs-provisioner-{disk}"
    container = k8s.container(
        "nfs-provisioner", PROVISIONER_IMAGE,
        args=[f"-provisioner={provisioner}"],
        env=[
            k8s.env_var("POD_IP", field_path="status.podIP"),
            k8s.env_var("SERVICE_NAME", name),
            k8s.env_var("POD_NAMESPACE", field_path="metadata.namespace"),
        ],
        ports=[k8s.port(2049, "nfs"), k8s.port(20048, "mountd"),
               k8s.port(111, "rpcbind")],
        security_context={"capabilities": {"add": ["DAC_READ_SEARCH",
                                                   "SYS_RESOURCE"]}},
        volume_mounts=[k8s.volume_mount("export-volume", "/export")],
    )
    spec = k8s.pod_spec([container], service_account="nfs-provisioner",
                        volumes=[{
                            "name": "export-volume",
                            "gcePersistentDisk": {"pdName": disk},
                        }])
    return [
        k8s.storage_class(name, provisioner),
        k8s.pvc(f"{name}-external", namespace, "1Mi", storage_class=name,
                access_modes=("ReadWriteMany",)),
        k8s.service(name, namespace, labels, [
            k8s.service_port(2049, name="nfs"),
            k8s.service_port(20048, name="mountd"),
            k8s.service_port(111, name="rpcbind"),
        ], labels=labels),
        k8s.deployment(name, namespace, spec, labels=labels),
    ]


def rbac(namespace: str) -> List[Dict[str, Any]]:
    return [
        k8s.service_account("nfs-provisioner", namespace),
        k8s.cluster_role_binding(
            "nfs-provisioner", "system:persistent-volume-provisioner",
            [k8s.subject("ServiceAccount", "nfs-provisioner", namespace)]),
        k8s.role("nfs-provisioner", namespace, [
            k8s.policy_rule([""], ["services", "endpoints"],
                            ["get", "list", "watch", "create", "update",
                             "patch"]),
        ]),
        k8s.role_binding("nfs-provisioner", namespace, "nfs-provisioner",
                         [k8s.subject("ServiceAccount", "nfs-provisioner",
                                      namespace)]),
    ]


def all_objects(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    disks = p["disks"]
    if not disks:
        return []
    ns = p["namespace"]
    objs = rbac(ns)
    for disk in disks:
        objs.extend(disk_objects(ns, disk))
    return objs


register("nfs", "NFS provisioners over GCE persistent disks", [
    Param("namespace", "default", "string"),
    Param("disks", "", "array",
          "Comma separated list of GCE persistent disks."),
], package="core")(all_objects)
