# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""CI plane: Argo e2e and release Workflow builders.

Replaces the reference's ksonnet CI components:

- presubmit e2e DAG: ``testing/workflows/components/workflows.libsonnet``
  — step DAG checkout → {setup, create-pr-symlink} → {tpujob-test,
  unit-test, serving-test}, onExit teardown → copy-artifacts
  (``:132-176``), with a buildTemplate helper injecting
  PYTHONPATH/creds env + the shared NFS volume into every step
  (``:58-99``), and prow env plumbing (``:5-20``).
- release DAG: ``releasing/releaser/components/workflows.libsonnet``
  — checkout → parallel image builds (DinD ``build_image.sh``) →
  deploy + smoke test (``:135-163,197-337``).

Same DAG shapes, TPU deltas: the tpujob E2E runs on a TPU nodepool,
images are the zero-CUDA families (serving-tpu, notebook-tpu,
trainer), and tests emit junit via kubeflow_tpu.utils.junit.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from kubeflow_tpu.manifests import k8s
from kubeflow_tpu.params import Param, REQUIRED, register

TEST_WORKER_IMAGE = "ghcr.io/kubeflow-tpu/test-worker:v0.1.0"
DIND_IMAGE = "docker:24-dind"

MOUNT_PATH = "/mnt/test-data-volume"


def _step_template(
    name: str,
    command: Sequence[str],
    *,
    params: Dict[str, Any],
    image: str = TEST_WORKER_IMAGE,
    extra_env: Optional[List[Dict[str, Any]]] = None,
    sidecars: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """The buildTemplate equivalent (reference ``workflows.libsonnet:
    58-99``): every step shares the NFS volume, artifact dir, and
    credential env."""
    env = [
        k8s.env_var("PYTHONPATH", f"{params['src_dir']}"),
        k8s.env_var("KFT_ARTIFACTS_DIR", params["artifacts_dir"]),
        k8s.env_var("JOB_NAME", params["job_name"]),
    ]
    if params.get("gcp_credentials_secret"):
        env.append(k8s.env_var(
            "GOOGLE_APPLICATION_CREDENTIALS",
            f"{MOUNT_PATH}/secrets/gcp-credentials/key.json"))
    env.extend(extra_env or [])
    template = {
        "name": name,
        "container": k8s._prune({
            "name": name,
            "image": image,
            "command": list(command),
            "env": env,
            "volumeMounts": [
                k8s.volume_mount(params["volume_name"], MOUNT_PATH),
            ],
            "workingDir": params["src_dir"],
        }),
    }
    if sidecars:
        template["sidecars"] = sidecars
    return template


def _dag_task(name: str, deps: Sequence[str]) -> Dict[str, Any]:
    task = {"name": name, "template": name}
    if deps:
        task["dependencies"] = list(deps)
    return task


def e2e_workflow(params: Dict[str, Any]) -> Dict[str, Any]:
    """The presubmit Workflow CR (reference ``workflows.libsonnet:
    100-248``)."""
    name = params["name"]
    namespace = params["namespace"]
    src = params["src_dir"]
    py = "python"

    steps = {
        "checkout": [
            "/bin/sh", "-c",
            f"mkdir -p {src} && git clone --depth=1 "
            f"{params['repo']} {src} && cd {src} && "
            f"git fetch origin {params['commit']} && "
            f"git checkout {params['commit']}",
        ],
        "create-pr-symlink": [
            py, "-m", "kubeflow_tpu.citests.artifacts", "create-pr-symlink",
        ],
        "unit-test": [
            py, "-m", "kubeflow_tpu.citests.unit",
            "--junit_path", f"{params['artifacts_dir']}/junit_unit.xml",
        ],
        # Presubmit lint gate (reference Makefile:15-18 shape): syntax,
        # import smoke, CLI boot, unused imports. The round-1 import
        # bug class dies here, before any cluster work starts.
        "lint-test": [
            py, f"{src}/scripts/lint.py",
        ],
        # Race-detection tier (SURVEY §5): tsan+asan stress of the
        # native queue/gang kernel. Hermetic — needs only g++.
        "sanitizer-test": [
            "make", "-C", f"{src}/native", "check-sanitizers",
        ],
        # Leader-failover-mid-restart (the last open VERDICT-r5
        # item): kill the lease holder between gang teardown and
        # recreation; the standby must resync its informers and
        # finish the restart without duplicate pods. Hermetic — the
        # crash is simulated, no cluster involved.
        "leader-failover-test": [
            py, "-m", "kubeflow_tpu.citests.leader_failover", "--fake",
            "--junit_path",
            f"{params['artifacts_dir']}/junit_leader_failover.xml",
        ],
        # Elastic-kill test (ISSUE 12): kill 1 of 4 gang hosts
        # mid-run — the reconciler must RESIZE the gang (Running
        # throughout, zero duplicate pods, no restart-budget burn)
        # and the seeded training run must resume from the
        # continuous sharded checkpoint on the surviving hosts with
        # < 2 steps lost and the same loss curve. Hermetic — fake
        # apiserver + virtual CPU devices.
        "elastic-kill-test": [
            py, "-m", "kubeflow_tpu.citests.elastic", "--fake",
            "--junit_path",
            f"{params['artifacts_dir']}/junit_elastic.xml",
        ],
        # Serving-mesh dryrun (ISSUE 10): the MULTICHIP-style gate
        # for the sharded export/load path — a CPU child pinned to a
        # virtual 2-device platform proves placement + bitwise
        # serving equality (and fails on XLA SPMD quality warnings)
        # before any TPU is involved. Hermetic — no cluster.
        "serving-mesh-dryrun": [
            py, f"{src}/scripts/dryrun_serving_mesh.py",
            "--devices", "2",
            "--junit_path",
            f"{params['artifacts_dir']}/junit_serving_mesh.xml",
        ],
        # Serving-chaos gate (ISSUE 13): the gray-failure resilience
        # sweep — a 3-replica stub fleet behind the pooled proxy with
        # one replica browned out to 10x latency (healthz stays
        # green) and one severing token streams mid-flight. Brownout
        # soft-eject must engage within 2 probe windows, gray-fleet
        # goodput must hold >= 0.9x clean, p99-of-successes must stay
        # within deadline, and every resumed stream must stitch a
        # bitwise-exact token sequence. Hermetic — sleep-based stub
        # replicas, no cluster, no accelerator.
        "serving-chaos": [
            py, f"{src}/bench.py", "--chaos",
        ],
        # Tenant-isolation gate (ISSUE 14): the noisy-neighbor sweep
        # — one tenant at 4x its quota vs three compliant tenants at
        # 0.8x, isolation off vs on. With isolation on, no compliant
        # tenant's p99 may cross its deadline, compliant tenants see
        # zero quota sheds, and the noisy excess must bounce as its
        # own structured 429s. Hermetic — sleep-based stub model, no
        # cluster, no accelerator (mirrors serving-chaos).
        "serving-tenancy": [
            py, f"{src}/bench.py", "--tenants",
        ],
        # Spec-decode gate (ISSUE 16): the speculative-decoding sweep
        # — a real CPU engine drafting k tokens per slot and verifying
        # them in one batched forward. Greedy AND sampled outputs must
        # stay bitwise-equal to vanilla B=1 decode, the strong-draft
        # acceptance rate must be nonzero, and per-slot verifier
        # forwards per emitted token must drop below 1.0. Hermetic —
        # tiny test model on JAX CPU, no cluster, no accelerator.
        "spec-decode": [
            py, f"{src}/bench.py", "--speculative",
        ],
        # Fleet-sim gate (ISSUE 19): the trace-calibrated simulator
        # sweep — record three closed-loop workloads against a stub
        # fleet through the real router, calibrate the sim's service
        # distribution from each recording (Little's law), and assert
        # replayed p99 within 10% of measured for every workload; then
        # replay a ramped traffic spike through the production
        # autoscaler reactive vs predictive and assert predictive cuts
        # time-over-SLO without exceeding the replica budget. Writes
        # sim_validation.json under $KFT_OBS_DIR for the collect-obs
        # sweep. Hermetic — sleep-based stub replicas + a pure
        # deterministic sim, no cluster, no accelerator.
        "fleet-sim": [
            py, f"{src}/bench.py", "--sim",
        ],
        # Tiered-KV gate (ISSUE 20): the tiered prefix-cache sweep —
        # a chat replay whose prefix working set is 4x the HBM page
        # pool, r15 HBM-only baseline vs the host-RAM spill tier at
        # a tiny pool. Tiering must hold >= 70% effective hit rate
        # where the baseline collapses, host re-adopts must be doing
        # the holding, and outputs must stay bitwise-equal to B=1
        # generate, greedy and sampled. Writes kv_tier_stats.json
        # under $KFT_OBS_DIR for the collect-obs sweep (the fleet
        # sim's prefix-hit service class calibrates from it).
        # Hermetic — tiny test model on JAX CPU, no cluster, no
        # accelerator.
        "kv-tier": [
            py, f"{src}/bench.py", "--prefix",
            "--working-set-multiple",
        ],
        # Trace-assembly gate (ISSUE 15): the distributed-tracing
        # sweep — a real proxy + two role-split servers + a span-
        # scraping collector; unary, SSE, role-split and hedged
        # requests must each assemble into ONE trace whose
        # queue/prefill/decode/relay/gap attribution covers >= 95% of
        # the client-measured wall, and the SpanStore caps must hold
        # under fuzz. Hermetic — in-process fleet, no cluster.
        "trace-assembly": [
            py, "-m", "pytest", f"{src}/tests/test_trace_assembly.py",
            "-q", "--junitxml",
            f"{params['artifacts_dir']}/junit_trace_assembly.xml",
        ],
        "deploy-test": [
            py, "-m", "kubeflow_tpu.citests.deploy", "setup",
            "--namespace", params["test_namespace"],
            "--junit_path", f"{params['artifacts_dir']}/junit_deploy.xml",
        ],
        # kubeflow-core has no serving objects; the serving e2e needs
        # the tpu-serving prototype applied first.
        "deploy-serving": [
            py, "-m", "kubeflow_tpu.citests.deploy", "deploy-serving",
            "--namespace", params["test_namespace"],
            "--junit_path",
            f"{params['artifacts_dir']}/junit_deploy_serving.xml",
        ],
        "tpujob-test": [
            py, "-m", "kubeflow_tpu.citests.tpujob",
            "--namespace", params["test_namespace"],
            "--junit_path", f"{params['artifacts_dir']}/junit_tpujob.xml",
        ],
        "serving-test": [
            py, "-m", "kubeflow_tpu.citests.serving",
            "--namespace", params["test_namespace"],
            "--junit_path", f"{params['artifacts_dir']}/junit_serving.xml",
        ],
        "dashboard-test": [
            py, "-m", "kubeflow_tpu.citests.dashboard",
            "--namespace", params["test_namespace"],
            "--junit_path",
            f"{params['artifacts_dir']}/junit_dashboard.xml",
        ],
        "teardown": [
            py, "-m", "kubeflow_tpu.citests.deploy", "teardown",
            "--namespace", params["test_namespace"],
            "--junit_path", f"{params['artifacts_dir']}/junit_teardown.xml",
        ],
        "copy-artifacts": [
            py, "-m", "kubeflow_tpu.citests.artifacts", "copy",
            "--bucket", params["bucket"],
        ],
    }
    templates = [
        _step_template(step, cmd, params=params)
        for step, cmd in steps.items()
    ]
    templates.append({
        "name": "e2e",
        "dag": {"tasks": [
            _dag_task("checkout", []),
            _dag_task("create-pr-symlink", ["checkout"]),
            _dag_task("lint-test", ["checkout"]),
            _dag_task("unit-test", ["checkout"]),
            _dag_task("sanitizer-test", ["checkout"]),
            _dag_task("leader-failover-test", ["checkout"]),
            _dag_task("elastic-kill-test", ["checkout"]),
            _dag_task("serving-mesh-dryrun", ["checkout"]),
            _dag_task("serving-chaos", ["checkout"]),
            _dag_task("serving-tenancy", ["checkout"]),
            _dag_task("spec-decode", ["checkout"]),
            _dag_task("fleet-sim", ["checkout"]),
            _dag_task("kv-tier", ["checkout"]),
            _dag_task("trace-assembly", ["checkout"]),
            _dag_task("deploy-test", ["checkout"]),
            _dag_task("deploy-serving", ["deploy-test"]),
            _dag_task("tpujob-test", ["deploy-test"]),
            _dag_task("serving-test", ["deploy-serving"]),
            _dag_task("dashboard-test", ["deploy-test"]),
        ]},
    })
    templates.append({
        "name": "exit-handler",
        "dag": {"tasks": [
            _dag_task("teardown", []),
            {"name": "copy-artifacts", "template": "copy-artifacts",
             "dependencies": ["teardown"]},
        ]},
    })

    return {
        "apiVersion": "argoproj.io/v1alpha1",
        "kind": "Workflow",
        "metadata": k8s.metadata(name, namespace,
                                 labels={"workflow": "kubeflow-tpu-e2e"}),
        "spec": {
            "entrypoint": "e2e",
            "onExit": "exit-handler",
            "volumes": [
                {"name": params["volume_name"],
                 "persistentVolumeClaim": {"claimName": params["nfs_claim"]}},
            ],
            "templates": templates,
        },
    }


def release_workflow(params: Dict[str, Any]) -> Dict[str, Any]:
    """Image-release Workflow (reference ``releasing/releaser/components/
    workflows.libsonnet:135-337``): checkout → parallel DinD image
    builds → deploy → smoke test."""
    name = params["name"]
    registry = params["registry"]
    tag = params["version_tag"]
    src = params["src_dir"]

    dind_sidecar = [{
        "name": "dind",
        "image": DIND_IMAGE,
        "securityContext": {"privileged": True},
        "mirrorVolumeMounts": True,
    }]
    build_env = [k8s.env_var("DOCKER_HOST", "127.0.0.1")]

    # One family per first-party image the manifests reference; each
    # has images/<family>/Dockerfile (tests assert the mapping).
    image_families = ["model-server", "model-server-http-proxy",
                      "trainer", "jax-notebook", "jupyterhub-k8s",
                      "tpujob-operator", "tpujob-dashboard",
                      "test-worker"]
    templates = [
        _step_template("checkout", [
            "/bin/sh", "-c",
            f"mkdir -p {src} && git clone --depth=1 {params['repo']} {src} "
            f"&& cd {src} && git checkout {params['commit']}",
        ], params=params),
    ]
    for family in image_families:
        templates.append(_step_template(
            f"build-{family}",
            ["/bin/sh", f"{src}/images/build_image.sh",
             family, f"{registry}/{family}:{tag}"],
            params=params, extra_env=build_env, sidecars=dind_sidecar,
        ))
    templates.append(_step_template(
        "smoke-test",
        ["python", "-m", "kubeflow_tpu.citests.serving",
         "--namespace", params["test_namespace"],
         "--junit_path", f"{params['artifacts_dir']}/junit_release.xml"],
        params=params,
    ))
    templates.append({
        "name": "release",
        "dag": {"tasks": [
            _dag_task("checkout", []),
            *[_dag_task(f"build-{f}", ["checkout"]) for f in image_families],
            _dag_task("smoke-test",
                      [f"build-{f}" for f in image_families]),
        ]},
    })
    return {
        "apiVersion": "argoproj.io/v1alpha1",
        "kind": "Workflow",
        "metadata": k8s.metadata(name, params["namespace"],
                                 labels={"workflow": "kubeflow-tpu-release"}),
        "spec": {
            "entrypoint": "release",
            "volumes": [
                {"name": params["volume_name"],
                 "persistentVolumeClaim": {"claimName": params["nfs_claim"]}},
            ],
            "templates": templates,
        },
    }


_COMMON_PARAMS = [
    Param("name", REQUIRED, "string", "workflow object name"),
    Param("namespace", "kubeflow-test-infra", "string",
          "namespace to run the workflow in"),
    Param("repo", "https://github.com/kubeflow-tpu/kubeflow-tpu.git",
          "string", "git repo URL to test"),
    Param("commit", "HEAD", "string", "commit/ref to check out"),
    Param("bucket", "kubeflow-tpu-ci-results", "string",
          "GCS bucket for junit artifacts"),
    Param("nfs_claim", "nfs-external", "string",
          "shared NFS PVC for step state"),
    Param("volume_name", "test-data-volume", "string",
          "workflow volume name"),
    Param("src_dir", f"{MOUNT_PATH}/src/kubeflow-tpu", "string",
          "checkout dir on the shared volume"),
    Param("artifacts_dir", f"{MOUNT_PATH}/artifacts", "string",
          "junit/log output dir"),
    Param("job_name", "manual", "string",
          "prow job name (env passthrough)"),
    Param("test_namespace", "kubeflow-e2e", "string",
          "ephemeral namespace for the deploy test"),
    Param("gcp_credentials_secret", "", "string",
          "secret with GCP SA key (optional)"),
]


@register("ci-e2e", "Presubmit E2E Argo workflow (deploy, tpujob, serving)",
          _COMMON_PARAMS, package="ci")
def _build_e2e(params: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [e2e_workflow(params)]


@register("ci-release",
          "Image release Argo workflow (DinD builds + smoke test)",
          _COMMON_PARAMS + [
              Param("registry", "ghcr.io/kubeflow-tpu", "string",
                    "image registry"),
              Param("version_tag", REQUIRED, "string",
                    "image tag to publish"),
          ], package="ci")
def _build_release(params: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [release_workflow(params)]
