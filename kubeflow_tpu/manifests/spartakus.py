# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Opt-in anonymous usage telemetry (spartakus).

Replaces reference ``kubeflow/core/spartakus.libsonnet``: ClusterRole
to list nodes ``:19-42``, volunteer Deployment ``:80-111``, gated on a
``reportUsage`` bool ``:4-14``. No TPU delta.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.manifests import k8s
from kubeflow_tpu.params import Param, register

IMAGE = "gcr.io/google_containers/spartakus-amd64:v1.0.0"


def all_objects(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    if not p["report_usage"]:
        # Telemetry is strictly opt-in (parity :4-14).
        return []
    ns = p["namespace"]
    labels = {"app": "spartakus"}
    container = k8s.container(
        "volunteer", IMAGE,
        args=[f"volunteer", f"--cluster-id={p['usage_id']}",
              "--database=https://stats-collector.kubeflow.org"],
    )
    return [
        k8s.service_account("spartakus", ns, labels=labels),
        k8s.cluster_role("spartakus", [
            k8s.policy_rule([""], ["nodes"], ["list"]),
        ], labels=labels),
        k8s.cluster_role_binding(
            "spartakus", "spartakus",
            [k8s.subject("ServiceAccount", "spartakus", ns)], labels=labels),
        k8s.deployment(
            "spartakus-volunteer", ns,
            k8s.pod_spec([container], service_account="spartakus"),
            labels=labels),
    ]


register("spartakus", "Opt-in anonymous usage telemetry", [
    Param("namespace", "default", "string"),
    Param("report_usage", "false", "bool",
          "Whether or not to report Kubeflow usage to kubeflow.org."),
    Param("usage_id", "unknown_cluster", "string",
          "Optional id to use when reporting usage."),
], package="core")(all_objects)
