# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Argo Workflows install (CI workflow engine).

Replaces reference ``kubeflow/argo/argo.libsonnet``: Workflow CRD
``:25-45``, workflow-controller Deployment + executor ConfigMap
``:48-120,225-235``, argo-ui ``:123-223``, RBAC ``:237-427``. No TPU
delta; versions modernized.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.manifests import k8s
from kubeflow_tpu.params import Param, register

CONTROLLER_IMAGE = "quay.io/argoproj/workflow-controller:v3.4.4"
EXECUTOR_IMAGE = "quay.io/argoproj/argoexec:v3.4.4"
UI_IMAGE = "quay.io/argoproj/argocli:v3.4.4"


def crd() -> Dict[str, Any]:
    return k8s.crd("workflows.argoproj.io", "argoproj.io", "v1alpha1",
                   "Workflow", "workflows", short_names=["wf"])


def controller(namespace: str) -> List[Dict[str, Any]]:
    cm = k8s.config_map(
        "workflow-controller-configmap", namespace,
        {"config": f"executorImage: {EXECUTOR_IMAGE}\n"})
    container = k8s.container(
        "workflow-controller", CONTROLLER_IMAGE,
        command=["workflow-controller"],
        args=["--configmap", "workflow-controller-configmap",
              "--executor-image", EXECUTOR_IMAGE],
    )
    deploy = k8s.deployment(
        "workflow-controller", namespace,
        k8s.pod_spec([container], service_account="argo"),
        labels={"app": "workflow-controller"})
    return [cm, deploy]


def ui(namespace: str, service_type: str) -> List[Dict[str, Any]]:
    labels = {"app": "argo-ui"}
    container = k8s.container(
        "argo-ui", UI_IMAGE,
        args=["server", "--namespaced"],
        ports=[k8s.port(2746)],
        env=[k8s.env_var("ARGO_NAMESPACE", field_path="metadata.namespace")],
    )
    return [
        k8s.deployment("argo-ui", namespace,
                       k8s.pod_spec([container], service_account="argo-ui"),
                       labels=labels),
        k8s.service("argo-ui", namespace, labels,
                    [k8s.service_port(80, target_port=2746)],
                    service_type=service_type, labels=labels),
    ]


def rbac(namespace: str) -> List[Dict[str, Any]]:
    wf_rules = [
        k8s.policy_rule([""], ["pods", "pods/exec", "pods/log"], ["*"]),
        k8s.policy_rule([""], ["secrets", "configmaps"], ["get", "list", "watch"]),
        k8s.policy_rule([""], ["persistentvolumeclaims"], ["create", "delete"]),
        k8s.policy_rule(["argoproj.io"], ["workflows", "workflows/finalizers"],
                        ["*"]),
    ]
    return [
        k8s.service_account("argo", namespace),
        k8s.cluster_role("argo", wf_rules),
        k8s.cluster_role_binding(
            "argo", "argo", [k8s.subject("ServiceAccount", "argo", namespace)]),
        k8s.service_account("argo-ui", namespace),
        k8s.cluster_role("argo-ui", [
            k8s.policy_rule([""], ["pods", "pods/log"], ["get", "list", "watch"]),
            k8s.policy_rule(["argoproj.io"], ["workflows"], ["get", "list", "watch"]),
        ]),
        k8s.cluster_role_binding(
            "argo-ui", "argo-ui",
            [k8s.subject("ServiceAccount", "argo-ui", namespace)]),
    ]


def all_objects(p: Dict[str, Any]) -> List[Dict[str, Any]]:
    ns = p["namespace"]
    return [crd(), *controller(ns), *ui(ns, p["ui_service_type"]), *rbac(ns)]


register("argo", "Argo workflow engine (CI plane)", [
    Param("namespace", "default", "string"),
    Param("ui_service_type", "NodePort", "string"),
], package="argo")(all_objects)
