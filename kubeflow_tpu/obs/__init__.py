# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Dependency-free observability: metrics, tracing, exposition.

The reference stack's observability was statsd sidecars flushing to a
collector plus TensorBoard for traces (SURVEY §5); nothing was
scrapeable and no request could be followed across hops. This package
is the rebuild's first-class replacement, stdlib-only:

- :mod:`kubeflow_tpu.obs.metrics` — Counter/Gauge/Histogram with
  labels and correct Prometheus text exposition, one process-wide
  default registry.
- :mod:`kubeflow_tpu.obs.tracing` — ``X-Request-Id`` / W3C
  ``traceparent`` request context propagated over HTTP headers and
  gRPC metadata, plus an in-process bounded span ring buffer exported
  as Chrome-trace-event JSON (openable in Perfetto).
- :mod:`kubeflow_tpu.obs.exposition` — ``/metrics`` + ``/tracez``
  tornado handlers (OpenMetrics content negotiation, span query
  filters), a stdlib exposition thread for processes without tornado
  (the operator), and the structured JSON access-log hook.
- :mod:`kubeflow_tpu.obs.collector` — the fleet telemetry collector:
  a scrape loop over the serving fleet + static targets feeding a
  windowed in-memory time-series store (counter-reset-aware rates,
  histogram quantiles, cross-replica aggregation, cardinality cap).
- :mod:`kubeflow_tpu.obs.slo` — declarative SLOs evaluated with
  Google-SRE multi-window burn rates; the alert state machine
  publishes Events, the ``kft-alerts`` ConfigMap and
  ``kft_alert_state`` gauges.

Everything here must be cheap enough to leave on in production:
``bench.py --obs-overhead`` asserts <2% serving-throughput cost with
metrics AND tracing enabled, and ``bench.py --slo`` asserts ≤2%
collector cost (PERF.md).
"""

from kubeflow_tpu.obs import metrics, tracing  # noqa: F401
