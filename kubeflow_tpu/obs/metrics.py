# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Prometheus-style metrics: Counter / Gauge / Histogram + exposition.

Dependency-free equivalent of the prometheus_client essentials, sized
for this tree's four scrape surfaces (serving server, HTTP proxy,
operator, dashboard). What matters and is easy to get wrong:

- **Text exposition format**: one ``# HELP`` + ``# TYPE`` block per
  metric family, samples as ``name{label="value"} <float>``, label
  values escaped (``\\`` ``\"`` ``\n``), HELP text escaped
  (``\\`` ``\n``). :func:`parse_exposition` is the strict inverse —
  tests scrape every endpoint through it, so a malformed escape or a
  TYPE-less family fails CI, not the first real Prometheus scrape.
- **Histogram semantics**: buckets are CUMULATIVE (each ``le`` bucket
  counts all observations ≤ its bound), ``+Inf`` equals ``_count``,
  and ``_sum`` is the raw total — Grafana's ``histogram_quantile``
  silently lies if any of that is off.
- **Cardinality**: a label value per request id is a time-series-per-
  request explosion that kills any TSDB. Label names that imply it
  (:data:`FORBIDDEN_LABELS`) are rejected at metric construction, and
  ``scripts/lint.py`` enforces the same statically.

Updates are a dict lookup + float add under a per-child lock — cheap
enough to leave on; :func:`set_enabled` exists so the overhead bench
can measure the cost rather than assume it.
"""

from __future__ import annotations

import bisect
import json
import re
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "CONTENT_TYPE",
    "CONTENT_TYPE_OPENMETRICS",
    "FORBIDDEN_LABELS",
    "Counter",
    "Gauge",
    "Histogram",
    "REGISTRY",
    "Registry",
    "counter_increase",
    "dump_jsonl",
    "enabled",
    "negotiate_content_type",
    "parse_exposition",
    "render",
    "set_enabled",
]

#: The Prometheus text exposition content type (format version 0.0.4).
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: The OpenMetrics text content type — the format that carries
#: exemplars. Served only when the scraper ASKS for it via Accept
#: (see :func:`negotiate_content_type`); everything else gets 0.0.4.
CONTENT_TYPE_OPENMETRICS = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8")


def negotiate_content_type(accept: Optional[str]) -> str:
    """Scrape-handler content negotiation: OpenMetrics when the
    client's ``Accept`` names it, Prometheus text 0.0.4 otherwise —
    the fallback ladder real Prometheus servers use. Exemplars only
    ride the OpenMetrics form (the 0.0.4 grammar has no ``#`` exemplar
    clause, and a strict 0.0.4 parser would reject it)."""
    if accept and "application/openmetrics-text" in accept:
        return CONTENT_TYPE_OPENMETRICS
    return CONTENT_TYPE


def counter_increase(prev: float, cur: float) -> float:
    """Increase of a cumulative counter between two samples, aware of
    process restarts: a counter that DROPPED was reset to zero (the
    replica restarted) and has climbed back to ``cur`` — the increase
    since the previous sample is at least ``cur``, never the negative
    delta. One shared helper for every rate() computed from scraped
    counters (the collector's store and the autoscaler's shed-rate
    differencing both ride this; a naive subtraction turns one
    replica restart into a huge negative rate)."""
    if cur >= prev:
        return cur - prev
    return max(0.0, cur)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: Label names whose values are per-request/per-object by construction:
#: one time series per request is the classic cardinality explosion.
#: High-cardinality data belongs in spans (obs/tracing.py) and access
#: logs, never in metric labels. Enforced here at construction AND
#: statically by scripts/lint.py check_metric_label_discipline.
FORBIDDEN_LABELS = frozenset({
    "request_id", "trace_id", "span_id", "batch_id", "pod_uid", "uid",
})

#: Default histogram buckets (seconds-oriented, same as
#: prometheus_client): sub-ms to 10s covers queue waits, dispatches,
#: reconciles and training steps alike.
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0)

# Process-wide update switch (the obs-overhead bench measures with
# this on vs off). One attribute read per update when disabled.
_enabled = True


def set_enabled(value: bool) -> None:
    """Globally enable/disable metric UPDATES (registration and
    rendering always work — a disabled registry renders zeros)."""
    global _enabled
    _enabled = bool(value)


def enabled() -> bool:
    return _enabled


def escape_label_value(value: str) -> str:
    return (value.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def escape_help(value: str) -> str:
    return value.replace("\\", "\\\\").replace("\n", "\\n")


class Registry:
    """A named collection of metric families; renders the exposition.

    ``reset()`` zeroes every value but KEEPS registrations — metric
    objects are module-level singletons bound at import, so dropping
    them from the registry would orphan every instrumented module.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, "_Metric"] = {}

    def register(self, metric: "_Metric") -> None:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None and existing is not metric:
                # Same definition registered twice happens legally
                # when a module body runs as BOTH `pkg.mod` and
                # `__main__` (python -m pkg.mod with a re-exporting
                # __init__): last wins, matching how the re-executed
                # module's objects are the live ones. A DIFFERENT
                # definition under one name is a real bug.
                if (type(existing) is not type(metric)
                        or existing.labelnames != metric.labelnames
                        or existing.help != metric.help
                        or getattr(existing, "buckets", None)
                        != getattr(metric, "buckets", None)):
                    raise ValueError(
                        f"metric {metric.name!r} already registered")
            self._metrics[metric.name] = metric

    def reset(self) -> None:
        """Zero every value IN PLACE (test isolation). Children are
        kept, not dropped: hot-path modules cache child objects at
        construction (e.g. ServedModel binds its shed counter once) —
        dropping children would orphan those caches, and their later
        updates would silently stop rendering."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m._reset()

    def collect(self) -> List["_Metric"]:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def render(self, openmetrics: bool = False) -> str:
        out: List[str] = []
        for metric in self.collect():
            out.append(f"# HELP {metric.name} {escape_help(metric.help)}")
            out.append(f"# TYPE {metric.name} {metric.type}")
            out.extend(metric._samples(openmetrics=openmetrics))
        if openmetrics:
            # The OpenMetrics terminator: a scraper that sees no EOF
            # treats the scrape as truncated.
            out.append("# EOF")
        return "\n".join(out) + "\n" if out else ""


#: The process-wide default registry every module instruments against.
REGISTRY = Registry()


def render(registry: Optional[Registry] = None,
           openmetrics: bool = False) -> str:
    return (registry or REGISTRY).render(openmetrics=openmetrics)


def _format_value(value: float) -> str:
    if value == float("inf"):
        return "+Inf"
    if value == float("-inf"):
        return "-Inf"
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _label_str(labelnames: Tuple[str, ...],
               labelvalues: Tuple[str, ...]) -> str:
    if not labelnames:
        return ""
    pairs = ",".join(
        f'{k}="{escape_label_value(v)}"'
        for k, v in zip(labelnames, labelvalues))
    return "{" + pairs + "}"


class _Child:
    """One labeled time series of a family. Holds its own lock: two
    threads bumping different children never contend."""

    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def reset(self) -> None:
        """Zero the stored value (render callbacks are live state and
        survive — they read the world, not this counter)."""
        with self._lock:
            self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value += amount

    def set(self, value: float) -> None:
        if not _enabled:
            return
        with self._lock:
            self._value = float(value)

    def set_function(self, fn: Callable[[], float]) -> None:
        """Read the value from ``fn`` at render time (bridges existing
        counters/queues without double bookkeeping). The callback must
        be cheap and thread-safe; a raising callback renders 0 rather
        than failing the whole scrape."""
        with self._lock:
            self._fn = fn

    def clear_function(self, owner: Any = None) -> None:
        """Drop the render-time callback — a bound-method callback on
        a registry-lifetime metric otherwise pins its object (and
        everything it references) forever. With ``owner``, clears only
        if the current callback is a method bound to that object, so a
        stopped instance never clobbers a newer instance's binding."""
        with self._lock:
            if self._fn is None:
                return
            if (owner is not None
                    and getattr(self._fn, "__self__", None)
                    is not owner):
                return
            self._fn = None

    def get(self) -> float:
        with self._lock:
            if self._fn is not None:
                try:
                    return float(self._fn())
                except Exception:  # noqa: BLE001 — never fail a scrape
                    return 0.0
            return self._value


class _Metric:
    type = "untyped"

    def __init__(self, name: str, help: str,  # noqa: A002 — prom idiom
                 labelnames: Iterable[str] = (),
                 registry: Optional[Registry] = REGISTRY):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        labelnames = tuple(labelnames)
        for label in labelnames:
            if not _LABEL_RE.match(label) or label.startswith("__"):
                raise ValueError(f"invalid label name {label!r}")
            if label in FORBIDDEN_LABELS:
                raise ValueError(
                    f"label {label!r} on metric {name!r} is per-request "
                    f"cardinality — put it in a span or access log, "
                    f"not a metric label")
        self.name = name
        self.help = help
        self.labelnames = labelnames
        self._children: Dict[Tuple[str, ...], Any] = {}
        self._children_lock = threading.Lock()
        if registry is not None:
            registry.register(self)

    def _make_child(self):
        return _Child()

    def labels(self, *labelvalues: str, **labelkw: str):
        if labelvalues and labelkw:
            raise ValueError("pass label values positionally OR by name")
        if labelkw:
            try:
                labelvalues = tuple(str(labelkw[k])
                                    for k in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"missing label {e.args[0]!r} for {self.name}"
                    ) from None
            if set(labelkw) - set(self.labelnames):
                raise ValueError(
                    f"unknown labels "
                    f"{sorted(set(labelkw) - set(self.labelnames))}")
        else:
            labelvalues = tuple(str(v) for v in labelvalues)
        if len(labelvalues) != len(self.labelnames):
            raise ValueError(
                f"{self.name} takes {len(self.labelnames)} label "
                f"values, got {len(labelvalues)}")
        with self._children_lock:
            child = self._children.get(labelvalues)
            if child is None:
                child = self._make_child()
                self._children[labelvalues] = child
            return child

    def _default_child(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; "
                f"use .labels(...)")
        return self.labels()

    def _reset(self) -> None:
        with self._children_lock:
            children = list(self._children.values())
        for child in children:
            child.reset()

    def remove_labels(self, *labelvalues: str) -> None:
        """Drop one labeled child (and everything its callbacks pin).
        For metrics labeled by a CHURNING identity — e.g. per-replica
        pod IPs — the series must leave /metrics when the member
        leaves the fleet, or cardinality and the closure-pinned
        objects grow for process lifetime. No-op when absent."""
        with self._children_lock:
            self._children.pop(tuple(str(v) for v in labelvalues),
                               None)

    def _iter_children(self):
        with self._children_lock:
            return list(self._children.items())

    def _samples(self, openmetrics: bool = False) -> List[str]:
        out = []
        for values, child in sorted(self._iter_children()):
            out.append(f"{self.name}"
                       f"{_label_str(self.labelnames, values)} "
                       f"{_format_value(child.get())}")
        if not out and not self.labelnames:
            out.append(f"{self.name} 0")
        return out


class _CounterChild(_Child):
    __slots__ = ()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters cannot decrease")
        super().inc(amount)


class Counter(_Metric):
    """Monotonically increasing value. ``inc`` only; negative
    increments raise (a decreasing counter corrupts rate()).
    ``set_function`` bridges pre-existing monotonic counters (e.g.
    the workqueue's lifetime totals) without double bookkeeping."""

    type = "counter"

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._default_child().set_function(fn)

    def _make_child(self):
        return _CounterChild()


class Gauge(_Metric):
    """A value that goes up and down; supports render-time callbacks
    (``set_function``) for bridging live state (queue depth, breaker
    state) without a write on every change."""

    type = "gauge"

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_child().inc(-amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._default_child().set_function(fn)


class _HistogramChild:
    __slots__ = ("_lock", "_buckets", "_counts", "_sum", "_count",
                 "_exemplars")

    def __init__(self, buckets: Tuple[float, ...],
                 exemplars: bool = False):
        self._lock = threading.Lock()
        self._buckets = buckets
        self._counts = [0] * len(buckets)
        self._sum = 0.0
        self._count = 0
        # One latest exemplar per bucket (index len(buckets) = +Inf):
        # (trace_id, value, unix_ts). Bounded by bucket count, so
        # exemplar memory can never grow with traffic.
        self._exemplars: Optional[List[Optional[Tuple[str, float,
                                                      float]]]] = (
            [None] * (len(buckets) + 1) if exemplars else None)

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * len(self._buckets)
            self._sum = 0.0
            self._count = 0
            if self._exemplars is not None:
                self._exemplars = [None] * (len(self._buckets) + 1)

    def observe(self, value: float,
                trace_id: Optional[str] = None) -> None:
        if not _enabled:
            return
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            # Per-bucket (non-cumulative) storage: one increment per
            # observe; the render accumulates. O(log n) search.
            i = bisect.bisect_left(self._buckets, value)
            if i < len(self._buckets):
                self._counts[i] += 1
            if trace_id and self._exemplars is not None:
                # The OpenMetrics exemplar: the trace that landed in
                # THIS bucket, latest wins — the join key from "the
                # p99 bucket grew" to the one slow request's spans.
                self._exemplars[i] = (str(trace_id)[:128], value,
                                      time.time())

    def snapshot(self):
        with self._lock:
            exemplars = (list(self._exemplars)
                         if self._exemplars is not None else None)
            return list(self._counts), self._sum, self._count, exemplars


class Histogram(_Metric):
    """Observations bucketed by upper bound. Exposition emits
    CUMULATIVE ``_bucket{le=...}`` samples (``+Inf`` == ``_count``),
    plus ``_sum`` and ``_count`` — the histogram_quantile contract.

    With ``exemplars=True``, ``observe(value, trace_id=...)`` pins the
    trace id to the bucket the observation lands in; the OpenMetrics
    render (``render(openmetrics=True)``) emits it as a bucket
    exemplar, which is how a dashboard jumps from "the deadline bucket
    grew" straight to one retained trace in ``/tracez?trace_id=``.
    The classic 0.0.4 render never carries exemplars (its grammar has
    none), so plain scrapers are unaffected."""

    type = "histogram"

    def __init__(self, name: str, help: str,  # noqa: A002
                 labelnames: Iterable[str] = (),
                 buckets: Iterable[float] = DEFAULT_BUCKETS,
                 registry: Optional[Registry] = REGISTRY,
                 exemplars: bool = False):
        buckets = tuple(sorted(float(b) for b in buckets))
        if not buckets:
            raise ValueError("histogram needs at least one bucket")
        if any(b2 <= b1 for b1, b2 in zip(buckets, buckets[1:])):
            raise ValueError(f"buckets must strictly increase: {buckets}")
        if buckets and buckets[-1] == float("inf"):
            buckets = buckets[:-1]  # +Inf is implicit
        self.buckets = buckets
        self.exemplars = bool(exemplars)
        super().__init__(name, help, labelnames, registry)

    def _make_child(self):
        return _HistogramChild(self.buckets, exemplars=self.exemplars)

    def observe(self, value: float,
                trace_id: Optional[str] = None) -> None:
        self._default_child().observe(value, trace_id=trace_id)

    @staticmethod
    def _exemplar_str(exemplar: Tuple[str, float, float]) -> str:
        trace_id, value, ts = exemplar
        return (f' # {{trace_id="{escape_label_value(trace_id)}"}} '
                f"{_format_value(value)} {ts:.3f}")

    def _samples(self, openmetrics: bool = False) -> List[str]:
        out = []
        for values, child in sorted(self._iter_children()):
            counts, total, count, exemplars = child.snapshot()
            if not openmetrics:
                exemplars = None
            cumulative = 0
            for i, (bound, n) in enumerate(zip(self.buckets, counts)):
                cumulative += n
                labels = _label_str(
                    self.labelnames + ("le",),
                    values + (_format_value(bound),))
                suffix = (self._exemplar_str(exemplars[i])
                          if exemplars and exemplars[i] else "")
                out.append(
                    f"{self.name}_bucket{labels} {cumulative}{suffix}")
            labels = _label_str(self.labelnames + ("le",),
                                values + ("+Inf",))
            suffix = (self._exemplar_str(exemplars[-1])
                      if exemplars and exemplars[-1] else "")
            out.append(f"{self.name}_bucket{labels} {count}{suffix}")
            base = _label_str(self.labelnames, values)
            out.append(f"{self.name}_sum{base} {_format_value(total)}")
            out.append(f"{self.name}_count{base} {count}")
        return out


# -- parsing (the test-side validator) ---------------------------------------


def _unescape_label_value(value: str) -> str:
    out = []
    i = 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt == "n":
                out.append("\n")
            elif nxt in ("\\", '"'):
                out.append(nxt)
            else:
                raise ValueError(f"bad escape \\{nxt} in label value")
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_labels(text: str) -> Dict[str, str]:
    labels: Dict[str, str] = {}
    i = 0
    while i < len(text):
        eq = text.index("=", i)
        name = text[i:eq].strip().lstrip(",").strip()
        if not _LABEL_RE.match(name):
            raise ValueError(f"bad label name {name!r}")
        if text[eq + 1] != '"':
            raise ValueError(f"label value for {name} not quoted")
        j = eq + 2
        raw = []
        while True:
            if j >= len(text):
                raise ValueError("unterminated label value")
            if text[j] == "\\":
                raw.append(text[j:j + 2])
                j += 2
                continue
            if text[j] == '"':
                break
            raw.append(text[j])
            j += 1
        labels[name] = _unescape_label_value("".join(raw))
        i = j + 1
    return labels


def _parse_exemplar(blob: str, lineno: int) -> Tuple[Dict[str, str],
                                                     float,
                                                     Optional[float]]:
    """Parse the OpenMetrics exemplar clause ``{labels} value [ts]``
    (the part after the sample's `` # `` separator)."""
    m = re.match(r"^\{(.*)\}\s+(\S+)(?:\s+(\S+))?$", blob.strip())
    if not m:
        raise ValueError(f"line {lineno}: malformed exemplar {blob!r}")
    label_blob, value_text, ts_text = m.groups()
    labels = _parse_labels(label_blob) if label_blob else {}
    try:
        value = float(value_text)
        ts = float(ts_text) if ts_text is not None else None
    except ValueError:
        raise ValueError(
            f"line {lineno}: bad exemplar value in {blob!r}") from None
    return labels, value, ts


def parse_exposition(text: str) -> Dict[str, Dict[str, Any]]:
    """Strictly parse Prometheus text exposition (0.0.4 and the
    OpenMetrics text extensions: bucket exemplars, ``# EOF``). Returns
    ``{family: {"help", "type", "samples": [(name, labels, value)],
    "exemplars": [(name, labels, ex_labels, ex_value, ex_ts)]}}``.

    Raises ValueError on: samples before their family's TYPE line,
    malformed label quoting/escapes, non-float values, histogram
    bucket counts that are not monotonically non-decreasing in
    ``le``-order, or ``+Inf`` != ``_count``. This is the validator
    the endpoint tests run every scrape surface through, and the
    collector's ingest front end.
    """
    families: Dict[str, Dict[str, Any]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            families.setdefault(name, {"help": None, "type": None,
                                       "samples": [], "exemplars": []})
            families[name]["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, mtype = rest.partition(" ")
            if mtype not in ("counter", "gauge", "histogram", "summary",
                            "untyped"):
                raise ValueError(f"line {lineno}: unknown type {mtype!r}")
            families.setdefault(name, {"help": None, "type": None,
                                       "samples": [], "exemplars": []})
            families[name]["type"] = mtype
            continue
        if line.startswith("#"):
            continue  # comment (includes the OpenMetrics "# EOF")

        def try_sample(candidate: str):
            # Sample line: name[{labels}] value. Returns the parsed
            # triple, or an error string when the candidate doesn't
            # parse as one (kept so the final diagnostic can name the
            # real problem, e.g. "bad value").
            m = re.match(
                r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)$",
                candidate)
            if not m:
                return None, f"malformed sample {candidate!r}"
            name, label_blob, value_text = m.groups()
            try:
                labels = (_parse_labels(label_blob[1:-1])
                          if label_blob else {})
            except ValueError as e:
                return None, str(e)
            try:
                value = float(value_text.replace("+Inf", "inf")
                              .replace("-Inf", "-inf"))
            except ValueError:
                return None, f"bad value {value_text!r}"
            return (name, labels, value), None

        # OpenMetrics exemplar clause rides after " # " on a sample
        # line — but a LABEL VALUE may legally contain " # " too, so
        # try the whole line as a plain sample first, then each split
        # point left to right (the first left side that parses as a
        # sample wins; anything right of it is the exemplar).
        exemplar_blob = None
        parsed, error = try_sample(line)
        if parsed is None:
            idx = line.find(" # ")
            while idx != -1 and parsed is None:
                parsed, _ = try_sample(line[:idx])
                if parsed is not None:
                    exemplar_blob = line[idx + 3:]
                idx = line.find(" # ", idx + 1)
        if parsed is None:
            raise ValueError(f"line {lineno}: {error}")
        sample_name, labels, value = parsed
        family = sample_name
        for suffix in ("_bucket", "_sum", "_count"):
            base = sample_name[:-len(suffix)] \
                if sample_name.endswith(suffix) else None
            if base and base in families:
                family = base
                break
        if family not in families or families[family]["type"] is None:
            raise ValueError(
                f"line {lineno}: sample {sample_name} precedes its "
                f"# TYPE line")
        families[family]["samples"].append((sample_name, labels, value))
        if exemplar_blob is not None:
            ex_labels, ex_value, ex_ts = _parse_exemplar(
                exemplar_blob, lineno)
            families[family]["exemplars"].append(
                (sample_name, labels, ex_labels, ex_value, ex_ts))
    _validate_histograms(families)
    return families


def _validate_histograms(families: Dict[str, Dict[str, Any]]) -> None:
    for name, fam in families.items():
        if fam["type"] != "histogram":
            continue
        # Group buckets by their non-le label set.
        series: Dict[Tuple, List[Tuple[float, float]]] = {}
        counts: Dict[Tuple, float] = {}
        for sample_name, labels, value in fam["samples"]:
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            if sample_name == f"{name}_bucket":
                le = labels.get("le")
                if le is None:
                    raise ValueError(f"{name}_bucket sample without le")
                bound = float("inf") if le == "+Inf" else float(le)
                series.setdefault(key, []).append((bound, value))
            elif sample_name == f"{name}_count":
                counts[key] = value
        for key, buckets in series.items():
            buckets.sort()
            last = -1.0
            for bound, value in buckets:
                if value < last:
                    raise ValueError(
                        f"{name}: bucket counts not cumulative at "
                        f"le={bound}")
                last = value
            if buckets[-1][0] != float("inf"):
                raise ValueError(f"{name}: missing le=+Inf bucket")
            if key in counts and buckets[-1][1] != counts[key]:
                raise ValueError(
                    f"{name}: +Inf bucket {buckets[-1][1]} != _count "
                    f"{counts[key]}")


def dump_jsonl(path: str, registry: Optional[Registry] = None) -> None:
    """Write every sample as one JSON object per line (the CI artifact
    shape — citests/artifacts.py copies these next to the junit XML)."""
    reg = registry or REGISTRY
    with open(path, "w") as f:
        for metric in reg.collect():
            for line in metric._samples():
                m = re.match(
                    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)$",
                    line)
                if not m:
                    continue
                name, label_blob, value = m.groups()
                f.write(json.dumps({
                    "name": name,
                    "labels": (_parse_labels(label_blob[1:-1])
                               if label_blob else {}),
                    "value": float(value.replace("+Inf", "inf")
                                   .replace("-Inf", "-inf")),
                    "type": metric.type,
                }) + "\n")
