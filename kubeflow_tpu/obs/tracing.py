# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Request tracing: propagated context + in-process span ring buffer.

One request, one ``request_id``: minted at the edge (the HTTP proxy —
or accepted from the client when it already carries one), carried over
REST as ``X-Request-Id`` + W3C ``traceparent`` headers and over gRPC
as binary-safe ASCII metadata, and attached to every span the request
produces on its way through proxy → server → manager → XLA dispatch.
That is what turns "p99 regressed" into "THIS request waited 412 ms in
the queue behind THAT batch" — the host-side half of the host+device
profiling story ("Exploring the limits of Concurrency in ML Training
on Google TPUs", PAPERS.md; the device half is the XPlane traces in
docs/profiling.md).

Spans land in a bounded ring buffer (:class:`Tracer`) — oldest spans
fall off, memory is O(capacity), and recording is an O(1) deque append
under one lock, cheap enough to leave on (bench.py --obs-overhead).
The export shape is Chrome trace-event JSON, so ``/tracez`` (serving,
dashboard) opens directly in Perfetto (ui.perfetto.dev) or
``chrome://tracing`` with zero conversion — recipe in
docs/observability.md.

Span linkage contract: request-scoped spans (``queue_wait``,
``batch_assembly``, ``execute``) carry ``args.request_id`` /
``args.trace_id`` and — once coalesced — ``args.batch``; the one
``batch_execute`` span per XLA dispatch carries the same ``args.batch``
id, which is how N request timelines join the single device dispatch
they shared.

Fleet assembly contract (ISSUE 15): every hop's root span ALSO
carries ``args.span_id`` (its own id) and ``args.parent_id`` (the
caller's span id, parsed off the inbound ``traceparent``), and spans
recorded under a context (:func:`span_args`) carry
``parent_id = ctx.span_id`` — so the collector's
:class:`~kubeflow_tpu.obs.collector.SpanStore` can reassemble ONE
request's full proxy → server → engine tree even when the spans were
scraped from N processes whose monotonic clocks never met. Multi-leg
requests (role-split hops, hedge twins, mid-stream resume replays)
share the trace id with distinct leg-tagged span ids: the proxy mints
a :meth:`TraceContext.child` per upstream hop with a ``leg`` tag
(``prefill`` / ``decode`` / ``primary`` / ``hedge`` / ``resume-N``)
that rides the ``X-KFT-Trace-Leg`` header, so a stitched stream still
yields one waterfall.
"""

from __future__ import annotations

import itertools
import json
import os
import random
import threading
import time
from collections import deque
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    List,
    Optional,
    Tuple,
)

__all__ = [
    "REQUEST_ID_HEADER",
    "TRACEPARENT_HEADER",
    "TRACE_LEG_HEADER",
    "TRACER",
    "TraceContext",
    "Tracer",
    "current_context",
    "current_trace_id",
    "ensure_context",
    "filter_spans",
    "from_grpc_metadata",
    "from_headers",
    "new_context",
    "parse_traceparent",
    "root_span_args",
    "span_args",
    "use_context",
]

REQUEST_ID_HEADER = "X-Request-Id"
TRACEPARENT_HEADER = "traceparent"
#: Leg tag of a multi-leg request (role-split hop, hedge twin, resume
#: replay): same trace id, distinct leg — the assembly layer shows one
#: waterfall with the legs side by side instead of N anonymous trees.
TRACE_LEG_HEADER = "X-KFT-Trace-Leg"

_HEX = "0123456789abcdef"

# Id generation is on the per-request hot path: uuid.uuid4() costs an
# os.urandom syscall per call (~45µs on an old kernel — measured
# 135µs per context, most of the obs overhead budget). Trace ids need
# collision resistance, not cryptographic strength: a Mersenne
# twister seeded once from urandom gives ~2µs ids. getrandbits is a
# single C call, so it's GIL-atomic across request threads.
_rng = random.Random(int.from_bytes(os.urandom(16), "big"))


def _hex128() -> str:
    return f"{_rng.getrandbits(128):032x}"


def _hex64() -> str:
    return f"{_rng.getrandbits(64):016x}"


def _is_hex(s: str, length: int) -> bool:
    return len(s) == length and all(c in _HEX for c in s.lower())


class TraceContext:
    """Immutable-ish propagation context: W3C trace/span ids plus the
    human-greppable request id (the access-log join key).
    ``parent_span_id`` is the CALLER's span id (parsed off the inbound
    ``traceparent``) — the edge that lets the collector rebuild the
    cross-process tree; ``leg`` names which leg of a multi-leg request
    this context rides (empty for single-leg requests)."""

    __slots__ = ("trace_id", "span_id", "request_id",
                 "parent_span_id", "leg")

    def __init__(self, trace_id: str, span_id: str, request_id: str,
                 parent_span_id: Optional[str] = None, leg: str = ""):
        self.trace_id = trace_id
        self.span_id = span_id
        self.request_id = request_id
        self.parent_span_id = parent_span_id
        self.leg = leg

    def child(self, leg: Optional[str] = None) -> "TraceContext":
        """Same trace/request, fresh span id parented on THIS context
        — what each hop sends downstream so parentage is
        reconstructible. ``leg`` tags the downstream hop (role-split
        hop, hedge twin, resume replay); None inherits."""
        return TraceContext(self.trace_id, _hex64(), self.request_id,
                            parent_span_id=self.span_id,
                            leg=self.leg if leg is None else leg)

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def headers(self) -> Dict[str, str]:
        out = {REQUEST_ID_HEADER: self.request_id,
               TRACEPARENT_HEADER: self.traceparent()}
        if self.leg:
            out[TRACE_LEG_HEADER] = self.leg
        return out

    def grpc_metadata(self) -> Tuple[Tuple[str, str], ...]:
        """gRPC metadata keys must be lowercase ASCII."""
        out = (("x-request-id", self.request_id),
               ("traceparent", self.traceparent()))
        if self.leg:
            out += (("x-kft-trace-leg", self.leg),)
        return out

    def __repr__(self) -> str:
        return (f"TraceContext(request_id={self.request_id!r}, "
                f"trace_id={self.trace_id!r})")


def new_context(request_id: Optional[str] = None) -> TraceContext:
    trace_id = _hex128()
    return TraceContext(trace_id, _hex64(),
                        request_id or trace_id[:16])


def span_args(ctx: Optional[TraceContext],
              **extra: Any) -> Dict[str, Any]:
    """The span-linkage args every context-tagged span carries:
    request/trace ids for the grep workflow, ``parent_id`` (= the
    context's own span id) for tree assembly, and the leg tag when the
    request is multi-leg. ``extra`` keys ride along verbatim; a None
    context yields just them (the span is then a documented root —
    scripts/lint.py check_span_discipline enforces the distinction)."""
    args: Dict[str, Any] = dict(extra)
    if ctx is not None:
        args.setdefault("request_id", ctx.request_id)
        args["trace_id"] = ctx.trace_id
        args["parent_id"] = ctx.span_id
        if ctx.leg:
            args.setdefault("leg", ctx.leg)
    return args


def root_span_args(ctx: Optional[TraceContext],
                   **extra: Any) -> Dict[str, Any]:
    """The HOP-ROOT flavor of :func:`span_args`: this span OWNS the
    context's span id (children recorded under the same context
    parent on it) and parents on the inbound caller's span id — the
    cross-process edge of the assembled tree. One helper, used by
    every hop root (HTTP mixin, native gRPC listener, the proxy's
    upstream windows), so a linkage change lands everywhere at
    once."""
    args = span_args(ctx, **extra)
    if ctx is not None:
        args["span_id"] = ctx.span_id
        if ctx.parent_span_id:
            args["parent_id"] = ctx.parent_span_id
        else:
            args.pop("parent_id", None)
    return args


def parse_traceparent(value: str) -> Optional[Tuple[str, str]]:
    """``00-<32 hex>-<16 hex>-<2 hex>`` → (trace_id, span_id), or None
    on anything malformed (a bad header must never 500 a request)."""
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if not (_is_hex(version, 2) and _is_hex(trace_id, 32)
            and _is_hex(span_id, 16) and _is_hex(flags, 2)):
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id.lower(), span_id.lower()


def from_headers(headers) -> Optional[TraceContext]:
    """Context from an HTTP request's headers (any Mapping-with-get,
    e.g. tornado's HTTPHeaders), or None when the request carries
    neither header. Client-supplied ids are capped at 128 chars: the
    id is echoed in response headers, copied into every span's args
    (ring-buffer memory is O(capacity × id size)) and written to each
    access-log line — an unbounded header must not ride that far."""
    request_id = headers.get(REQUEST_ID_HEADER)
    if request_id:
        request_id = str(request_id)[:128]
    leg = headers.get(TRACE_LEG_HEADER)
    leg = str(leg)[:32] if leg else ""
    parent = headers.get(TRACEPARENT_HEADER)
    parsed = parse_traceparent(parent) if parent else None
    if parsed:
        # The inbound traceparent's span id is the CALLER's span — it
        # becomes this hop's parent, and this hop mints its own span
        # id, so the assembled tree has one node per hop instead of N
        # hops claiming one id.
        trace_id, parent_span_id = parsed
        return TraceContext(trace_id, _hex64(),
                            request_id or trace_id[:16],
                            parent_span_id=parent_span_id, leg=leg)
    if request_id:
        ctx = new_context(request_id=request_id)
        ctx.leg = leg
        return ctx
    return None


def ensure_context(headers) -> TraceContext:
    """The edge rule (proxy): adopt the caller's context when present,
    mint a fresh one otherwise — every request downstream of here HAS
    an id."""
    return from_headers(headers) or new_context()


# Per-thread active context — the exemplar hook: a Histogram deep in
# a library can stamp "the current request's trace id" onto the bucket
# it observes without the id being threaded through every call
# signature. Explicit obs_ctx plumbing (manager, engine) stays the
# primary path; this is the fallback for code that has no ctx param.
_ACTIVE = threading.local()


class _UseCtx:
    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx

    def __enter__(self):
        self._prev = getattr(_ACTIVE, "ctx", None)
        _ACTIVE.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _ACTIVE.ctx = self._prev
        return False


def use_context(ctx: Optional[TraceContext]) -> _UseCtx:
    """Make ``ctx`` the thread's current context for the block."""
    return _UseCtx(ctx)


def current_context() -> Optional[TraceContext]:
    return getattr(_ACTIVE, "ctx", None)


def current_trace_id() -> Optional[str]:
    ctx = getattr(_ACTIVE, "ctx", None)
    return ctx.trace_id if ctx is not None else None


def from_grpc_metadata(metadata: Optional[Iterable]
                       ) -> Optional[TraceContext]:
    """Context from gRPC invocation metadata: an iterable of (key,
    value) pairs (grpcio's context.invocation_metadata())."""
    if metadata is None:
        return None
    found = {}
    for item in metadata:
        key, value = item[0], item[1]
        if key.lower() in ("x-request-id", "traceparent",
                           "x-kft-trace-leg"):
            found[key.lower()] = value
    if "x-request-id" not in found and "traceparent" not in found:
        return None

    class _MD:
        def get(self, name, default=None):
            return found.get(name.lower(), default)

    return from_headers(_MD())


#: Span outcomes ALWAYS retained under tail sampling: errors and the
#: deadline/overload family — exactly the spans an SLO alert sends an
#: operator looking for.
RETAIN_OUTCOMES = frozenset({"error", "expired", "deadline_exceeded",
                             "shed"})


class Tracer:
    """Bounded in-process span recorder.

    ``record()`` appends one finished span (a plain dict, Chrome
    trace-event "X" shape) to a deque with maxlen — O(1), no
    allocation churn beyond the dict itself, oldest spans evicted.
    ``enabled=False`` makes record() a no-op (one attribute read);
    the obs-overhead bench flips exactly this switch.

    **Tail sampling** (:meth:`set_tail_sampling`): at fleet load the
    happy path produces thousands of identical spans per second and
    the ring holds seconds of history — the one slow request an
    exemplar points at is long evicted. With tail sampling on, spans
    are kept by what they turned out to be (hence *tail*-based):
    error/deadline/shed outcomes and the slowest decile per span name
    always land in a separate retained buffer; happy-path spans are
    kept with probability ``keep_prob``. ``/tracez`` stays bounded
    (both buffers have maxlen) but the interesting traces survive
    minutes, not milliseconds.
    """

    def __init__(self, capacity: int = 4096, component: str = ""):
        self.enabled = True
        self.component = component or os.environ.get(
            "KFT_OBS_COMPONENT", "")
        self._pid = os.getpid()
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=int(capacity))
        self._batch_ids = itertools.count(1)
        # Tail-sampling state (None = off, the default: record() then
        # costs exactly what it did before the feature existed).
        self._tail_keep_prob: Optional[float] = None
        self._retained: deque = deque(maxlen=int(capacity))
        self._slow_quantile = 0.9
        self._durations: Dict[str, deque] = {}
        self._dur_seen: Dict[str, int] = {}
        self._slow_thr: Dict[str, float] = {}
        # Span-shipping export queue (None = off, the default): every
        # stored span is ALSO appended here for a SpanShipper to drain
        # and push to the fleet collector. Bounded (oldest dropped,
        # counted) so a dead collector can never grow this process.
        self._export: Optional[deque] = None
        self._export_dropped = 0
        #: Called (outside the lock) when the export queue crosses
        #: half capacity — the shipper's wake-early hook, so buffer
        #: pressure ships spans before the ring evicts them.
        self.on_export_pressure: Optional[Callable[[], None]] = None

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self._spans = deque(self._spans, maxlen=int(capacity))

    def set_tail_sampling(self, keep_prob: Optional[float], *,
                          retained_capacity: Optional[int] = None,
                          slow_quantile: float = 0.9) -> None:
        """Enable tail-based retention (``keep_prob`` = probability a
        happy-path span is kept; errors and the slowest
        ``1-slow_quantile`` fraction per span name are always kept in
        a separate bounded buffer). ``None`` turns it off."""
        if keep_prob is not None and not (0.0 <= keep_prob <= 1.0):
            raise ValueError("keep_prob must be in [0, 1]")
        if not (0.0 < slow_quantile < 1.0):
            raise ValueError("slow_quantile must be in (0, 1)")
        with self._lock:
            self._tail_keep_prob = keep_prob
            self._slow_quantile = slow_quantile
            if retained_capacity is not None:
                self._retained = deque(self._retained,
                                       maxlen=int(retained_capacity))
            if keep_prob is None:
                self._durations.clear()
                self._slow_thr.clear()

    def next_batch_id(self) -> str:
        return f"batch-{self._pid}-{next(self._batch_ids)}"

    # -- span shipping (export queue) ------------------------------------

    def enable_export(self, capacity: int = 2048) -> None:
        """Turn on the export queue: every span record() stores is
        also queued for a shipper to drain (collector push path).
        Bounded — a stalled shipper costs dropped exports, never
        memory."""
        with self._lock:
            self._export = deque(self._export or (),
                                 maxlen=int(capacity))

    def disable_export(self) -> None:
        with self._lock:
            self._export = None
            self._export_dropped = 0

    def drain_export(self) -> List[Dict[str, Any]]:
        """Pop everything queued for shipping (the SpanShipper's
        cycle body). Empty list when export is off."""
        with self._lock:
            if not self._export:
                return []
            out = list(self._export)
            self._export.clear()
        return out

    def export_stats(self) -> Dict[str, int]:
        with self._lock:
            return {"queued": len(self._export or ()),
                    "dropped": self._export_dropped}

    def _export_locked(self, event: Dict[str, Any]) -> bool:
        """Queue one stored span for shipping; True when the queue
        crossed half capacity (caller fires the pressure hook outside
        the lock)."""
        q = self._export
        if q is None:
            return False
        if len(q) == q.maxlen:
            self._export_dropped += 1
        q.append(event)
        return len(q) * 2 >= (q.maxlen or 1)

    def _classify_locked(self, name: str, dur_s: float,
                         args: Optional[Dict[str, Any]]) -> Optional[str]:
        """Tail-sampling verdict: "error" / "slow" (→ retained
        buffer), None (→ ring, subject to keep_prob). Caller holds
        the lock. The slow threshold is the per-name duration decile
        over a sliding window of recent spans, recomputed every 32
        observations (sorting 128 floats amortized — not per span)."""
        outcome = (args or {}).get("outcome")
        if outcome in RETAIN_OUTCOMES:
            return "error"
        window = self._durations.get(name)
        if window is None:
            window = deque(maxlen=128)
            self._durations[name] = window
        window.append(dur_s)
        # Recompute the decile every 32 observations (a lifetime
        # counter, NOT len(window) — once the window is full its
        # length pins at maxlen and a len-based trigger would sort on
        # every record).
        seen = self._dur_seen.get(name, 0) + 1
        self._dur_seen[name] = seen
        if seen >= 16 and seen % 32 == 0:
            ranked = sorted(window)
            self._slow_thr[name] = ranked[
                min(len(ranked) - 1,
                    int(self._slow_quantile * len(ranked)))]
        thr = self._slow_thr.get(name)
        # Strictly above the decile: a workload whose durations are
        # all identical has no tail, and >= would retain every span.
        if thr is not None and dur_s > thr:
            return "slow"
        return None

    def record(self, name: str, cat: str, start_s: float, dur_s: float,
               args: Optional[Dict[str, Any]] = None,
               tid: Optional[int] = None) -> None:
        """Record one completed span. ``start_s`` is a
        ``time.monotonic()`` timestamp; durations in seconds. Hot
        path: one dict + one locked deque append, no formatting —
        rounding/pretty-printing happens at export time."""
        if not self.enabled:
            return
        event = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": start_s * 1e6,               # µs, Chrome contract
            "dur": dur_s * 1e6 if dur_s > 0.0 else 0.0,
            "pid": self._pid,
            "tid": (tid if tid is not None
                    else threading.get_ident() & 0x7FFFFFFF),
        }
        if args:
            event["args"] = args
        pressure = False
        with self._lock:
            if self._tail_keep_prob is None:
                self._spans.append(event)
                pressure = self._export_locked(event)
            else:
                verdict = self._classify_locked(name, dur_s, args)
                if verdict is not None:
                    args = dict(args or ())
                    args["retain"] = verdict
                    event["args"] = args
                    self._retained.append(event)
                    pressure = self._export_locked(event)
                elif (self._tail_keep_prob >= 1.0
                      or _rng.random() < self._tail_keep_prob):
                    self._spans.append(event)
                    pressure = self._export_locked(event)
        if pressure:
            cb = self.on_export_pressure
            if cb is not None:
                try:
                    cb()
                except Exception:  # noqa: BLE001 — a shipper hook bug
                    pass  # must never fail the recording hot path

    class _SpanCtx:
        __slots__ = ("_tracer", "_name", "_cat", "_args", "_t0")

        def __init__(self, tracer, name, cat, args):
            self._tracer = tracer
            self._name = name
            self._cat = cat
            self._args = args

        def __enter__(self):
            self._t0 = time.monotonic()
            return self

        def __exit__(self, exc_type, exc, tb):
            if exc_type is not None:
                args = dict(self._args or ())
                args["outcome"] = "error"
                self._args = args
            self._tracer.record(self._name, self._cat, self._t0,
                                time.monotonic() - self._t0, self._args)
            return False

    def span(self, name: str, cat: str = "app",
             args: Optional[Dict[str, Any]] = None) -> "Tracer._SpanCtx":
        """Context manager recording one span around a block."""
        return Tracer._SpanCtx(self, name, cat, args)

    def snapshot(self) -> List[Dict[str, Any]]:
        """All live spans (ring + tail-retained), timestamp-ordered —
        one merged timeline whichever buffer a span survived in."""
        with self._lock:
            if not self._retained:
                return list(self._spans)
            spans = list(self._spans) + list(self._retained)
        spans.sort(key=lambda s: s.get("ts", 0.0))
        return spans

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._retained.clear()
            self._durations.clear()
            self._dur_seen.clear()
            self._slow_thr.clear()
            if self._export is not None:
                self._export.clear()

    def export_chrome(self, spans: Optional[List[Dict[str, Any]]] = None
                      ) -> Dict[str, Any]:
        """The Perfetto-openable document: trace events plus a process
        metadata record naming the component. ``spans`` overrides the
        live snapshot (the /tracez handlers pass a filtered list)."""
        events: List[Dict[str, Any]] = []
        if self.component:
            events.append({"name": "process_name", "ph": "M",
                           "pid": os.getpid(),
                           "args": {"name": self.component}})
        events.extend(self.snapshot() if spans is None else spans)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump_jsonl(self, path: str) -> None:
        """One span per line (the CI artifact shape —
        citests/artifacts.py copies these next to the junit XML)."""
        with open(path, "w") as f:
            for span in self.snapshot():
                f.write(json.dumps(span) + "\n")


def filter_spans(spans: Iterable[Dict[str, Any]], *,
                 trace_id: Optional[str] = None,
                 status: Optional[str] = None,
                 min_duration_ms: Optional[float] = None,
                 limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """The ``/tracez`` query filters, shared by the tornado and stdlib
    exposition handlers: a full 4096-span ring serialized per request
    is megabytes of JSON nobody reads — these narrow it to the trace,
    status or latency band the caller is hunting.

    - ``trace_id`` — spans whose args carry this trace (or request) id
      (the exemplar workflow: histogram bucket → exemplar trace id →
      ``/tracez?trace_id=``).
    - ``status`` — ``error`` matches every non-ok outcome (the
      :data:`RETAIN_OUTCOMES` family); any other value matches that
      outcome exactly.
    - ``min_duration_ms`` — spans at least this long.
    - ``limit`` — keep only the NEWEST n after the other filters.
    """
    out = []
    for span in spans:
        args = span.get("args") or {}
        if trace_id is not None:
            if trace_id not in (args.get("trace_id"),
                                args.get("request_id")):
                continue
        if status is not None:
            outcome = args.get("outcome")
            if status == "error":
                if outcome not in RETAIN_OUTCOMES:
                    continue
            elif outcome != status:
                continue
        if min_duration_ms is not None:
            if span.get("dur", 0.0) < min_duration_ms * 1e3:
                continue
        out.append(span)
    if limit is not None and len(out) > max(0, limit):
        # limit=0 must mean "none": out[-0:] would slice the WHOLE
        # list — the exact unbounded dump the filter exists to stop.
        out = out[-limit:] if limit > 0 else []
    return out


#: The process-wide tracer every module records against.
TRACER = Tracer()
