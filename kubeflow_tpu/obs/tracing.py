# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Request tracing: propagated context + in-process span ring buffer.

One request, one ``request_id``: minted at the edge (the HTTP proxy —
or accepted from the client when it already carries one), carried over
REST as ``X-Request-Id`` + W3C ``traceparent`` headers and over gRPC
as binary-safe ASCII metadata, and attached to every span the request
produces on its way through proxy → server → manager → XLA dispatch.
That is what turns "p99 regressed" into "THIS request waited 412 ms in
the queue behind THAT batch" — the host-side half of the host+device
profiling story ("Exploring the limits of Concurrency in ML Training
on Google TPUs", PAPERS.md; the device half is the XPlane traces in
docs/profiling.md).

Spans land in a bounded ring buffer (:class:`Tracer`) — oldest spans
fall off, memory is O(capacity), and recording is an O(1) deque append
under one lock, cheap enough to leave on (bench.py --obs-overhead).
The export shape is Chrome trace-event JSON, so ``/tracez`` (serving,
dashboard) opens directly in Perfetto (ui.perfetto.dev) or
``chrome://tracing`` with zero conversion — recipe in
docs/observability.md.

Span linkage contract: request-scoped spans (``queue_wait``,
``batch_assembly``, ``execute``) carry ``args.request_id`` /
``args.trace_id`` and — once coalesced — ``args.batch``; the one
``batch_execute`` span per XLA dispatch carries the same ``args.batch``
id, which is how N request timelines join the single device dispatch
they shared.
"""

from __future__ import annotations

import itertools
import json
import os
import random
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "REQUEST_ID_HEADER",
    "TRACEPARENT_HEADER",
    "TRACER",
    "TraceContext",
    "Tracer",
    "ensure_context",
    "from_grpc_metadata",
    "from_headers",
    "new_context",
    "parse_traceparent",
]

REQUEST_ID_HEADER = "X-Request-Id"
TRACEPARENT_HEADER = "traceparent"

_HEX = "0123456789abcdef"

# Id generation is on the per-request hot path: uuid.uuid4() costs an
# os.urandom syscall per call (~45µs on an old kernel — measured
# 135µs per context, most of the obs overhead budget). Trace ids need
# collision resistance, not cryptographic strength: a Mersenne
# twister seeded once from urandom gives ~2µs ids. getrandbits is a
# single C call, so it's GIL-atomic across request threads.
_rng = random.Random(int.from_bytes(os.urandom(16), "big"))


def _hex128() -> str:
    return f"{_rng.getrandbits(128):032x}"


def _hex64() -> str:
    return f"{_rng.getrandbits(64):016x}"


def _is_hex(s: str, length: int) -> bool:
    return len(s) == length and all(c in _HEX for c in s.lower())


class TraceContext:
    """Immutable-ish propagation context: W3C trace/span ids plus the
    human-greppable request id (the access-log join key)."""

    __slots__ = ("trace_id", "span_id", "request_id")

    def __init__(self, trace_id: str, span_id: str, request_id: str):
        self.trace_id = trace_id
        self.span_id = span_id
        self.request_id = request_id

    def child(self) -> "TraceContext":
        """Same trace/request, fresh span id — what each hop sends
        downstream so parentage is reconstructible."""
        return TraceContext(self.trace_id, _hex64(), self.request_id)

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def headers(self) -> Dict[str, str]:
        return {REQUEST_ID_HEADER: self.request_id,
                TRACEPARENT_HEADER: self.traceparent()}

    def grpc_metadata(self) -> Tuple[Tuple[str, str], ...]:
        """gRPC metadata keys must be lowercase ASCII."""
        return (("x-request-id", self.request_id),
                ("traceparent", self.traceparent()))

    def __repr__(self) -> str:
        return (f"TraceContext(request_id={self.request_id!r}, "
                f"trace_id={self.trace_id!r})")


def new_context(request_id: Optional[str] = None) -> TraceContext:
    trace_id = _hex128()
    return TraceContext(trace_id, _hex64(),
                        request_id or trace_id[:16])


def parse_traceparent(value: str) -> Optional[Tuple[str, str]]:
    """``00-<32 hex>-<16 hex>-<2 hex>`` → (trace_id, span_id), or None
    on anything malformed (a bad header must never 500 a request)."""
    parts = value.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if not (_is_hex(version, 2) and _is_hex(trace_id, 32)
            and _is_hex(span_id, 16) and _is_hex(flags, 2)):
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id.lower(), span_id.lower()


def from_headers(headers) -> Optional[TraceContext]:
    """Context from an HTTP request's headers (any Mapping-with-get,
    e.g. tornado's HTTPHeaders), or None when the request carries
    neither header. Client-supplied ids are capped at 128 chars: the
    id is echoed in response headers, copied into every span's args
    (ring-buffer memory is O(capacity × id size)) and written to each
    access-log line — an unbounded header must not ride that far."""
    request_id = headers.get(REQUEST_ID_HEADER)
    if request_id:
        request_id = str(request_id)[:128]
    parent = headers.get(TRACEPARENT_HEADER)
    parsed = parse_traceparent(parent) if parent else None
    if parsed:
        trace_id, span_id = parsed
        return TraceContext(trace_id, span_id,
                            request_id or trace_id[:16])
    if request_id:
        return new_context(request_id=request_id)
    return None


def ensure_context(headers) -> TraceContext:
    """The edge rule (proxy): adopt the caller's context when present,
    mint a fresh one otherwise — every request downstream of here HAS
    an id."""
    return from_headers(headers) or new_context()


def from_grpc_metadata(metadata: Optional[Iterable]
                       ) -> Optional[TraceContext]:
    """Context from gRPC invocation metadata: an iterable of (key,
    value) pairs (grpcio's context.invocation_metadata())."""
    if metadata is None:
        return None
    found = {}
    for item in metadata:
        key, value = item[0], item[1]
        if key.lower() in ("x-request-id", "traceparent"):
            found[key.lower()] = value
    if not found:
        return None

    class _MD:
        def get(self, name, default=None):
            return found.get(name.lower(), default)

    return from_headers(_MD())


class Tracer:
    """Bounded in-process span recorder.

    ``record()`` appends one finished span (a plain dict, Chrome
    trace-event "X" shape) to a deque with maxlen — O(1), no
    allocation churn beyond the dict itself, oldest spans evicted.
    ``enabled=False`` makes record() a no-op (one attribute read);
    the obs-overhead bench flips exactly this switch.
    """

    def __init__(self, capacity: int = 4096, component: str = ""):
        self.enabled = True
        self.component = component or os.environ.get(
            "KFT_OBS_COMPONENT", "")
        self._pid = os.getpid()
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=int(capacity))
        self._batch_ids = itertools.count(1)

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self._spans = deque(self._spans, maxlen=int(capacity))

    def next_batch_id(self) -> str:
        return f"batch-{self._pid}-{next(self._batch_ids)}"

    def record(self, name: str, cat: str, start_s: float, dur_s: float,
               args: Optional[Dict[str, Any]] = None,
               tid: Optional[int] = None) -> None:
        """Record one completed span. ``start_s`` is a
        ``time.monotonic()`` timestamp; durations in seconds. Hot
        path: one dict + one locked deque append, no formatting —
        rounding/pretty-printing happens at export time."""
        if not self.enabled:
            return
        event = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": start_s * 1e6,               # µs, Chrome contract
            "dur": dur_s * 1e6 if dur_s > 0.0 else 0.0,
            "pid": self._pid,
            "tid": (tid if tid is not None
                    else threading.get_ident() & 0x7FFFFFFF),
        }
        if args:
            event["args"] = args
        with self._lock:
            self._spans.append(event)

    class _SpanCtx:
        __slots__ = ("_tracer", "_name", "_cat", "_args", "_t0")

        def __init__(self, tracer, name, cat, args):
            self._tracer = tracer
            self._name = name
            self._cat = cat
            self._args = args

        def __enter__(self):
            self._t0 = time.monotonic()
            return self

        def __exit__(self, exc_type, exc, tb):
            if exc_type is not None:
                args = dict(self._args or ())
                args["outcome"] = "error"
                self._args = args
            self._tracer.record(self._name, self._cat, self._t0,
                                time.monotonic() - self._t0, self._args)
            return False

    def span(self, name: str, cat: str = "app",
             args: Optional[Dict[str, Any]] = None) -> "Tracer._SpanCtx":
        """Context manager recording one span around a block."""
        return Tracer._SpanCtx(self, name, cat, args)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def export_chrome(self) -> Dict[str, Any]:
        """The Perfetto-openable document: trace events plus a process
        metadata record naming the component."""
        events: List[Dict[str, Any]] = []
        if self.component:
            events.append({"name": "process_name", "ph": "M",
                           "pid": os.getpid(),
                           "args": {"name": self.component}})
        events.extend(self.snapshot())
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump_jsonl(self, path: str) -> None:
        """One span per line (the CI artifact shape —
        citests/artifacts.py copies these next to the junit XML)."""
        with open(path, "w") as f:
            for span in self.snapshot():
                f.write(json.dumps(span) + "\n")


#: The process-wide tracer every module records against.
TRACER = Tracer()
