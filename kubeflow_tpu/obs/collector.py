# Copyright 2026 The kubeflow-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Fleet telemetry collector: scrape loop + windowed time-series store.

r9 gave every process a ``/metrics``; at fleet scale (N serving
replicas + router + autoscaler + operator) each endpoint is an island
— nothing aggregates cross-replica rates and nothing can evaluate an
SLO that spans the fleet. This module is the aggregation half of the
telemetry pipeline (obs/slo.py is the alerting half), dependency-free
like the rest of ``obs/``:

- :class:`TimeSeriesStore` — a windowed in-memory store: per series a
  ring of ``(monotonic_ts, value)`` samples, counter-reset-aware
  ``rate()`` (one shared :func:`metrics.counter_increase` with the
  autoscaler's shed differencing), histogram-quantile estimation from
  ``_bucket`` rates, and cross-replica sum/avg/max aggregation. A
  STRICT series-cardinality cap bounds memory: past the cap new
  series are counted and dropped, never stored — one label-churning
  replica cannot OOM the collector.
- :class:`Collector` — the scrape loop: targets come from the scaling
  control plane's endpoints file / pool (the serving fleet) plus
  static targets (operator, proxy, dashboard); each cycle fetches
  every target's ``/metrics`` concurrently (bounded per-scrape
  timeout, OpenMetrics ``Accept`` so exemplars ride along), runs the
  strict :func:`metrics.parse_exposition`, and ingests every sample
  with ``instance``/``job`` labels stamped on. Runs as a thread in
  the dashboard or as a sidecar (``python -m
  kubeflow_tpu.obs.collector``).

Wait discipline: the loop is Event-paced (bounded, interruptible) and
all control timing is monotonic; every fetch carries an explicit
timeout (scripts/lint.py check_serving_timeout_discipline covers this
file).
"""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import logging
import threading
import time
import urllib.request
import weakref
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
)

from kubeflow_tpu.obs import metrics as obs_metrics

logger = logging.getLogger(__name__)

__all__ = [
    "Collector",
    "ScrapeTarget",
    "SpanShipper",
    "SpanStore",
    "TimeSeriesStore",
    "fleet_replica_rows",
    "live_collectors",
    "parse_static_targets",
    "quantile_from_buckets",
    "scrape_metrics",
    "scrape_spans",
]

_C_SCRAPES = obs_metrics.Counter(
    "kft_collector_scrapes_total",
    "Collector scrape attempts by target and outcome",
    ("instance", "outcome"))
_H_SCRAPE = obs_metrics.Histogram(
    "kft_collector_scrape_seconds",
    "Wall time of one target scrape (fetch + parse + ingest)")
_G_SERIES = obs_metrics.Gauge(
    "kft_collector_series",
    "Time series currently held by the collector store")
_C_DROPPED = obs_metrics.Counter(
    "kft_collector_dropped_series_total",
    "New series rejected by the cardinality cap")
_C_SPANS = obs_metrics.Counter(
    "kft_collector_spans_total",
    "Spans accepted into the trace store, by ingest path "
    "(scrape | push)", ("path",))
_C_SPANS_DROPPED = obs_metrics.Counter(
    "kft_collector_dropped_spans_total",
    "Spans rejected by the trace store's caps")

#: Every live Collector in this process (weak — a stopped/forgotten
#: collector leaves no trace). citests/artifacts.py collect-obs dumps
#: each one's state next to the junit XML.
_LIVE: "weakref.WeakSet[Collector]" = weakref.WeakSet()


def live_collectors() -> List["Collector"]:
    return list(_LIVE)


_LabelsKey = Tuple[Tuple[str, str], ...]


def _labels_key(labels: Dict[str, str]) -> _LabelsKey:
    return tuple(sorted(labels.items()))


def _matches(labels: Dict[str, str],
             label_filter: Optional[Dict[str, str]]) -> bool:
    if not label_filter:
        return True
    return all(labels.get(k) == v for k, v in label_filter.items())


def quantile_from_buckets(q: float,
                          buckets: Dict[float, float]
                          ) -> Optional[float]:
    """``histogram_quantile``: interpolate the q-quantile from per-le
    bucket RATES (cumulative, +Inf included). Returns None with no
    observations; the highest finite bound when the quantile falls in
    +Inf (Prometheus's convention — the estimate saturates rather
    than invents a value beyond the instrumented range)."""
    if not buckets:
        return None
    bounds = sorted(buckets)
    total = buckets.get(float("inf"))
    if total is None:
        total = buckets[bounds[-1]]
    if total <= 0.0:
        return None
    rank = q * total
    prev_bound, prev_cum = 0.0, 0.0
    for bound in bounds:
        cum = buckets[bound]
        if cum >= rank:
            if bound == float("inf"):
                finite = [b for b in bounds if b != float("inf")]
                return finite[-1] if finite else None
            if cum <= prev_cum:
                return bound
            lower = prev_bound if prev_bound < bound else 0.0
            return lower + (bound - lower) * (rank - prev_cum) \
                / (cum - prev_cum)
        prev_bound, prev_cum = bound, cum
    finite = [b for b in bounds if b != float("inf")]
    return finite[-1] if finite else None


class TimeSeriesStore:
    """Windowed in-memory multi-series store with a hard cardinality
    cap. Timestamps are caller-supplied monotonic seconds (injectable
    in tests; the collector passes ``time.monotonic()``)."""

    def __init__(self, *, max_samples_per_series: int = 1024,
                 max_series: int = 8192):
        self.max_samples_per_series = int(max_samples_per_series)
        self.max_series = int(max_series)
        self._lock = threading.Lock()
        # name → labels_key → deque[(ts, value)]
        self._series: Dict[str, Dict[_LabelsKey, deque]] = {}
        self._kinds: Dict[str, str] = {}
        # (name, labels_key) → (trace_id, value, ts) — latest exemplar
        # per bucket series (bounded by series count, itself capped).
        self._exemplars: Dict[Tuple[str, _LabelsKey],
                              Tuple[str, float, float]] = {}
        self._count = 0
        self._dropped = 0

    # -- ingest ---------------------------------------------------------

    def ingest(self, name: str, labels: Dict[str, str], value: float,
               ts: float, kind: str = "untyped") -> bool:
        """Append one sample; False when the cardinality cap rejected
        a NEW series (existing series always accept)."""
        key = _labels_key(labels)
        with self._lock:
            by_labels = self._series.setdefault(name, {})
            ring = by_labels.get(key)
            if ring is None:
                if self._count >= self.max_series:
                    self._dropped += 1
                    if not by_labels:
                        del self._series[name]
                    return False
                ring = deque(maxlen=self.max_samples_per_series)
                by_labels[key] = ring
                self._count += 1
                self._kinds.setdefault(name, kind)
            ring.append((float(ts), float(value)))
        return True

    def ingest_exposition(self, families: Dict[str, Dict[str, Any]],
                          ts: float,
                          extra_labels: Optional[Dict[str, str]] = None
                          ) -> Tuple[int, int]:
        """Ingest one parsed scrape (``parse_exposition`` output),
        stamping ``extra_labels`` (instance/job) onto every series.
        Returns (ingested, dropped) sample counts."""
        extra = extra_labels or {}
        ingested = dropped = 0
        for fam_name, fam in families.items():
            kind = fam.get("type") or "untyped"
            accepted = set()
            for sample_name, labels, value in fam.get("samples", ()):
                merged = {**labels, **extra}
                key = _labels_key(merged)
                if self.ingest(sample_name, merged, value, ts,
                               kind=kind):
                    ingested += 1
                    accepted.add((sample_name, key))
                else:
                    dropped += 1
            for (sample_name, labels, ex_labels, ex_value,
                 ex_ts) in fam.get("exemplars", ()):
                trace_id = ex_labels.get("trace_id")
                if not trace_id:
                    continue
                key = (sample_name, _labels_key({**labels, **extra}))
                # Only series the cap ADMITTED may carry exemplars —
                # otherwise a label-churning histogram would grow the
                # exemplar map without bound, bypassing the very cap
                # that bounds the store.
                if key not in accepted:
                    continue
                with self._lock:
                    self._exemplars[key] = (trace_id, ex_value, ts)
        return ingested, dropped

    # -- introspection --------------------------------------------------

    def series_count(self) -> int:
        with self._lock:
            return self._count

    def dropped_series(self) -> int:
        with self._lock:
            return self._dropped

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def kind(self, name: str) -> str:
        with self._lock:
            return self._kinds.get(name, "untyped")

    def _snapshot_series(self, name: str
                         ) -> List[Tuple[_LabelsKey, List[Tuple[float,
                                                                float]]]]:
        with self._lock:
            by_labels = self._series.get(name)
            if not by_labels:
                return []
            return [(key, list(ring))
                    for key, ring in by_labels.items()]

    # -- queries --------------------------------------------------------

    def latest(self, name: str,
               label_filter: Optional[Dict[str, str]] = None,
               staleness_s: Optional[float] = None,
               now: Optional[float] = None
               ) -> List[Tuple[Dict[str, str], float, float]]:
        """Per matching series: (labels, ts, value) of the newest
        sample, optionally dropping series staler than
        ``staleness_s``."""
        out = []
        for key, samples in self._snapshot_series(name):
            labels = dict(key)
            if not _matches(labels, label_filter) or not samples:
                continue
            ts, value = samples[-1]
            if (staleness_s is not None and now is not None
                    and now - ts > staleness_s):
                continue
            out.append((labels, ts, value))
        return out

    def aggregate_latest(self, name: str, agg: str = "sum",
                         label_filter: Optional[Dict[str, str]] = None,
                         staleness_s: Optional[float] = None,
                         now: Optional[float] = None
                         ) -> Optional[float]:
        """Cross-series aggregation of the latest values: the
        fleet-wide view of a per-replica gauge (sum of queue depths,
        max of breaker states, mean saturation)."""
        values = [v for _, _, v in self.latest(
            name, label_filter, staleness_s=staleness_s, now=now)]
        if not values:
            return None
        if agg == "sum":
            return float(sum(values))
        if agg == "avg":
            return float(sum(values) / len(values))
        if agg == "max":
            return float(max(values))
        if agg == "min":
            return float(min(values))
        raise ValueError(f"unknown aggregation {agg!r}")

    def rate(self, name: str, window_s: float, now: float,
             label_filter: Optional[Dict[str, str]] = None
             ) -> Dict[_LabelsKey, float]:
        """Per-series per-second increase over the trailing window,
        counter-reset-aware: deltas between consecutive samples ride
        :func:`metrics.counter_increase`, so a replica restart (the
        cumulative counter drops) clamps instead of going negative.
        Series with fewer than two in-window samples are omitted."""
        cutoff = now - window_s
        out: Dict[_LabelsKey, float] = {}
        for key, samples in self._snapshot_series(name):
            if not _matches(dict(key), label_filter):
                continue
            in_window = [(ts, v) for ts, v in samples if ts >= cutoff]
            if len(in_window) < 2:
                continue
            increase = 0.0
            for (_, prev), (_, cur) in zip(in_window, in_window[1:]):
                increase += obs_metrics.counter_increase(prev, cur)
            elapsed = in_window[-1][0] - in_window[0][0]
            if elapsed <= 0:
                continue
            out[key] = increase / elapsed
        return out

    def sum_rate(self, name: str, window_s: float, now: float,
                 label_filter: Optional[Dict[str, str]] = None
                 ) -> Optional[float]:
        """Fleet-wide rate: the per-series rates summed (the
        cross-replica aggregation SLOs evaluate against). None when NO
        series had enough samples — "no data" and "zero rate" are
        different answers to a burn-rate question."""
        rates = self.rate(name, window_s, now, label_filter)
        if not rates:
            return None
        return float(sum(rates.values()))

    def bucket_rates(self, name: str, window_s: float, now: float,
                     label_filter: Optional[Dict[str, str]] = None
                     ) -> Dict[float, float]:
        """Per-``le`` bucket rates of histogram ``name`` summed across
        every matching series (instances, models): the input shape
        :func:`quantile_from_buckets` wants. ``le`` label excluded
        from matching."""
        rates = self.rate(f"{name}_bucket", window_s, now)
        out: Dict[float, float] = {}
        for key, value in rates.items():
            labels = dict(key)
            le = labels.pop("le", None)
            if le is None or not _matches(labels, label_filter):
                continue
            bound = float("inf") if le == "+Inf" else float(le)
            out[bound] = out.get(bound, 0.0) + value
        return out

    def histogram_quantile(self, name: str, q: float, window_s: float,
                           now: float,
                           label_filter: Optional[Dict[str, str]] = None
                           ) -> Optional[float]:
        return quantile_from_buckets(
            q, self.bucket_rates(name, window_s, now, label_filter))

    def exemplars(self, name: Optional[str] = None,
                  label_filter: Optional[Dict[str, str]] = None
                  ) -> List[Dict[str, Any]]:
        """Latest bucket exemplars, newest first: the trace ids the
        fleet-health page links at ``/tracez?trace_id=``."""
        with self._lock:
            items = list(self._exemplars.items())
        out = []
        for (sample_name, key), (trace_id, value, ts) in items:
            labels = dict(key)
            if name is not None and sample_name != f"{name}_bucket":
                continue
            if not _matches(labels, label_filter):
                continue
            out.append({"metric": sample_name, "labels": labels,
                        "trace_id": trace_id, "value": value,
                        "ts": ts})
        out.sort(key=lambda e: -e["ts"])
        return out

    def state(self) -> Dict[str, Any]:
        """Store stats for the dashboard/CI artifact."""
        with self._lock:
            per_name = {name: len(by_labels)
                        for name, by_labels in self._series.items()}
            return {"series": self._count,
                    "dropped_series": self._dropped,
                    "max_series": self.max_series,
                    "families": len(per_name),
                    "exemplars": len(self._exemplars),
                    "series_by_name": dict(sorted(
                        per_name.items(), key=lambda kv: -kv[1])[:20])}


class SpanStore:
    """Bounded fleet span store indexed by trace id (ISSUE 15).

    The trace-assembly half of the collector: spans arrive from the
    per-cycle ``/tracez`` scrape of every target AND from processes
    pushing on span-buffer pressure (``POST /spans`` on the collector
    exposition surface); both paths land here. Caps mirror the metric
    store's cardinality discipline — ``max_traces`` LRU-evicts whole
    traces (newest-touched survive), ``max_spans_per_trace`` bounds
    one hot request, and everything past a cap is COUNTED and
    dropped, never stored. Scrape overlap (the same ring dumped twice)
    dedupes on the ``(pid, tid, ts, name)`` identity a span keeps for
    its lifetime."""

    def __init__(self, *, max_traces: int = 256,
                 max_spans_per_trace: int = 512):
        self.max_traces = int(max_traces)
        self.max_spans_per_trace = int(max_spans_per_trace)
        self._lock = threading.Lock()
        # trace_id → {"spans": [event...], "keys": {identity...},
        #             "request_id": str}
        self._traces: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self.dropped_spans = 0
        self.evicted_traces = 0
        self.ingested = 0

    @staticmethod
    def _identity(span: Dict[str, Any]) -> Tuple:
        return (span.get("pid"), span.get("tid"), span.get("ts"),
                span.get("name"))

    def ingest(self, spans: Sequence[Dict[str, Any]],
               instance: Optional[str] = None,
               path: str = "scrape") -> Tuple[int, int]:
        """Ingest one batch of Chrome trace events; spans without a
        ``args.trace_id`` (process metadata, unlinked internals) and
        non-dict items (a malformed push batch) are skipped silently
        — they can never join a waterfall. Returns (ingested,
        dropped); both land in the ``kft_collector_spans_total``/
        ``kft_collector_dropped_spans_total`` families, labeled by
        ingest ``path`` (scrape | push). ``instance`` stamps where
        the span came from (the waterfall's per-process column)."""
        ingested = dropped = 0
        with self._lock:
            for span in spans:
                if not isinstance(span, dict):
                    continue
                args = span.get("args") or {}
                trace_id = args.get("trace_id")
                if not trace_id or span.get("ph", "X") != "X":
                    continue
                trace_id = str(trace_id)
                entry = self._traces.get(trace_id)
                if entry is None:
                    while len(self._traces) >= self.max_traces:
                        self._traces.popitem(last=False)
                        self.evicted_traces += 1
                    entry = {"spans": [], "keys": set(),
                             "request_id": args.get("request_id", "")}
                    self._traces[trace_id] = entry
                else:
                    self._traces.move_to_end(trace_id)
                key = self._identity(span)
                if key in entry["keys"]:
                    continue  # re-scraped ring overlap, not a drop
                if len(entry["spans"]) >= self.max_spans_per_trace:
                    # Count each over-cap span ONCE: remember its
                    # identity (bounded at 4× the cap so a hot trace
                    # can't grow the key set forever; past that
                    # bound, rescrape overlap may re-count — the
                    # counter stays an upper bound) — otherwise every
                    # 5 s rescrape of the same ring would re-count
                    # the same overflow and inflate the cap-
                    # discipline signal into noise.
                    if len(entry["keys"]) \
                            < 4 * self.max_spans_per_trace:
                        entry["keys"].add(key)
                        dropped += 1
                    continue
                if instance and "instance" not in args:
                    span = dict(span)
                    span["args"] = {**args, "instance": instance}
                entry["keys"].add(key)
                entry["spans"].append(span)
                ingested += 1
            self.ingested += ingested
            self.dropped_spans += dropped
        if ingested:
            _C_SPANS.labels(path).inc(ingested)
        if dropped:
            _C_SPANS_DROPPED.inc(dropped)
        return ingested, dropped

    def trace(self, trace_id: str) -> List[Dict[str, Any]]:
        """One trace's spans (also matched by request id — the
        access-log join key a human actually holds)."""
        with self._lock:
            entry = self._traces.get(trace_id)
            if entry is None:
                for candidate in reversed(self._traces.values()):
                    if candidate.get("request_id") == trace_id:
                        entry = candidate
                        break
            if entry is None:
                return []
            return list(entry["spans"])

    def trace_ids(self, limit: int = 64) -> List[Dict[str, Any]]:
        """Newest-touched traces first: id, request id, span count."""
        with self._lock:
            rows = [{"trace_id": tid,
                     "request_id": entry.get("request_id", ""),
                     "spans": len(entry["spans"])}
                    for tid, entry in self._traces.items()]
        rows.reverse()
        return rows[:max(0, limit)]

    def trace_count(self) -> int:
        with self._lock:
            return len(self._traces)

    def state(self) -> Dict[str, Any]:
        with self._lock:
            spans = sum(len(e["spans"]) for e in self._traces.values())
            return {"traces": len(self._traces), "spans": spans,
                    "max_traces": self.max_traces,
                    "max_spans_per_trace": self.max_spans_per_trace,
                    "ingested": self.ingested,
                    "dropped_spans": self.dropped_spans,
                    "evicted_traces": self.evicted_traces}


@dataclass(frozen=True)
class ScrapeTarget:
    """One /metrics endpoint: ``address`` becomes the ``instance``
    label, ``job`` names the plane (serving | router | operator |
    dashboard | ...)."""

    address: str
    job: str = "serving"

    @property
    def url(self) -> str:
        base = (self.address if "://" in self.address
                else f"http://{self.address}")
        return f"{base}/metrics"

    @property
    def tracez_url(self) -> str:
        """The same process's span surface — every scrape plane
        (server, proxy, dashboard, operator exposition thread) serves
        /tracez next to /metrics."""
        base = (self.address if "://" in self.address
                else f"http://{self.address}")
        return f"{base}/tracez"


def parse_static_targets(spec: str, default_job: str = "static"
                         ) -> List[ScrapeTarget]:
    """The shared ``addr[=job][,addr[=job]...]`` grammar of every
    --static / --collect_static flag (sidecar CLI and dashboard alike
    — one parser, one syntax)."""
    targets = []
    for item in filter(None, (spec or "").split(",")):
        address, _, job = item.partition("=")
        targets.append(ScrapeTarget(address.strip(),
                                    job.strip() or default_job))
    return targets


def scrape_metrics(target: ScrapeTarget, timeout_s: float = 2.0) -> str:
    """One bounded /metrics fetch. Sends the OpenMetrics ``Accept``
    (falling back to 0.0.4 — the server negotiates) so exemplars ride
    along when the endpoint supports them; the per-scrape timeout is
    the no-unbounded-fetch contract (one dead replica must cost the
    cycle one timeout, not wedge it)."""
    request = urllib.request.Request(target.url, headers={
        "Accept": ("application/openmetrics-text; version=1.0.0, "
                   "text/plain;version=0.0.4;q=0.5"),
    })
    with urllib.request.urlopen(request, timeout=timeout_s) as resp:
        return resp.read().decode("utf-8", "replace")


def scrape_spans(target: ScrapeTarget, timeout_s: float = 2.0,
                 limit: int = 512) -> List[Dict[str, Any]]:
    """One bounded /tracez fetch: the newest ``limit`` spans as Chrome
    trace events (the shared ?limit= filter keeps a full 4096-span
    ring from shipping megabytes per cycle). Same no-unbounded-fetch
    contract as the metrics scrape."""
    url = f"{target.tracez_url}?limit={int(limit)}"
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        doc = json.loads(resp.read().decode("utf-8", "replace"))
    return [e for e in doc.get("traceEvents", [])
            if e.get("ph", "X") == "X"]


@dataclass
class _TargetStatus:
    ok: bool = False
    error: str = ""
    at: float = 0.0            # monotonic, scrape completion
    duration_ms: float = 0.0
    samples: int = 0
    dropped: int = 0
    job: str = ""

    def snapshot(self, now: float) -> Dict[str, Any]:
        return {"ok": self.ok, "error": self.error, "job": self.job,
                "age_s": round(max(0.0, now - self.at), 1),
                "duration_ms": round(self.duration_ms, 2),
                "samples": self.samples, "dropped": self.dropped}


class Collector:
    """The fleet scrape loop: discover targets, fetch every
    ``/metrics`` concurrently with a per-scrape deadline, parse
    strictly, ingest into the store, then run the ``on_cycle`` hooks
    (the SLO evaluator registers here so alerting runs on fresh data,
    same thread, no second timer)."""

    def __init__(self, store: Optional[TimeSeriesStore] = None, *,
                 source: Optional[Any] = None,
                 pool: Optional[Any] = None,
                 static_targets: Sequence[Any] = (),
                 interval_s: float = 5.0,
                 timeout_s: float = 2.0,
                 fetch: Optional[Callable[[ScrapeTarget], str]] = None,
                 max_workers: int = 8,
                 span_store: Optional[SpanStore] = None,
                 span_fetch: Optional[
                     Callable[[ScrapeTarget], List[Dict[str, Any]]]
                 ] = None,
                 span_limit: int = 512):
        self.store = store or TimeSeriesStore()
        #: Trace-assembly store (ISSUE 15): when set, every cycle also
        #: scrapes each target's /tracez and ingests the spans — the
        #: pull half of span shipping (SpanShipper + POST /spans is
        #: the push half). None keeps the r13 metrics-only collector.
        self.span_store = span_store
        self._span_fetch = span_fetch
        self.span_limit = int(span_limit)
        self.source = source          # specs() → [(address, grpc)]
        self.pool = pool              # EndpointPool → endpoints()
        self.static_targets = [self._coerce_target(t)
                               for t in static_targets]
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self._fetch = fetch
        self.on_cycle: List[Callable[[float], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._executor: Optional[
            concurrent.futures.ThreadPoolExecutor] = None
        self._max_workers = int(max_workers)
        self._status: Dict[str, _TargetStatus] = {}
        self._status_lock = threading.Lock()
        self.cycles = 0
        _G_SERIES.set_function(self.store.series_count)
        _C_DROPPED.set_function(self.store.dropped_series)
        _LIVE.add(self)

    @staticmethod
    def _coerce_target(t: Any) -> ScrapeTarget:
        if isinstance(t, ScrapeTarget):
            return t
        if isinstance(t, str):
            return ScrapeTarget(t)
        address, job = t
        return ScrapeTarget(address, job)

    def targets(self) -> List[ScrapeTarget]:
        """Static targets + the serving fleet as discovered RIGHT NOW
        (endpoints file hot-reloads; the pool follows scale events) —
        membership churn needs no collector restart."""
        out: Dict[str, ScrapeTarget] = {}
        for t in self.static_targets:
            out.setdefault(t.address, t)
        if self.source is not None:
            for spec in self.source.specs():
                # 2- or 3-tuple (role-carrying schema v2) — the
                # collector scrapes every role alike.
                out.setdefault(spec[0], ScrapeTarget(spec[0], "serving"))
        if self.pool is not None:
            for ep in self.pool.endpoints():
                out.setdefault(ep.address,
                               ScrapeTarget(ep.address, "serving"))
        return list(out.values())

    def _scrape_one(self, target: ScrapeTarget
                    ) -> Tuple[ScrapeTarget, Optional[str], str,
                               float, float]:
        t0 = time.monotonic()
        fetch = self._fetch or (
            lambda t: scrape_metrics(t, self.timeout_s))
        try:
            text: Optional[str] = fetch(target)
            error = ""
        except Exception as e:  # noqa: BLE001 — unreachable target
            text, error = None, f"{type(e).__name__}: {e}"
        # The span scrape rides the same fan-out slot (one target, one
        # worker, one cycle): a dead target already burned its
        # timeout above, so don't pay a second one.
        spans: List[Dict[str, Any]] = []
        if self.span_store is not None and text is not None:
            span_fetch = self._span_fetch or (
                lambda t: scrape_spans(t, self.timeout_s,
                                       self.span_limit))
            try:
                spans = span_fetch(target)
            except Exception:  # noqa: BLE001 — spanless target (old
                # build, operator without /tracez): metrics still land.
                spans = []
        done_at = time.monotonic()
        # Per-target completion time rides back with the result: the
        # fan-out's map() drains only when the SLOWEST fetch (a dead
        # replica's full timeout) returns, and a fast target's
        # samples must carry the moment ITS scrape finished, not the
        # cycle-drain time — short-window rate denominators feel a
        # 2 s skew.
        return target, text, error, done_at - t0, done_at, spans

    def scrape_once(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One full cycle (tests call this directly; run() paces it).
        All targets scrape concurrently so N dead replicas cost ONE
        timeout, not N."""
        targets = self.targets()
        results = []
        if targets:
            if self._executor is None:
                self._executor = concurrent.futures.ThreadPoolExecutor(
                    max_workers=self._max_workers,
                    thread_name_prefix="kft-scrape")
            results = list(self._executor.map(self._scrape_one,
                                              targets))
        ok = failed = 0
        for target, text, error, duration_s, done_at, spans in results:
            at = done_at if now is None else now
            status = _TargetStatus(at=at, job=target.job,
                                   duration_ms=duration_s * 1e3)
            if text is not None:
                try:
                    families = obs_metrics.parse_exposition(text)
                    ingested, dropped = self.store.ingest_exposition(
                        families, at,
                        {"instance": target.address,
                         "job": target.job})
                    status.ok = True
                    status.samples = ingested
                    status.dropped = dropped
                except ValueError as e:
                    error = f"parse: {e}"
            if spans and self.span_store is not None:
                self.span_store.ingest(spans,
                                       instance=target.address)
            if status.ok:
                ok += 1
            else:
                failed += 1
                status.error = error[:200]
            _C_SCRAPES.labels(target.address,
                              "ok" if status.ok else "error").inc()
            _H_SCRAPE.observe(duration_s)
            with self._status_lock:
                self._status[target.address] = status
        with self._status_lock:
            live = {t.address for t in targets}
            for address in list(self._status):
                if address not in live:
                    del self._status[address]
                    # Pod-IP churn must not grow the collector's own
                    # /metrics without bound (the r10 per-address
                    # metric-children rule).
                    _C_SCRAPES.remove_labels(address, "ok")
                    _C_SCRAPES.remove_labels(address, "error")
        self.cycles += 1
        cycle_now = time.monotonic() if now is None else now
        for hook in list(self.on_cycle):
            try:
                hook(cycle_now)
            except Exception:  # noqa: BLE001 — keep the loop alive
                logger.exception("collector on_cycle hook failed")
        return {"targets": len(targets), "ok": ok, "failed": failed}

    def target_status(self, now: Optional[float] = None
                      ) -> Dict[str, Dict[str, Any]]:
        now = time.monotonic() if now is None else now
        with self._status_lock:
            return {address: status.snapshot(now)
                    for address, status in sorted(self._status.items())}

    def state(self) -> Dict[str, Any]:
        """Collector + store snapshot (dashboard /tpujobs/api/slo and
        the CI artifact trail)."""
        state = {"cycles": self.cycles,
                 "interval_s": self.interval_s,
                 "targets": self.target_status(),
                 "store": self.store.state()}
        if self.span_store is not None:
            state["spans"] = self.span_store.state()
        return state

    def run(self, *, max_cycles: Optional[int] = None) -> None:
        cycles = 0
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 — keep the loop alive
                logger.exception("collector cycle failed")
            cycles += 1
            if max_cycles is not None and cycles >= max_cycles:
                return
            self._stop.wait(self.interval_s)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self.run,
                                        name="kft-collector",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._executor is not None:
            self._executor.shutdown(wait=False)
            self._executor = None


class SpanShipper:
    """Push half of span shipping: a paced thread draining one
    tracer's export queue into a collector's ``POST /spans``.

    The scrape (pull) path covers steady state; this covers the spans
    a busy ring would evict between scrapes — the tracer's
    ``on_export_pressure`` hook wakes the shipper early when the
    export queue crosses half capacity, so buffer pressure ships spans
    instead of losing them. Wait discipline: Event-paced bounded
    waits, explicit POST timeout, failures counted and dropped (a
    dead collector must cost this process one timeout per interval,
    never memory or a wedge).

    **Bounded by construction** (the collector's own ≤2%-of-a-core
    discipline, PERF r13/r19): serializing a span costs ~5 µs of CPU,
    so an UNCAPPED shipper's cost would scale with offered load —
    ``max_spans_per_s`` rate-caps what ships (newest kept, overflow
    counted in ``dropped_spans``), pinning the shipping budget to
    cap × ~5 µs/s of a core whatever the fleet does. The scrape path
    and tail sampling carry the rest."""

    def __init__(self, tracer: Any, url: str, *,
                 component: str = "",
                 interval_s: float = 2.0,
                 timeout_s: float = 2.0,
                 max_spans_per_s: float = 500.0,
                 post: Optional[Callable[[str, bytes], None]] = None):
        base = url.rstrip("/")
        if "://" not in base:
            base = f"http://{base}"
        self.url = f"{base}/spans"
        self.tracer = tracer
        self.component = component
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.max_spans_per_s = float(max_spans_per_s)
        self._post = post
        self.shipped = 0
        self.dropped_spans = 0
        self.failed_posts = 0
        self._last_ship_at: Optional[float] = None
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _default_post(self, url: str, body: bytes) -> None:
        request = urllib.request.Request(
            url, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(
                request, timeout=self.timeout_s) as resp:
            resp.read()

    def ship_once(self) -> int:
        """Drain + POST one batch (tests call this directly). The
        rate cap keeps the NEWEST spans of an over-budget drain —
        the freshest traces are the ones an exemplar points at."""
        spans = self.tracer.drain_export()
        if not spans:
            return 0
        now = time.monotonic()
        elapsed = (self.interval_s if self._last_ship_at is None
                   else max(0.05, now - self._last_ship_at))
        self._last_ship_at = now
        budget = max(1, int(self.max_spans_per_s * elapsed))
        if len(spans) > budget:
            self.dropped_spans += len(spans) - budget
            spans = spans[-budget:]
        body = json.dumps({"component": self.component,
                           "spans": spans},
                          separators=(",", ":")).encode()
        try:
            (self._post or self._default_post)(self.url, body)
        except Exception as e:  # noqa: BLE001 — dead collector: the
            # batch is dropped (bounded queue already protects memory).
            self.failed_posts += 1
            logger.debug("span ship to %s failed: %s", self.url, e)
            return 0
        self.shipped += len(spans)
        return len(spans)

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=self.interval_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.ship_once()
            except Exception:  # noqa: BLE001 — keep shipping
                logger.exception("span shipper cycle failed")

    def start(self) -> None:
        if self._thread is not None:
            return
        self.tracer.enable_export()
        self.tracer.on_export_pressure = self._wake.set
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="kft-span-shipper",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self.tracer.on_export_pressure == self._wake.set:
            self.tracer.on_export_pressure = None
        self.tracer.disable_export()


def fleet_replica_rows(collector: Collector,
                       specs: Sequence[Tuple[str, Optional[str]]],
                       now: Optional[float] = None,
                       window_s: Optional[float] = None
                       ) -> List[Dict[str, Any]]:
    """Per-replica autoscaler rows from the collector's store instead
    of a second scrape sweep: one fleet, one scraper. Shapes match
    ``AutoscalerLoop._replica_sample`` — queue wait from the serving
    gauges (depth × est latency, per model, summed), shed/expired as
    store rates (counter-reset-aware), reachability from the last
    scrape status."""
    now = time.monotonic() if now is None else now
    window_s = window_s or max(4 * collector.interval_s, 10.0)
    status = collector.target_status(now)
    store = collector.store
    rows: List[Dict[str, Any]] = []
    for address, *_rest in specs:  # 2- or 3-tuple (role schema v2)
        st = status.get(address)
        if st is None or not st.get("ok"):
            rows.append({"address": address, "reachable": False})
            continue
        flt = {"instance": address}
        depth_by_model = {
            labels.get("model", ""): value
            for labels, _, value in store.latest(
                "kft_serving_queue_depth", flt,
                staleness_s=window_s, now=now)}
        latency_by_model = {
            labels.get("model", ""): value
            for labels, _, value in store.latest(
                "kft_serving_est_batch_latency_seconds", flt,
                staleness_s=window_s, now=now)}
        queue_wait_ms = sum(
            depth * latency_by_model.get(model, 0.0) * 1e3
            for model, depth in depth_by_model.items())
        shed_rate = store.sum_rate("kft_serving_shed_total",
                                   window_s, now, flt) or 0.0
        expired_rate = store.sum_rate("kft_serving_expired_total",
                                      window_s, now, flt) or 0.0
        rows.append({
            "address": address,
            "reachable": True,
            "status": "ok",
            "queue_wait_ms": round(queue_wait_ms, 3),
            "shed_rate": round(shed_rate, 4),
            "expired_rate": round(expired_rate, 4),
            "resident_models": sorted(m for m in depth_by_model if m),
            # Span-surface pass-through (ISSUE 15): where this
            # replica's half of a waterfall lives — the dashboard and
            # kft-trace link straight here.
            "tracez": ScrapeTarget(address).tracez_url,
        })
    return rows


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kft-collector")
    parser.add_argument("--static", default="",
                        help="static scrape targets: "
                             "addr[=job][,addr[=job]...]")
    parser.add_argument("--endpoints_file", default=None,
                        help="serving-fleet membership JSON (the "
                             "autoscaler-maintained file; hot-reloads)")
    parser.add_argument("--interval", type=float, default=5.0)
    parser.add_argument("--timeout", type=float, default=2.0,
                        help="per-scrape deadline (seconds)")
    parser.add_argument("--max_series", type=int, default=8192,
                        help="series-cardinality cap")
    parser.add_argument("--metrics_port", type=int, default=0,
                        help="expose the collector's OWN /metrics "
                             "(+ /tracez, and with --spans the "
                             "/traces + /trace assembly endpoints "
                             "and the POST /spans push path); "
                             "0 disables")
    parser.add_argument("--spans", action="store_true",
                        help="collect spans too: scrape each "
                             "target's /tracez per cycle into the "
                             "bounded trace store (kft-trace and the "
                             "dashboard Waterfall page read it)")
    parser.add_argument("--max_traces", type=int, default=256,
                        help="trace-store cap (whole traces, LRU)")
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--alerts", action="store_true",
                        help="evaluate the default serving SLOs and "
                             "publish alerts (Event + kft-alerts "
                             "ConfigMap); needs apiserver access")
    parser.add_argument("--apiserver", default=None,
                        help="apiserver base URL (dev); default: "
                             "in-cluster ServiceAccount")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    source = None
    if args.endpoints_file:
        from kubeflow_tpu.scaling.endpoints import FileEndpointSource

        source = FileEndpointSource(args.endpoints_file)
    static = parse_static_targets(args.static)
    store = TimeSeriesStore(max_series=args.max_series)
    span_store = (SpanStore(max_traces=args.max_traces)
                  if args.spans else None)
    collector = Collector(store, source=source, static_targets=static,
                          interval_s=args.interval,
                          timeout_s=args.timeout,
                          span_store=span_store)
    if args.alerts:
        from kubeflow_tpu.obs.slo import AlertManager, default_slos
        from kubeflow_tpu.operator.http_client import HttpApiClient

        api = (HttpApiClient(args.apiserver) if args.apiserver
               else HttpApiClient.in_cluster())
        alerts = AlertManager(store, default_slos(),
                              api=api, namespace=args.namespace)
        collector.on_cycle.append(alerts.evaluate)
    if args.metrics_port:
        from kubeflow_tpu.obs.exposition import start_exposition_server

        start_exposition_server(args.metrics_port,
                                span_store=span_store)
        logger.info("collector metrics on :%d%s", args.metrics_port,
                    " (+ trace assembly)" if span_store else "")
    logger.info("collector: %d static target(s)%s, interval %.1fs",
                len(static),
                f" + endpoints file {args.endpoints_file}"
                if args.endpoints_file else "",
                args.interval)
    try:
        collector.run()
    except KeyboardInterrupt:
        collector.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
